"""Pixel-LM throughput microbench: training steps/s AND KV-cache decode tokens/s.

Companion to ``bench_transformer.py`` for the decoder family (``models/lm.py``): the
training half measures teacher-forced next-token steps/s (the same scanned-program
protocol); the decode half measures the generation surface — ``lm.generate``'s
jit-compiled KV-cache sampling loop — in tokens/s, the number a serving user asks
first. GQA (``--kv-heads``) shrinks the decode cache ``heads/kv_heads``×; RoPE and
sliding windows (``--rope``/``--window``) bench the same knobs the trainer exposes.

Protocol: identical honest-sync discipline to the other benches (device→host fetch of
a value data-dependent on the full computation; ``block_until_ready`` alone can
resolve at enqueue-ack on tunnelled PJRT backends); one untimed warmup per program,
median of 3 timed runs. Prints exactly ONE JSON line on stdout. CPU-drivable at tiny
shapes (tests); run via ``tools/hw_followups.sh`` step 2b2 on hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--vocab", type=int, default=16, help="gray levels (BOS is +1)")
    p.add_argument("--seq", type=int, default=784)
    p.add_argument("--batch", type=int, default=64, help="training batch")
    p.add_argument("--gen-batch", type=int, default=8, help="decode batch")
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=0, help="GQA K/V heads (0 = MHA)")
    p.add_argument("--rope", action=argparse.BooleanOptionalAction, default=False)
    p.add_argument("--window", type=int, default=0,
                   help="sliding-window attention width (0 = full)")
    p.add_argument("--steps", type=int, default=20, help="training steps per run")
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction, default=True)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        chained_diff_time,
        enable_compile_cache,
        peak_flops,
        peak_hbm_bytes,
        timed_state_run,
    )

    enable_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_results", ".jax_cache"))

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm as lm_mod,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state, make_train_step,
    )

    model = lm_mod.TransformerLM(
        vocab_size=args.vocab + 1, seq_len=args.seq, embed_dim=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        num_kv_heads=args.kv_heads or None, rope=args.rope,
        attention_window=args.window or 0, dropout_rate=0.0,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32)

    rng = np.random.default_rng(0)
    targets = jnp.asarray(rng.integers(
        0, args.vocab, size=(args.batch, args.seq)).astype(np.int32))

    state = create_train_state(model, jax.random.PRNGKey(1),
                               sample_input_shape=(1, args.seq))

    def lm_loss(params, xs, ys, rng_):
        del ys
        return lm_mod.next_token_loss(model, params, xs, None, deterministic=True)

    step = make_train_step(model, learning_rate=1e-3, momentum=0.0,
                           optimizer=None, loss_fn=lm_loss)
    key = jax.random.PRNGKey(2)

    @jax.jit
    def run_train(state):
        def body(st, _):
            st, loss = step(st, targets, targets[:, 0], key)
            return st, loss

        return lax.scan(body, state, None, length=args.steps)

    def timed_train(state):
        return timed_state_run(run_train, state)   # honest sync (module docstring)

    state, _, _ = timed_train(state)               # warmup
    train_times, last_loss = [], None
    for _ in range(3):
        state, dt, last_loss = timed_train(state)
        train_times.append(dt)
    train_median = float(np.median(train_times))
    steps_per_s = args.steps / train_median

    # Decode weights in the activation dtype: serving reads bf16 weights, and the
    # weight read is the term batch amortizes (master f32 stays in the train state).
    gen_params = jax.tree_util.tree_map(
        lambda x: x.astype(model.dtype) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, state.params)

    # Tunnelled PJRT dispatch+sync costs ~70 ms — comparable to a whole 784-step
    # decode — so one-dispatch-per-rep measures the tunnel (the r3 capture's 60.4k
    # tokens/s was mostly that). Chain R generates in one compiled scan (each
    # fold_in's the previous tokens, so none can be elided) and report the
    # two-point difference, exactly like bench_attention.py.
    def gen_chain(n):
        def body(k, _):
            ids = lm_mod.generate(model, gen_params, k, batch=args.gen_batch,
                                  temperature=1.0)
            return jax.random.fold_in(k, jnp.sum(ids)), ()

        def run(k):
            return lax.scan(body, k, None, length=n)[0]

        return jax.jit(run)

    def synced_gen_chain(n):
        compiled = gen_chain(n)
        return lambda: jax.device_get(compiled(jax.random.PRNGKey(3)))

    gen_median, (n1, t1), (n2, t2), gen_converged = chained_diff_time(
        synced_gen_chain, n1=1, grow=4, max_n=256)
    gen_times = [t1, t2]
    decode_tokens_per_s = args.gen_batch * args.seq / gen_median

    # Model-FLOPs accounting mirrors bench_transformer.py, adjusted for this bench's
    # knobs: GQA narrows the KV projection (4e²·kvh/H instead of 4e²) and a sliding
    # window caps the attended keys at W. The attention term charges the full causal
    # scan (upper bound — required work averages s/2; the dense masked implementation
    # executes the full s×s einsums either way), plus the vocab head (2·e·V);
    # embedding gathers are negligible. Training ≈ 3× forward.
    e = args.d_model
    kvh = args.kv_heads or args.heads
    proj_flops = (20 + 4 * kvh / args.heads) * e * e   # q/out/mlp 20e² + kv 4e²·kvh/H
    s_att = min(args.window, args.seq) if args.window else args.seq
    fwd_per_token = (args.layers * (proj_flops + 4 * s_att * e)
                     + 2 * e * (args.vocab + 1))
    train_flops_per_step = int(3 * fwd_per_token * args.seq * args.batch)
    achieved = steps_per_s * train_flops_per_step
    dev = jax.devices()[0]
    peak = peak_flops(getattr(dev, "device_kind", "")) if dev.platform == "tpu" else None

    # Decode HBM roofline: each step re-reads every layer's cached K+V prefix (the
    # segmented scan bounds it at ceil((t+1)/SEG)·SEG rows) and the decode weights
    # (amortized over the batch). Activations/cache-writes are negligible.
    hd = e // args.heads
    cache_itemsize = jnp.dtype(model.dtype).itemsize
    # generate()'s segmented scan reads a static prefix of ceil((t+1)/SEG)·SEG cache
    # rows at step t — average that exactly rather than charging the full length.
    seg = lm_mod.DECODE_SEGMENT
    avg_prefix = sum(min((t // seg + 1) * seg, args.seq)
                     for t in range(args.seq)) / args.seq
    cache_row_bytes = 2 * args.layers * avg_prefix * kvh * hd * cache_itemsize
    param_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(gen_params))
    decode_bytes_per_token = cache_row_bytes + param_bytes / args.gen_batch
    achieved_hbm = decode_tokens_per_s * decode_bytes_per_token
    hbm_peak = (peak_hbm_bytes(getattr(dev, "device_kind", ""))
                if dev.platform == "tpu" else None)
    print(json.dumps({
        "metric": (f"pixel-LM train steps/s + decode tokens/s (L={args.layers}, "
                   f"d_model={args.d_model}, seq={args.seq}, batch={args.batch}, "
                   f"heads={args.heads}"
                   f"{f', kv_heads={args.kv_heads}' if args.kv_heads else ''}"
                   f"{', rope' if args.rope else ''}"
                   f"{f', window={args.window}' if args.window else ''}, "
                   f"{'bf16' if args.bf16 else 'f32'})"),
        "value": round(steps_per_s, 2),
        "unit": "steps/s",
        "vs_baseline": None,       # beyond-parity surface: the reference has no LM
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "train_seconds_per_run_all": [round(t, 4) for t in train_times],
        "train_tokens_per_s": round(steps_per_s * args.batch * args.seq),
        "decode_seconds_all": [round(t, 4) for t in gen_times],
        "decode_chain_lengths": [n1, n2],
        # False ⇒ max_n exhausted before the chain added min_delta seconds: the
        # two-point difference is still jitter-dominated (r4 advisor finding).
        "decode_chain_converged": gen_converged,
        "decode_tokens_per_s": round(decode_tokens_per_s, 1),
        "decode_batch": args.gen_batch,
        "decode_bytes_per_token": round(decode_bytes_per_token),
        "decode_achieved_hbm_bytes_per_s": round(achieved_hbm),
        "decode_hbm_roofline_frac": (round(achieved_hbm / hbm_peak, 4)
                                     if hbm_peak else None),
        "model_train_flops_per_step": train_flops_per_step,
        "achieved_model_flops_per_s": round(achieved),
        "mfu_vs_bf16_peak": round(achieved / peak, 6) if peak else None,
        "final_train_loss": round(last_loss, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
