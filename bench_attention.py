"""Long-context attention microbench: flash (Pallas) vs dense (XLA) on one chip.

Measures forward+backward wall time of a causal multi-head self-attention at growing
sequence lengths. The dense path materializes the ``[H, S, S]`` score matrix (O(S²) HBM);
the flash kernels (``ops/pallas_attention.py``) stream K/V blocks through VMEM (O(S·D)),
so it keeps scaling after the dense path exhausts memory — the single-chip half of the
framework's long-context story (the cross-chip half is ``parallel/ring_attention.py``).

Honest timing: this backend can sit behind a tunnelled PJRT transport whose fixed
dispatch+host-sync latency is ~70 ms — larger than a whole fwd+bwd at S ≤ 8k, so a
one-dispatch-per-rep protocol measures the tunnel, not the kernel (the r3 capture's
flat ~0.08 s rows at 1k-4k were exactly that). Each measurement therefore runs the
op N times CHAINED inside one compiled ``lax.scan`` (each iteration's inputs nudged
by the previous grads, so nothing can be hoisted or dead-code-eliminated), fetches a
scalar data-dependent on the final iteration, and reports the two-point difference
``(t(N2) − t(N1)) / (N2 − N1)`` — the constant dispatch+sync cost cancels exactly.

Usage: ``python bench_attention.py [--out results.jsonl]`` — one JSON line per
(impl, seq_len); dense rows appear up to the longest S that fits/compiles.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys

import numpy as np

B, H, D = 1, 8, 64
SEQ_LENS = (1024, 2048, 4096, 8192, 16384)
DENSE_MAX_SCORE_BYTES = 2 << 30  # dense keeps [B, H, S, S] f32 score residuals;
                                 # 2 GiB (S=8192 at the default B=1, H=8) is the
                                 # measured comfort wall — the gate scales with
                                 # the --batch/--heads geometry, not S alone
WARMUP, REPS = 1, 3
MIN_DELTA = 0.25        # seconds of chained work the N2 run must add over N1


def _measure(fn, q, k, v):
    import jax
    import jax.numpy as jnp

    grad_fn = jax.grad(
        lambda q, k, v: jnp.sum(fn(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))
    # 1e-20 is representable in bf16's 8-bit exponent; the nudge rounds away in the
    # add (values stay fixed) but the compiler cannot prove that, so every
    # iteration's fwd+bwd stays live and serialized on the previous one.
    eps = jnp.asarray(1e-20, q.dtype)

    def chain(n):
        def body(carry, _):
            q, k, v = carry
            gq, gk, gv = grad_fn(q, k, v)
            return (q + eps * gq, k + eps * gk, v + eps * gv), ()

        def run(q, k, v):
            (q, _, _), _ = jax.lax.scan(body, (q, k, v), None, length=n)
            return q

        return jax.jit(run)

    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        chained_diff_time,
    )

    def synced_chain(n):
        compiled = chain(n)
        return lambda: float(jnp.sum(compiled(q, k, v)[0, 0, 0]))  # grad-dep sync

    per_iter, _, _, converged = chained_diff_time(synced_chain, min_delta=MIN_DELTA,
                                                  reps=REPS, warmup=WARMUP)
    return per_iter, converged


def _attended_pairs(s: int, window: int | None) -> int:
    """Number of (query, key) pairs a CAUSAL attention over length ``s`` must score —
    query i attends ``min(i+1, W)`` keys under a sliding window of W (all i+1
    without one). The roofline below charges only these required pairs: the dense
    path executes the full S×S square anyway and the flash kernels skip
    above-diagonal/out-of-band blocks, but both are judged against the same
    model-required work (the MFU convention the trainer benches use)."""
    w = min(window or s, s)
    return w * (w + 1) // 2 + (s - w) * w


def _fwdbwd_model_flops(s: int, window: int | None, b: int = B, h: int = H,
                        d: int = D) -> int:
    """Required fwd+bwd FLOPs of causal MHA at b,h,d: 2 matmul FLOPs per attended
    pair per D for each of QKᵀ and PV forward (4·B·H·D·pairs), backward's four
    matmuls (dV, dP, dQ, dK) ≈ 2× forward; flash's in-backward forward recompute is
    real work but NOT credited — MFU counts model FLOPs, not implementation FLOPs.
    Softmax/mask flops are O(pairs) without the D factor and are omitted (<1%)."""
    return 3 * 4 * b * h * d * _attended_pairs(s, window)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None, help="also append JSONL here")
    parser.add_argument("--seq-lens", type=int, nargs="+", default=list(SEQ_LENS),
                        help="sequence lengths to measure (must divide by 128); "
                             "small values make the tool drivable on CPU interpret mode")
    parser.add_argument("--plot", default=None,
                        help="also save the flash-vs-dense curve PNG here")
    parser.add_argument("--block", type=int, default=None,
                        help="flash kernel block rows (multiple of 128; default 128) "
                             "— the r3 tuning knob for the S<=8k regime")
    parser.add_argument("--block-sweep", type=int, nargs="+", default=None,
                        help="measure flash at each of these block sizes per seq_len "
                             "(dense measured once); finds the per-S best block")
    parser.add_argument("--window", type=int, default=None,
                        help="sliding-window width: flash runs the BANDED grid "
                             "(O(S*W) compute), dense applies the same band mask — "
                             "the local-attention long-context comparison")
    parser.add_argument("--dtype", choices=("float32", "bfloat16"),
                        default="float32",
                        help="q/k/v dtype; bfloat16 is the training dtype and runs "
                             "the kernels' matmuls at the MXU's native rate")
    parser.add_argument("--native-layout", action="store_true",
                        help="feed the kernels the model's [B,S,H,D] layout "
                             "directly (no transpose repacks) — r5 measurement "
                             "knob; rows carry native_layout: true")
    parser.add_argument("--batch", type=int, default=B)
    parser.add_argument("--heads", type=int, default=H)
    parser.add_argument("--head-dim", type=int, default=D,
                        help="per-head width; the default 64 runs the MXU's "
                             "contractions at half depth — 128 is the "
                             "full-depth geometry the trainer configs use")
    args = parser.parse_args()
    b_sz, h_ct, d_hd = args.batch, args.heads, args.head_dim
    if args.block is not None and args.block_sweep is not None:
        parser.error("--block and --block-sweep are mutually exclusive")

    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu import ops

    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        peak_flops,
    )

    platform = jax.default_backend()
    device_kind = jax.devices()[0].device_kind
    # Roofline denominator (r4 verdict item 2): the chip's bf16 peak — conservative
    # for f32 runs, exact for --dtype bfloat16, None off-TPU.
    peak = peak_flops(device_kind) if platform == "tpu" else None
    all_rows = []
    for s in args.seq_lens:
        rng = np.random.default_rng(s)
        q, k, v = (jnp.asarray(
            rng.normal(size=(b_sz, s, h_ct, d_hd)).astype(np.float32),
            dtype=args.dtype) for _ in range(3))
        row = {"seq_len": s, "batch": b_sz, "heads": h_ct, "head_dim": d_hd,
               "platform": platform, "device_kind": device_kind, "causal": True,
               "dtype": args.dtype, "reps": REPS}
        if args.window is not None:
            row["window"] = args.window
        if args.native_layout:
            from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
                native_mode,
            )
            row["native_layout"] = True
            # Which native form the env knobs actually select at this head
            # width — a capture file's name can't misstate what it timed.
            row["native_mode"] = native_mode(d_hd)
        sweeping = args.block_sweep is not None
        blocks = (args.block_sweep if sweeping
                  else [args.block] if args.block is not None else [None])
        best_block = None
        row["flash_fwdbwd_s"] = None   # stays None if every block size fails
        for blk in blocks:
            # Sweep rows keep the per-block key schema even for one candidate, so
            # partial re-measurements append cleanly to an existing tune JSONL.
            key = f"flash_fwdbwd_s_b{blk}" if sweeping else "flash_fwdbwd_s"
            flash_kw = {}
            if blk is not None:
                flash_kw["block"] = blk
            if args.window is not None:
                flash_kw["window"] = args.window
            if args.native_layout:
                flash_kw["native_layout"] = True
            flash = (ops.flash_attention if not flash_kw else
                     functools.partial(ops.flash_attention, **flash_kw))
            try:
                # flash_attention validates blk itself (multiple of 128, divides S).
                t, conv = _measure(flash, q, k, v)
            except Exception as e:  # a memory/compile wall is a result, not a crash
                t, conv = None, None
                row[key.replace("fwdbwd_s", "error")] = (
                    f"{type(e).__name__}: {str(e)[:200]}")
            row[key] = t
            if sweeping and conv is not None:
                row[key.replace("fwdbwd_s", "converged")] = conv
            if t is not None and (best_block is None or t < row["flash_fwdbwd_s"]):
                best_block, row["flash_fwdbwd_s"] = (blk or 128), t
                row["flash_converged"] = conv
        if sweeping:
            row["flash_best_block"] = best_block
        # Roofline accounting (r4 verdict item 2): required causal fwd+bwd FLOPs over
        # measured seconds, judged against the chip's bf16 peak — the same discipline
        # the trainer benches carry, extended to where the kernels live.
        model_flops = _fwdbwd_model_flops(s, args.window, b_sz, h_ct, d_hd)
        row["fwdbwd_model_flops"] = model_flops

        def roofline(impl: str) -> None:
            achieved = model_flops / row[f"{impl}_fwdbwd_s"]
            row[f"{impl}_achieved_flops_per_s"] = round(achieved)
            row[f"{impl}_pct_of_bf16_peak"] = (round(100 * achieved / peak, 2)
                                               if peak else None)

        if row["flash_fwdbwd_s"]:
            roofline("flash")
        if b_sz * h_ct * s * s * 4 <= DENSE_MAX_SCORE_BYTES:
            try:
                dense = (ops.full_attention if args.window is None else
                         functools.partial(ops.full_attention,
                                           window=args.window))
                row["dense_fwdbwd_s"], row["dense_converged"] = _measure(dense, q,
                                                                         k, v)
                roofline("dense")
                if row["flash_fwdbwd_s"]:  # speedup needs a nonzero flash denominator
                    row["speedup_flash_vs_dense"] = round(
                        row["dense_fwdbwd_s"] / row["flash_fwdbwd_s"], 3)
            except Exception as e:  # OOM/compile failure: the dense wall, recorded
                row["dense_fwdbwd_s"] = None
                row["dense_error"] = f"{type(e).__name__}: {str(e)[:200]}"
        else:
            row["dense_fwdbwd_s"] = None
            row["dense_error"] = (
                f"skipped: B*H*S*S f32 scores exceed {DENSE_MAX_SCORE_BYTES} bytes")
        print(json.dumps(row), flush=True)
        all_rows.append(row)
        if args.out:  # append per row — a later-size failure must not lose earlier rows
            with open(args.out, "a") as f:
                f.write(json.dumps(row) + "\n")
        if args.plot:  # re-save per row for the same reason (overwrite-in-place)
            from csed_514_project_distributed_training_using_pytorch_tpu.utils.plotting import (
                save_attention_curve,
            )
            if save_attention_curve(all_rows, args.plot) is None:
                print(f"warning: --plot {args.plot} not written "
                      f"(matplotlib unavailable)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
