"""Checkpoint promotion with canary rollout and auto-rollback (DESIGN.md §26).

The trainer publishes health-stamped checkpoints into a versioned store
(``utils/checkpoint.py`` manifest entries carry ``health`` and ``cursor``);
the promoter watches that store and walks every new candidate through a fixed
pipeline:

1. **Gate** (cheap, offline, ordered cheapest-first):
   a. *health stamp* — a candidate its own trainer stamped ``clean: false``
      (in-program anomaly detection fired that epoch) is rejected without
      ever touching the fleet;
   b. *accuracy budget* — the candidate's held-out ``decode_nll`` may exceed
      the incumbent's by at most ``nll_budget`` (an absolute nats/token
      margin, the ``bench_guard`` tolerance idiom);
   c. *perf tolerance* — the median of ``perf_probes`` timed probes may
      exceed the incumbent's by at most ``perf_tolerance`` (relative).
2. **Canary** — survivors roll onto ONE replica (``Router.canary_reload``)
   while the rest of the fleet serves the incumbent; after the observation
   window the canary's windowed SLO attainment and sampled-token NLL are
   compared against the rest of the fleet (windows and margins, not raw
   latencies — see DESIGN.md §26 for why).
3. **Verdict** — pass promotes fleet-wide (``Router.promote_canary``, the
   never-below-N−1-ready roll); fail or inconclusive auto-rolls-back to the
   incumbent (``Router.rollback_canary``). Every transition lands in an
   append-only JSONL promotion ledger plus ``promote``/``canary`` telemetry
   events, so the whole trajectory is auditable from the stream alone.

The module is deliberately jax-free: the accuracy and perf probes are
injected callables (``nll_fn(path)``, ``perf_fn(path)``,
``sample_nll_fn(samples)``), so the gate/canary/ledger logic unit-tests on
echo fleets, and ``tools/train_serve_loop.py`` supplies the real
``models.lm.decode_nll``-backed scorers.
"""

import dataclasses
import json
import os
import time

from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    checkpoint,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    telemetry as T,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
    JsonlWriter,
)


@dataclasses.dataclass
class GateConfig:
    """The offline qualification gate, ordered cheapest-first.

    ``nll_budget`` is ABSOLUTE (nats/token the candidate may regress vs the
    incumbent); ``perf_tolerance`` is RELATIVE (fraction the candidate's
    median probe may exceed the incumbent's — the bench_guard idiom).
    ``require_stamp`` escalates the health check from "not stamped unclean"
    to "stamped clean" (guard-off trainers produce no stamp at all, and a
    legacy store must stay promotable)."""

    nll_budget: float = 0.05
    perf_tolerance: float = 0.5
    perf_probes: int = 3
    require_stamp: bool = False


@dataclasses.dataclass
class CanaryConfig:
    """The canary observation window and its pass margins.

    ``min_requests`` floors BOTH sides of the comparison — with fewer
    completions than that on either side the verdict is ``inconclusive``
    (which rolls back: an unjudgeable candidate must not ship).
    ``attainment_margin`` is how far below the fleet's windowed attainment
    the canary may sit; ``nll_margin`` how far above the fleet's
    sampled-token NLL (both under the ONE shared scorer)."""

    window_s: float = 5.0
    min_requests: int = 3
    attainment_margin: float = 0.10
    nll_margin: float = 0.10


class PromotionLedger:
    """Append-only JSONL promotion history: one line per lifecycle transition
    (``candidate_seen``/``superseded``/``gate_pass``/``gate_fail``/
    ``canary_start``/``canary_pass``/``canary_fail``/``promoted``/
    ``rolled_back``). Append, never truncate — a restarted promoter resumes
    onto the same file and the run's full trajectory survives. ``path`` empty
    disables writes (record still returns the row)."""

    def __init__(self, path: str):
        self.path = path or ""
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)

    def record(self, action: str, candidate: str, **fields) -> dict:
        row = {"t": round(time.time(), 3), "action": action,
               "candidate": candidate, **fields}
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(row) + "\n")
        return row


def read_ledger(path: str) -> list[dict]:
    """Load a promotion ledger, tolerating a torn final line (the promoter
    may be mid-append when a reader samples the file)."""
    rows: list[dict] = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


class Promoter:
    """The promotion controller. ``ckpt_dir`` is the watched versioned store;
    ``router`` a started ``serving.router.Router`` (None = gate-only mode:
    qualification verdicts without a fleet, for offline qualification and
    unit tests). ``incumbent`` seeds last-good (None = the first qualifying
    candidate promotes unopposed — there is no incumbent to regress
    against)."""

    def __init__(self, ckpt_dir: str, *, router=None,
                 nll_fn=None, perf_fn=None, sample_nll_fn=None,
                 gate: GateConfig | None = None,
                 canary: CanaryConfig | None = None,
                 ledger_path: str = "", telemetry: str = "",
                 incumbent: str | None = None,
                 dwell_fn=None):
        self.ckpt_dir = ckpt_dir
        self.router = router
        self.nll_fn = nll_fn
        self.perf_fn = perf_fn
        self.sample_nll_fn = sample_nll_fn
        self.gate = gate or GateConfig()
        self.canary = canary or CanaryConfig()
        self.ledger = PromotionLedger(ledger_path)
        self._writer = JsonlWriter(telemetry)
        # The dwell hook: how the canary window passes. Default wall-clock
        # sleep; the serve loop injects its own (drive traffic while
        # waiting), tests inject a no-op.
        self._dwell = dwell_fn or (lambda s: time.sleep(s))
        self.incumbent = incumbent
        self.incumbent_nll: float | None = None
        self.incumbent_perf_s: float | None = None
        self._seen: set[str] = set()
        if incumbent:
            self._seen.add(os.path.basename(incumbent))
        self.counts = {"promoted": 0, "gate_fail": 0, "rolled_back": 0,
                       "superseded": 0}

    def close(self) -> None:
        self._writer.close()

    # ------------------------------------------------------------- discovery

    def candidates(self) -> list[dict]:
        """Unseen manifest entries whose bytes exist, oldest-first. The
        manifest is the source of truth (it carries the health stamp and the
        data cursor); a ``ckpt_*.msgpack`` that never made the manifest is a
        torn publish and is invisible here by design."""
        out = []
        for entry in checkpoint.load_manifest(self.ckpt_dir)["entries"]:
            name = entry.get("file")
            if not name or name in self._seen:
                continue
            if not os.path.exists(os.path.join(self.ckpt_dir, name)):
                continue
            out.append(entry)
        out.sort(key=lambda e: e.get("step", 0))
        return out

    # ------------------------------------------------------------------ gate

    def qualify(self, entry: dict) -> tuple[bool, str, dict]:
        """Run one candidate through the gate. Returns ``(ok, reason,
        measured)`` where ``measured`` carries the probe numbers (recorded in
        the ledger either way, so a rejection's margin is auditable)."""
        path = os.path.join(self.ckpt_dir, entry["file"])
        measured: dict = {}
        health = entry.get("health")
        if health is not None and not health.get("clean", True):
            return False, "unclean_health_stamp", measured
        if self.gate.require_stamp and health is None:
            return False, "missing_health_stamp", measured
        if self.nll_fn is not None:
            self._ensure_baseline()
            nll = float(self.nll_fn(path))
            measured["nll"] = nll
            measured["incumbent_nll"] = self.incumbent_nll
            if (self.incumbent_nll is not None
                    and nll > self.incumbent_nll + self.gate.nll_budget):
                return False, "nll_over_budget", measured
        if self.perf_fn is not None:
            self._ensure_baseline()
            probes = sorted(float(self.perf_fn(path))
                            for _ in range(max(1, self.gate.perf_probes)))
            perf = probes[len(probes) // 2]
            measured["perf_s"] = perf
            measured["incumbent_perf_s"] = self.incumbent_perf_s
            if (self.incumbent_perf_s is not None and perf >
                    self.incumbent_perf_s * (1.0 + self.gate.perf_tolerance)):
                return False, "perf_over_tolerance", measured
        return True, "", measured

    def _ensure_baseline(self) -> None:
        """Lazily measure the incumbent's NLL/perf ONCE — the yardstick every
        gate comparison uses until a promotion replaces it."""
        if self.incumbent is None:
            return
        if self.nll_fn is not None and self.incumbent_nll is None:
            self.incumbent_nll = float(self.nll_fn(self.incumbent))
        if self.perf_fn is not None and self.incumbent_perf_s is None:
            probes = sorted(float(self.perf_fn(self.incumbent))
                            for _ in range(max(1, self.gate.perf_probes)))
            self.incumbent_perf_s = probes[len(probes) // 2]

    # ---------------------------------------------------------------- canary

    def judge_canary(self, report: dict,
                     canary_nll: float | None,
                     fleet_nll: float | None) -> tuple[str, str]:
        """The canary verdict from one ``Router.canary_report`` plus the two
        sampled-token NLL scores: ``(verdict, reason)`` with verdict ``pass``
        / ``fail`` / ``inconclusive``. Attainment compares WINDOWS (fractions
        of the SLO promise kept over the same wall-clock window), never raw
        latencies — a canary absorbing the fleet's heaviest prompts would
        fail a raw-latency bar while keeping every promise."""
        c, f = report["canary"], report["fleet"]
        if (c["requests"] < self.canary.min_requests
                or f["requests"] < self.canary.min_requests):
            return "inconclusive", (
                f"too few requests (canary {c['requests']}, "
                f"fleet {f['requests']}, need {self.canary.min_requests})")
        if (c["attainment"] is not None and f["attainment"] is not None
                and c["attainment"]
                < f["attainment"] - self.canary.attainment_margin):
            return "fail", (
                f"attainment {c['attainment']:.3f} < fleet "
                f"{f['attainment']:.3f} - {self.canary.attainment_margin}")
        if (canary_nll is not None and fleet_nll is not None
                and canary_nll > fleet_nll + self.canary.nll_margin):
            return "fail", (
                f"sampled nll {canary_nll:.4f} > fleet {fleet_nll:.4f} "
                f"+ {self.canary.nll_margin}")
        return "pass", ""

    # ------------------------------------------------------------- lifecycle

    def process(self, entry: dict) -> str:
        """Walk ONE candidate through gate → canary → promote/rollback.
        Returns the terminal action (``gate_fail`` / ``promoted`` /
        ``rolled_back``). Gate-only mode (no router) promotes on gate pass —
        qualification IS the deployment decision when there is no fleet."""
        name = entry["file"]
        path = os.path.join(self.ckpt_dir, name)
        step = entry.get("step")
        self._seen.add(name)
        self.ledger.record("candidate_seen", name, step=step,
                           health=entry.get("health"))
        self._writer.emit(T.promote_event(
            action="candidate_seen", candidate=name, step=step,
            incumbent=self.incumbent or ""))
        ok, reason, measured = self.qualify(entry)
        if not ok:
            self.counts["gate_fail"] += 1
            self.ledger.record("gate_fail", name, step=step, reason=reason,
                               **measured)
            self._writer.emit(T.promote_event(
                action="gate_fail", candidate=name, step=step, reason=reason,
                incumbent=self.incumbent or "",
                nll=measured.get("nll"),
                incumbent_nll=measured.get("incumbent_nll"),
                perf_s=measured.get("perf_s"),
                incumbent_perf_s=measured.get("incumbent_perf_s")))
            return "gate_fail"
        self.ledger.record("gate_pass", name, step=step, **measured)
        self._writer.emit(T.promote_event(
            action="gate_pass", candidate=name, step=step,
            incumbent=self.incumbent or "",
            nll=measured.get("nll"),
            incumbent_nll=measured.get("incumbent_nll"),
            perf_s=measured.get("perf_s"),
            incumbent_perf_s=measured.get("incumbent_perf_s")))
        if self.router is None:
            self._promote_state(path, measured)
            self.ledger.record("promoted", name, step=step, canaried=False)
            self._writer.emit(T.promote_event(
                action="promoted", candidate=name, step=step,
                reason="gate_only", incumbent=self.incumbent or ""))
            return "promoted"
        return self._canary_and_settle(entry, path, measured)

    def _canary_and_settle(self, entry: dict, path: str,
                           measured: dict) -> str:
        name, step = entry["file"], entry.get("step")
        self.ledger.record("canary_start", name, step=step)
        self._writer.emit(T.promote_event(
            action="canary_start", candidate=name, step=step,
            incumbent=self.incumbent or ""))
        roll = self.router.canary_reload(path)
        self._dwell(self.canary.window_s)
        report = self.router.canary_report()
        canary_nll = fleet_nll = None
        if self.sample_nll_fn is not None:
            if report["canary_samples"]:
                canary_nll = float(self.sample_nll_fn(
                    report["canary_samples"]))
            if report["fleet_samples"]:
                fleet_nll = float(self.sample_nll_fn(report["fleet_samples"]))
        verdict, reason = self.judge_canary(report, canary_nll, fleet_nll)
        self._writer.emit(T.canary_event(
            candidate=name, replica=roll["replica"], verdict=verdict,
            window_s=self.canary.window_s,
            canary_attainment=report["canary"]["attainment"],
            fleet_attainment=report["fleet"]["attainment"],
            canary_nll=canary_nll, fleet_nll=fleet_nll,
            canary_requests=report["canary"]["requests"],
            fleet_requests=report["fleet"]["requests"],
            reason=reason))
        self.ledger.record(
            "canary_pass" if verdict == "pass" else "canary_fail", name,
            step=step, verdict=verdict, reason=reason,
            replica=roll["replica"],
            canary_attainment=report["canary"]["attainment"],
            fleet_attainment=report["fleet"]["attainment"],
            canary_nll=canary_nll, fleet_nll=fleet_nll,
            canary_requests=report["canary"]["requests"],
            fleet_requests=report["fleet"]["requests"])
        if verdict == "pass":
            self.router.promote_canary()
            self._promote_state(path, measured)
            self.ledger.record("promoted", name, step=step, canaried=True)
            self._writer.emit(T.promote_event(
                action="promoted", candidate=name, step=step,
                incumbent=self.incumbent or ""))
            return "promoted"
        self.router.rollback_canary()
        self.counts["rolled_back"] += 1
        self.ledger.record("rolled_back", name, step=step, reason=reason,
                           incumbent=self.incumbent or "")
        self._writer.emit(T.promote_event(
            action="rolled_back", candidate=name, step=step, reason=reason,
            incumbent=self.incumbent or ""))
        return "rolled_back"

    def _promote_state(self, path: str, measured: dict) -> None:
        """The new last-good: the candidate's OWN gate measurements become
        the next comparison's incumbent baseline (re-probing the same file
        later would only add noise)."""
        self.counts["promoted"] += 1
        self.incumbent = path
        self.incumbent_nll = measured.get("nll", self.incumbent_nll)
        self.incumbent_perf_s = measured.get("perf_s", self.incumbent_perf_s)

    def run_once(self) -> list[str]:
        """One poll: process the NEWEST unseen candidate; older unseen ones
        are marked ``superseded`` (a faster trainer than promoter must not
        queue an ever-growing canary backlog — the newest checkpoint
        subsumes its elders). Returns the terminal actions taken."""
        cands = self.candidates()
        if not cands:
            return []
        for stale in cands[:-1]:
            self._seen.add(stale["file"])
            self.counts["superseded"] += 1
            self.ledger.record("superseded", stale["file"],
                               step=stale.get("step"),
                               by=cands[-1]["file"])
        return [self.process(cands[-1])]

    def run(self, *, stop_fn, poll_s: float = 0.5) -> dict:
        """The watch loop ``tools/train_serve_loop.py`` drives: poll the
        store until ``stop_fn()`` goes true, then drain any final unseen
        candidate before returning the action counts."""
        while not stop_fn():
            self.run_once()
            time.sleep(poll_s)
        self.run_once()
        return dict(self.counts)
