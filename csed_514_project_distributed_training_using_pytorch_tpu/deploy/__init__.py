"""Continuous deployment: checkpoint promotion with canary rollout.

``deploy/promoter.py`` closes the train→serve loop: it watches a versioned
checkpoint store (``utils/checkpoint.py``), qualifies each new candidate at a
gate (health stamp → accuracy budget → perf tolerance), canaries survivors on
ONE fleet replica via the router's rolling-reload path, and promotes
fleet-wide or auto-rolls-back on regression (DESIGN.md §26).
"""

from csed_514_project_distributed_training_using_pytorch_tpu.deploy.promoter import (
    CanaryConfig,
    GateConfig,
    Promoter,
    PromotionLedger,
    read_ledger,
)

__all__ = [
    "CanaryConfig",
    "GateConfig",
    "Promoter",
    "PromotionLedger",
    "read_ledger",
]
