"""Goodput accounting: an EXCLUSIVE wall-time decomposition of a training run.

The headline artifact of the reference paper is time-to-train vs machines —
and on a preemptible fleet, time-to-train is dominated not by step time but by
everything around it: XLA compile, checkpoint stalls, the teardown/backoff/
respawn of every restart, and the steps a resumed attempt re-executes because
the work they did the first time never became durable. The telemetry substrate
already records all of it (epoch/compile/checkpoint events per attempt,
supervisor restart events, trace spans); this module JOINS those streams into
one run-level ledger:

    wall_s == init_compile_s + compute_s + data_wait_s + checkpoint_stall_s
              + restart_badput_s + idle_s            (exclusive, by construction)

    goodput_frac == compute_s / wall_s

Segment rules (DESIGN.md §21 — the exclusive-decomposition rule):

- ``init_compile`` — fleet spawn + process init + AOT compile, but only for
  the FIRST attempt (attempt start → first epoch start). The same window in a
  restarted attempt is recovery overhead and charged to ``restart_badput``.
- ``compute`` — device execution (``execute_s``) plus eval of every epoch
  executed for the FIRST time. This is the goodput numerator: the only
  seconds that moved the model forward.
- ``data_wait`` — the epochs' ``data_s`` (index-plan/feed construction): the
  classic way real fleets miss their MFU numbers.
- ``checkpoint_stall`` — synchronous checkpoint-save wall time (the
  write-behind saver's ``background`` saves overlap compute and charge
  nothing). Restore wall is NOT added here: a restore only exists inside an
  init window already charged to its attempt's segment.
- ``restart_badput`` — everything a restart costs: the crash→respawn gap
  (teardown, backoff, re-import), the restarted attempt's init/compile
  window, and the full wall of every REPLAYED epoch — an epoch whose index an
  earlier attempt already executed. Replayed step time is badput, not
  compute: those steps re-derive state a checkpoint should have kept. A run
  with zero restarts has ``restart_badput_s == 0.0`` exactly.
- ``rollback_badput`` — the same accounting for restarts the supervisor
  classified as ``poisoned`` or ``desync`` (the numerical-immune-system
  rollback-and-skip path, resilience/poison.py): the teardown gap, the
  recovery init, and the replayed window of an attempt whose PREDECESSOR
  tripped the anomaly policy. Split from ``restart_badput`` because the cure
  differs — process badput says buy better capacity, rollback badput says
  the detector/skip policy is paying for bad math — and a run with zero
  rollbacks has ``rollback_badput_s == 0.0`` exactly.
- ``idle`` — the residual: whatever the instrumented windows do not cover
  (host work between epochs, drain tails, supervisor polling). Computed as
  ``wall - everything_else`` and clamped at zero; a negative residual (clock
  skew, overlapping windows) is surfaced as ``unaccounted_s`` instead of
  silently distorting a named segment.

Stream joining: every input file is JSONL through the one guarded reader
(``utils.jsonl.read_jsonl`` — a killed writer tears at most the final line,
which is skipped). Rows self-classify by ``event`` kind: ``span`` rows are
trace spans (absolute ``ts``), ``restart``/``supervise_summary`` rows are the
supervisor stream (absolute ``unix_time`` + relative ``t_s``), everything
else is trainer telemetry — split into ATTEMPTS at each ``manifest`` row and
anchored to absolute time via ``manifest.unix_time - manifest.t_s`` (the
writer's birth). Multi-attempt histories exist because the non-stream
``TelemetryWriter`` preserves prior events on the same path (utils/telemetry
.py): a supervised restart APPENDS its attempt after the crashed one's.

Backend-free (stdlib + utils.jsonl): ``tools/telemetry_report.py --goodput``
renders this without paying for a jax import.
"""

from __future__ import annotations

import os

from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
    read_jsonl,
)

#: Event kinds that belong to the supervisor stream (absolute ``unix_time``).
SUPERVISOR_KINDS = ("restart", "supervise_summary")

#: DERIVED ledger kinds: outputs of this module / the perf gate, not run
#: streams. ``--goodput --emit`` drops its line next to the run's other
#: files, and a later join of the same directory must skip it — a ledger
#: row carries no manifest and would otherwise masquerade as an unanchored
#: trainer attempt.
DERIVED_KINDS = ("goodput", "bench_guard")

#: The exclusive segments, in render order.
SEGMENTS = ("init_compile_s", "compute_s", "data_wait_s",
            "checkpoint_stall_s", "restart_badput_s", "rollback_badput_s",
            "idle_s")

#: Supervisor restart reasons whose recovery cost charges to
#: ``rollback_badput_s`` (the anomaly rollback-and-skip path).
ROLLBACK_REASONS = ("poisoned", "desync")


def _expand(paths) -> list[str]:
    """Files-or-directories -> the JSONL files under them (sorted)."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(os.path.join(p, f) for f in os.listdir(p)
                              if f.endswith(".jsonl")))
        else:
            out.append(p)
    return out


def read_streams(paths) -> dict:
    """Load + classify every row of every input file.

    Returns ``{"attempts": [...], "supervisor": [...], "spans": [...],
    "files": N, "events": N}``. Each attempt is ``{"anchor": unix-seconds or
    None, "rows": [...]}`` — one per ``manifest`` row per telemetry file, in
    file order (rows before a file's first manifest form an unanchored
    leading attempt, tolerated for hand-built streams)."""
    attempts: list[dict] = []
    supervisor: list[dict] = []
    spans: list[dict] = []
    files = _expand(paths)
    events = 0
    for path in files:
        current: dict | None = None
        for row in read_jsonl(path):
            events += 1
            kind = row.get("event")
            if kind == "span":
                spans.append(row)
                continue
            if kind in SUPERVISOR_KINDS:
                supervisor.append(row)
                continue
            if kind in DERIVED_KINDS:
                continue              # prior ledger output: never a stream
            if kind == "manifest":
                anchor = None
                if (row.get("unix_time") is not None
                        and row.get("t_s") is not None):
                    anchor = float(row["unix_time"]) - float(row["t_s"])
                current = {"anchor": anchor, "rows": [row]}
                attempts.append(current)
                continue
            if current is None:
                current = {"anchor": None, "rows": []}
                attempts.append(current)
            current["rows"].append(row)
    return {"attempts": attempts, "supervisor": supervisor, "spans": spans,
            "files": len(files), "events": events}


def _attempt_facts(attempt: dict) -> dict:
    """Reduce one attempt's rows to the decomposition's inputs, with absolute
    times where the attempt is anchored (relative ``t_s`` otherwise — a
    single unanchored stream still decomposes; only cross-stream joins need
    the anchor)."""
    anchor = attempt["anchor"] or 0.0
    rows = attempt["rows"]
    ts = [float(r["t_s"]) for r in rows if r.get("t_s") is not None]
    start = anchor
    end = anchor + (max(ts) if ts else 0.0)
    epochs = []
    for r in rows:
        if r.get("event") != "epoch":
            continue
        t_end = anchor + float(r.get("t_s") or 0.0)
        epochs.append({
            "epoch": int(r.get("epoch") or 0),
            "steps": int(r.get("steps") or 0),
            "wall_s": float(r.get("wall_s") or 0.0),
            "execute_s": float(r.get("execute_s") or 0.0),
            "eval_s": float(r.get("eval_s") or 0.0),
            "data_s": float(r.get("data_s") or 0.0),
            "end": t_end,
        })
    saves = [r for r in rows if r.get("event") == "checkpoint"
             and r.get("op") == "save"]
    restores = [r for r in rows if r.get("event") == "checkpoint"
                and r.get("op") == "restore"]
    return {
        "anchor": attempt["anchor"],
        "start": start,
        "end": end,
        "epochs": epochs,
        "save_stall_s": sum(float(r.get("wall_s") or 0.0) for r in saves
                            if not r.get("background")),
        "saves": len(saves),
        "restore_s": sum(float(r.get("wall_s") or 0.0) for r in restores),
        "restores": len(restores),
        "preempted": any(r.get("event") == "preempt" for r in rows),
    }


def decompose(paths) -> dict:
    """The run ledger: join the streams under ``paths`` (files and/or
    directories of JSONL) and return the exclusive decomposition.

    Raises ``ValueError`` when no attempt with epochs exists — there is no
    run to account for. Multi-attempt runs need anchors (each attempt's
    manifest carries one by construction); a hand-built single attempt
    without one decomposes in its own relative clock."""
    streams = read_streams(paths)
    attempts = [_attempt_facts(a) for a in streams["attempts"]]
    # A sidecar file of non-run events (a serving log, a drain summary) can
    # produce an anchored-or-not attempt with no epochs and no manifest
    # anchor; it contributes nothing and must not trip the multi-attempt
    # anchoring guard below.
    attempts = [a for a in attempts
                if a["epochs"] or a["anchor"] is not None]
    if not any(a["epochs"] for a in attempts):
        raise ValueError(
            f"no trainer epochs found in {list(paths)!r} — goodput needs at "
            f"least one telemetry stream with epoch events")
    if len(attempts) > 1 and any(a["anchor"] is None for a in attempts):
        raise ValueError(
            "multi-attempt run with an unanchored attempt (manifest without "
            "unix_time) — attempts cannot be ordered on one clock")
    attempts.sort(key=lambda a: a["start"])

    # Run span: trainer attempts, the supervisor's own stream (its writer is
    # born at supervise() entry and its summary lands after the final
    # teardown), and any trace spans, all on the shared unix clock.
    starts = [a["start"] for a in attempts]
    ends = [a["end"] for a in attempts]
    for row in streams["supervisor"]:
        if row.get("unix_time") is not None and row.get("t_s") is not None:
            anchor = float(row["unix_time"]) - float(row["t_s"])
            starts.append(anchor)
            ends.append(float(row["unix_time"]))
    for span in streams["spans"]:
        if span.get("ts") is not None:
            starts.append(float(span["ts"]))
            ends.append(float(span["ts"]) + float(span.get("dur_s") or 0.0))
    run_start, run_end = min(starts), max(ends)
    wall_s = max(0.0, run_end - run_start)

    # Attribute each restarted attempt's recovery cost by its CAUSE: the
    # supervisor restart event that spawned it — matched by TIME (the newest
    # restart stamped at or before the attempt's anchored start), not by
    # index, because an attempt that died before writing any telemetry leaves
    # no attempt entry and would shift an index-based join. Poisoned/desync
    # restarts are the anomaly rollback path and charge to rollback_badput;
    # everything else (crash, hung, timeout, or no supervisor stream at all)
    # stays restart_badput.
    restart_rows = sorted(
        (r for r in streams["supervisor"] if r.get("event") == "restart"),
        key=lambda r: float(r.get("unix_time") or 0.0))

    def badput_key(attempt_index: int) -> str:
        if not restart_rows or attempt_index <= 0:
            return "restart_badput_s"
        start = attempts[attempt_index]["start"]
        cause = None
        for r in restart_rows:
            stamp = r.get("unix_time")
            if stamp is None or float(stamp) <= start + 1e-6:
                cause = r
        if cause is None:               # clock skew: fall back to index order
            cause = restart_rows[min(attempt_index, len(restart_rows)) - 1]
        return ("rollback_badput_s" if cause.get("reason") in ROLLBACK_REASONS
                else "restart_badput_s")

    seg = dict.fromkeys(SEGMENTS, 0.0)
    seen_epochs: set[int] = set()
    epochs_total = epochs_replayed = replayed_steps = 0
    saves = restores = 0
    restore_s = 0.0
    prev_end: float | None = None
    for i, a in enumerate(attempts):
        first = i == 0
        if not first and prev_end is not None:
            # Crash -> respawn: teardown, supervisor backoff, the new
            # process's imports — none of it happens in an unfaulted run.
            seg[badput_key(i)] += max(0.0, a["start"] - prev_end)
        if a["epochs"]:
            first_epoch = a["epochs"][0]
            init = max(0.0, (first_epoch["end"] - first_epoch["wall_s"])
                       - a["start"])
            seg["init_compile_s" if first else badput_key(i)] += init
        for e in a["epochs"]:
            epochs_total += 1
            if e["epoch"] in seen_epochs:
                # A replay: an earlier attempt already executed this epoch.
                epochs_replayed += 1
                replayed_steps += e["steps"]
                seg[badput_key(i)] += e["wall_s"]
            else:
                seg["compute_s"] += e["execute_s"] + e["eval_s"]
                seg["data_wait_s"] += e["data_s"]
            seen_epochs.add(e["epoch"])
        seg["checkpoint_stall_s"] += a["save_stall_s"]
        saves += a["saves"]
        restores += a["restores"]
        restore_s += a["restore_s"]
        prev_end = a["end"]

    accounted = sum(seg.values())
    seg["idle_s"] = max(0.0, wall_s - accounted)
    unaccounted = max(0.0, accounted - wall_s)

    restarts = sum(r.get("event") == "restart"
                   for r in streams["supervisor"])
    rollbacks = sum(r.get("reason") in ROLLBACK_REASONS
                    for r in restart_rows)
    sup_summary = next((r for r in reversed(streams["supervisor"])
                        if r.get("event") == "supervise_summary"), None)
    return {
        "wall_s": wall_s,
        "start_unix": run_start,
        "end_unix": run_end,
        "segments": seg,
        "goodput_frac": seg["compute_s"] / wall_s if wall_s else None,
        "badput_frac": ((seg["restart_badput_s"] + seg["rollback_badput_s"])
                        / wall_s if wall_s else None),
        "attempts": len(attempts),
        "restarts": restarts if streams["supervisor"] else
        max(0, len(attempts) - 1),
        "rollbacks": rollbacks,
        "supervise_status": (sup_summary or {}).get("status"),
        "epochs": epochs_total,
        "epochs_replayed": epochs_replayed,
        "replayed_steps": replayed_steps,
        "checkpoint": {"saves": saves, "restores": restores,
                       "restore_s": restore_s},
        "preempted": any(a["preempted"] for a in attempts),
        "streams": {"files": streams["files"], "events": streams["events"],
                    "spans": len(streams["spans"]),
                    "supervisor_events": len(streams["supervisor"])},
        "unaccounted_s": unaccounted,
    }


def goodput_event(report: dict) -> dict:
    """The ledger as one ``{"event": "goodput", ...}`` telemetry line — what
    ``tools/telemetry_report.py --goodput --emit`` appends next to a run's
    other events, so A-vs-B comparisons can read the decomposition back
    without re-joining the streams."""
    return {
        "event": "goodput",
        "wall_s": report["wall_s"],
        **report["segments"],
        "goodput_frac": report["goodput_frac"],
        "badput_frac": report["badput_frac"],
        "attempts": report["attempts"],
        "restarts": report["restarts"],
        "rollbacks": report.get("rollbacks", 0),
        "epochs": report["epochs"],
        "epochs_replayed": report["epochs_replayed"],
        "replayed_steps": report["replayed_steps"],
        "unaccounted_s": report["unaccounted_s"],
    }
