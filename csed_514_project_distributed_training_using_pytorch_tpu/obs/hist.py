"""Mergeable log-bucket streaming histograms with a fixed relative-error bound.

The serving summaries used to keep every request's TTFT/TPOT/e2e/queue-wait as
a float list so the drain-time percentiles could use the repo's one estimator,
nearest-rank (``utils.jsonl.percentiles``). That is O(requests) memory per
series per process — fine for a bench run, wrong for a long-lived server. This
module is the bounded replacement: a DDSketch-style histogram whose buckets are
geometric in the value, so

- a quantile estimate is within a CONFIGURED relative error ``rel_err`` of the
  exact nearest-rank answer (the bucket containing the q-th value spans
  ``[gamma^(i-1), gamma^i]`` with ``gamma = (1+rel_err)/(1-rel_err)``; the
  reported midpoint ``2*gamma^i/(gamma+1)`` is within ``rel_err`` of every
  value in the bucket);
- memory is O(buckets), independent of the request count — for latencies
  between 10 microseconds and 1 hour at 1% relative error that is ~1000
  int-keyed counts, and in practice a serving run touches a few dozen;
- two histograms MERGE by adding bucket counts — replicas can sketch locally
  and ship the sketch to the router (it rides the stats protocol as plain
  JSON), and the merged quantiles carry the same error bound as if one
  process had seen every sample.

Nearest-rank over the raw series stays the ORACLE estimator: tests pin this
sketch against it within ``rel_err``, and anything that still has the full
series (the report CLI reading per-request events) keeps using it.

Zeros and negatives: latencies are nonnegative, but a clock hiccup can produce
0.0 (and upstream code sometimes clamps); zeros get a dedicated count (exact,
not bucketed). Negative values raise — a negative latency is a bug to surface,
not data to sketch. None values are skipped, matching ``percentiles``.

Backend-free (stdlib only): the router and the report CLIs import this.
"""

from __future__ import annotations

import math


class LogHistogram:
    """A streaming histogram over nonnegative floats with relative-error
    quantiles, ``O(buckets)`` memory, and loss-free merge.

    ``rel_err`` is the guarantee: ``|estimate - exact| <= rel_err * exact``
    for any quantile of the values added (exact = the nearest-rank answer
    over the same multiset). JSON round-trip: :meth:`to_json` emits a plain
    dict (string bucket keys — JSON objects cannot key on ints), and
    :meth:`from_json` restores it; merge accepts either a ``LogHistogram``
    or such a dict, so a sketch can cross a process boundary as JSON and be
    merged without reconstruction.
    """

    def __init__(self, rel_err: float = 0.01):
        if not 0.0 < rel_err < 1.0:
            raise ValueError(f"rel_err must be in (0, 1), got {rel_err}")
        self.rel_err = float(rel_err)
        self._gamma = (1.0 + rel_err) / (1.0 - rel_err)
        self._log_gamma = math.log(self._gamma)
        self._buckets: dict[int, int] = {}
        self._zeros = 0
        self._count = 0
        self._sum = 0.0
        self._min: float | None = None
        self._max: float | None = None

    # ------------------------------------------------------------------ write

    def add(self, x: float | None) -> None:
        """Record one value. None is skipped (the ``percentiles`` convention:
        an unmeasured latency contributes nothing, not a zero)."""
        if x is None:
            return
        x = float(x)
        if math.isnan(x):
            return
        if x < 0.0:
            raise ValueError(f"LogHistogram holds nonnegative values, got {x}")
        self._count += 1
        self._sum += x
        self._min = x if self._min is None else min(self._min, x)
        self._max = x if self._max is None else max(self._max, x)
        if x == 0.0:
            self._zeros += 1
            return
        idx = math.ceil(math.log(x) / self._log_gamma)
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def extend(self, xs) -> None:
        for x in xs:
            self.add(x)

    def merge(self, other: "LogHistogram | dict") -> "LogHistogram":
        """Fold ``other`` (a histogram or its :meth:`to_json` dict) into this
        one, in place. Gammas must match — merging sketches built at different
        error bounds would silently void the guarantee."""
        if isinstance(other, dict):
            other = LogHistogram.from_json(other)
        if abs(other.rel_err - self.rel_err) > 1e-12:
            raise ValueError(
                f"cannot merge histograms with different rel_err "
                f"({self.rel_err} vs {other.rel_err})")
        for idx, n in other._buckets.items():
            self._buckets[idx] = self._buckets.get(idx, 0) + n
        self._zeros += other._zeros
        self._count += other._count
        self._sum += other._sum
        for attr in ("_min", "_max"):
            a, b = getattr(self, attr), getattr(other, attr)
            if b is not None:
                red = min if attr == "_min" else max
                setattr(self, attr, b if a is None else red(a, b))
        return self

    # ------------------------------------------------------------------- read

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float | None:
        return self._sum / self._count if self._count else None

    @property
    def min(self) -> float | None:
        return self._min

    @property
    def max(self) -> float | None:
        return self._max

    @property
    def num_buckets(self) -> int:
        return len(self._buckets) + (1 if self._zeros else 0)

    def quantile(self, q: float) -> float | None:
        """The q-th percentile (``q`` in [0, 100]), nearest-rank semantics:
        the value whose rank is ``ceil(q/100 * count)`` — the same rank rule
        as ``utils.jsonl.percentiles``, so the two estimators disagree only
        by the bucket rounding the ``rel_err`` bound covers. None when empty.

        The estimate for a bucket ``i`` (covering ``(gamma^(i-1), gamma^i]``)
        is ``2*gamma^i / (gamma + 1)``: the value equidistant (in relative
        terms) from both bucket edges, which is what makes the bound
        symmetric: ``estimate/(1+rel_err) <= true <= estimate/(1-rel_err)``.
        The min/max are tracked exactly, so q=0/q=100 are exact and every
        estimate is clamped into ``[min, max]`` (the clamp can only shrink
        the error)."""
        if self._count == 0:
            return None
        rank = max(1, math.ceil(q / 100.0 * self._count))
        if rank <= self._zeros:
            return 0.0
        seen = self._zeros
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if seen >= rank:
                est = 2.0 * self._gamma ** idx / (self._gamma + 1.0)
                return min(max(est, self._min), self._max)
        return self._max          # float drift fallback: the top bucket

    def percentiles(self, qs=(50, 95, 99)) -> dict | None:
        """The serving-summary shape: ``{"p50": ..., "p95": ..., "p99": ...}``
        (None when the histogram is empty) — drop-in for
        ``utils.jsonl.percentiles`` on a sketched series."""
        if self._count == 0:
            return None
        return {f"p{q}": self.quantile(q) for q in qs}

    # ------------------------------------------------------------------- json

    def to_json(self) -> dict:
        """A plain-JSON snapshot (string bucket keys). Small by construction:
        one entry per occupied bucket."""
        return {
            "rel_err": self.rel_err,
            "count": self._count,
            "zeros": self._zeros,
            "sum": self._sum,
            "min": self._min,
            "max": self._max,
            "buckets": {str(i): n for i, n in sorted(self._buckets.items())},
        }

    @classmethod
    def from_json(cls, doc: dict) -> "LogHistogram":
        h = cls(rel_err=float(doc["rel_err"]))
        h._count = int(doc.get("count") or 0)
        h._zeros = int(doc.get("zeros") or 0)
        h._sum = float(doc.get("sum") or 0.0)
        h._min = None if doc.get("min") is None else float(doc["min"])
        h._max = None if doc.get("max") is None else float(doc["max"])
        h._buckets = {int(i): int(n)
                      for i, n in (doc.get("buckets") or {}).items()}
        return h

    def __len__(self) -> int:
        return self._count

    def __repr__(self) -> str:
        return (f"LogHistogram(rel_err={self.rel_err}, count={self._count}, "
                f"buckets={self.num_buckets})")


class WindowedLogHistogram:
    """Sliding-window quantiles over a latency stream, sketch-backed.

    The gray-failure detectors (straggler ejection, the hedge deadline —
    DESIGN.md §23) need *recent* dispatch-latency quantiles: a replica that
    was slow ten minutes ago and recovered must not read as a straggler now.
    This is the classic two-pane rotation: samples land in the CURRENT
    :class:`LogHistogram` pane; every ``window_s`` the panes rotate (current
    becomes previous, previous is dropped). A query merges both panes, so the
    answer always covers between one and two windows of history — bounded
    staleness with O(buckets) memory and no per-sample ring buffer, the same
    tradeoff the attainment tracker makes with its time-bucketed window.

    Not thread-safe by itself; the router calls it under its own lock.
    """

    def __init__(self, rel_err: float = 0.01, window_s: float = 30.0):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.rel_err = float(rel_err)
        self.window_s = float(window_s)
        self._cur = LogHistogram(rel_err)
        self._prev: LogHistogram | None = None
        self._cur_start: float | None = None

    def _rotate(self, now: float) -> None:
        if self._cur_start is None:
            self._cur_start = now
            return
        # Catch up over long idle gaps: more than two windows of silence
        # leaves NO recent evidence — both panes drop.
        while now - self._cur_start >= self.window_s:
            self._prev = self._cur if now - self._cur_start < 2 * self.window_s \
                else None
            self._cur = LogHistogram(self.rel_err)
            self._cur_start += self.window_s

    def add(self, x: float | None, now: float) -> None:
        self._rotate(now)
        self._cur.add(x)

    def count(self, now: float) -> int:
        self._rotate(now)
        return self._cur.count + (self._prev.count if self._prev else 0)

    def quantile(self, q: float, now: float) -> float | None:
        """The q-th percentile over the last one-to-two windows (None when
        empty) — merge is bucket addition, so the estimate keeps the panes'
        ``rel_err`` bound."""
        self._rotate(now)
        if self._prev is None or self._prev.count == 0:
            return self._cur.quantile(q)
        merged = LogHistogram(self.rel_err)
        merged.merge(self._cur)
        merged.merge(self._prev)
        return merged.quantile(q)

    def reset(self) -> None:
        """Drop all history — the post-probe fresh start: a recovered
        replica's score must come from post-recovery evidence only."""
        self._cur = LogHistogram(self.rel_err)
        self._prev = None
        self._cur_start = None
