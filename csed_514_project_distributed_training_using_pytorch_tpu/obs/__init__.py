"""Run-level observability: histograms, goodput accounting, SLO attainment.

This package sits ABOVE the JSONL telemetry substrate (``utils/jsonl.py``,
``utils/telemetry.py``) and below the report CLIs: it turns event streams into
the run-level numbers an operator actually steers by —

- :mod:`obs.hist` — mergeable log-bucket streaming histograms (DDSketch-style
  fixed relative error), the bounded-memory replacement for the serving
  summaries' full per-request latency lists;
- :mod:`obs.goodput` — the exclusive wall-time decomposition of a training
  run (init/compile, step compute, checkpoint stall, restart badput, data
  wait, idle) joined from the telemetry/checkpoint/supervisor/trace streams,
  with the headline goodput fraction;
- :mod:`obs.slo` — SLO specs (TTFT/TPOT/e2e targets + attainment window) and
  the sliding-window attainment tracker the serving fleet surfaces in
  ``serve_summary``/``router_summary``/``fleet_snapshot``.

Everything here is backend-free by doctrine (graftlint ``backend-purity``):
the router, the supervisor, and the report CLIs all import from this package,
and none of them may initialize — or even import — a jax backend.
"""

from csed_514_project_distributed_training_using_pytorch_tpu.obs.hist import (
    LogHistogram,
)
from csed_514_project_distributed_training_using_pytorch_tpu.obs.slo import (
    AttainmentTracker,
    SLOSpec,
)

__all__ = ["LogHistogram", "SLOSpec", "AttainmentTracker"]
