"""SLO specs and attainment tracking for the serving path.

A latency percentile answers "how slow were we"; an SLO answers "did we keep
the promise". This module is the promise side: a spec names per-request
targets (TTFT, TPOT, e2e — any subset), and attainment is the fraction of
finished requests that met EVERY named target (a timed-out request never
attains — a missing latency on a request that never produced a first token is
a miss, not a free pass).

Two consumers, two shapes:

- **run-level** — ``Server``/``Router`` count met/total over the whole run and
  emit one ``{"event": "slo", ...}`` line at drain, plus the same dict inside
  ``serve_summary``/``router_summary`` (the A-vs-B surface);
- **windowed** — :class:`AttainmentTracker` also keeps a sliding window
  (``spec.window_s``) so the periodic ``fleet_snapshot`` can report RECENT
  attainment per replica and fleet-wide. That is the signal the autoscaler
  should eventually scale on (ROADMAP open item 5: attainment, not raw
  utilization — a fleet at 60% utilization that is missing its TTFT target
  needs capacity; one at 95% that is meeting it does not).

Backend-free (stdlib only): the router imports this, and the router must
never initialize a jax backend.
"""

from __future__ import annotations

import dataclasses
from collections import deque

#: The per-request latency fields a spec can bound, in report order.
TARGET_FIELDS = ("ttft_s", "tpot_s", "e2e_s")


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Per-request latency targets (None = not part of the promise) plus the
    sliding-window width the snapshot-time attainment is computed over."""

    ttft_s: float | None = None
    tpot_s: float | None = None
    e2e_s: float | None = None
    window_s: float = 30.0

    def __post_init__(self):
        if all(getattr(self, f) is None for f in TARGET_FIELDS):
            raise ValueError("SLOSpec needs at least one of "
                             f"{'/'.join(TARGET_FIELDS)} set")
        if self.window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {self.window_s}")

    @classmethod
    def parse(cls, text: str) -> "SLOSpec | None":
        """The CLI surface: ``"ttft=0.5,e2e=2.0,window=30"`` (keys are the
        target fields minus ``_s``, plus ``window``). Empty/``"off"`` = None —
        serving without a promise is the default."""
        text = (text or "").strip()
        if not text or text == "off":
            return None
        kw: dict = {}
        for part in text.split(","):
            key, _, value = part.partition("=")
            key = key.strip()
            field = "window_s" if key == "window" else f"{key}_s"
            if field not in TARGET_FIELDS + ("window_s",):
                raise ValueError(f"unknown SLO field {key!r} in {text!r}")
            kw[field] = float(value)
        return cls(**kw)

    def describe(self) -> dict:
        """The spec as it appears inside slo events/summaries."""
        return {f: getattr(self, f) for f in TARGET_FIELDS} | {
            "window_s": self.window_s}

    def meets(self, *, ok: bool = True, ttft_s: float | None = None,
              tpot_s: float | None = None, e2e_s: float | None = None) -> bool:
        """Did one finished request keep the promise? Every NAMED target must
        be measured and under target; an unnamed target is ignored. A request
        that did not finish ok (timeout, error) never attains."""
        if not ok:
            return False
        measured = {"ttft_s": ttft_s, "tpot_s": tpot_s, "e2e_s": e2e_s}
        for field in TARGET_FIELDS:
            target = getattr(self, field)
            if target is None:
                continue
            value = measured[field]
            if value is None or value > target:
                return False
        return True


class AttainmentTracker:
    """Run-level and sliding-window attainment for one spec.

    ``observe`` takes the completion's latencies plus ``now`` (the caller's
    ``time.monotonic()`` — the serving path's one clock); ``attainment()`` is
    the run-level fraction, ``window()`` the recent-window view the
    ``fleet_snapshot`` timeline reports. Not thread-safe on its own: the
    router already serializes completion recording under its lock, the server
    resolves from its single loop thread."""

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.requests = 0
        self.met = 0
        self._recent: deque[tuple[float, bool]] = deque()

    def observe(self, now: float, *, ok: bool = True,
                ttft_s: float | None = None, tpot_s: float | None = None,
                e2e_s: float | None = None) -> bool:
        hit = self.spec.meets(ok=ok, ttft_s=ttft_s, tpot_s=tpot_s,
                              e2e_s=e2e_s)
        self.requests += 1
        self.met += hit
        self._recent.append((now, hit))
        self._evict(now)
        return hit

    def _evict(self, now: float) -> None:
        horizon = now - self.spec.window_s
        while self._recent and self._recent[0][0] < horizon:
            self._recent.popleft()

    def attainment(self) -> float | None:
        """Run-level: met / finished, None before the first completion."""
        return self.met / self.requests if self.requests else None

    def window(self, now: float) -> dict:
        """The sliding-window view: ``{"attainment", "requests"}`` over the
        last ``window_s`` seconds (attainment None when the window is empty —
        an idle replica has no recent promise to have kept or broken)."""
        self._evict(now)
        n = len(self._recent)
        met = sum(hit for _, hit in self._recent)
        return {"attainment": met / n if n else None, "requests": n}

    def summary(self) -> dict:
        """The run-level dict embedded in serve_summary/router_summary."""
        return {
            "spec": self.spec.describe(),
            "requests": self.requests,
            "met": self.met,
            "attainment": self.attainment(),
        }


def slo_event(tracker: AttainmentTracker, *, source: str,
              window: dict | None = None) -> dict:
    """The drain-time (or snapshot-time) ``slo`` telemetry line: the spec,
    run-level attainment, and optionally the current window view. ``source``
    names the emitter (``"server"``, ``"router"``) — one run can carry both,
    and the report must not conflate the replica-local promise with the
    client-facing one."""
    ev = {"event": "slo", "source": source, **tracker.summary()}
    if window is not None:
        ev["window"] = window
    return ev
