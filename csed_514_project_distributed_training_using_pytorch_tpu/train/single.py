"""Single-process trainer — the reference ``src/train.py`` workflow, TPU-native.

Reproduces, in order (call stack in SURVEY.md §3.1): wall-clock start, seeding, loader
construction, the 6-digit sample-grid figure, baseline eval *before* training, then
``n_epochs`` of (train with a progress line + metric record + checkpoint every
``log_interval`` batches, then eval), and the final train/test loss-curve figure
(reference ``src/train.py:10-117``).

TPU-first differences:

- the hot loop runs as jit-compiled ``lax.scan`` segments of ``log_interval`` steps over the
  device-resident dataset — one host sync per *log tick* (which the reference already pays to
  print) instead of per batch, and zero per-step Python dispatch;
- the loop is a ``main(config)`` function, not an import-time script (the reference executes
  on import, SURVEY.md §3.1), and reads everything from ``SingleProcessConfig`` instead of
  module globals (quirk §2d.3);
- checkpoints keep the reference's overwrite-in-place every-log-tick policy
  (``src/train.py:84-85``, quirk §2d.4) but are atomic and restorable (``--resume``).
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    BatchLoader, download_mnist, load_mnist, mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    build_model,
    validate_model_config,
)
from csed_514_project_distributed_training_using_pytorch_tpu import resilience
from csed_514_project_distributed_training_using_pytorch_tpu.ops import optim
from csed_514_project_distributed_training_using_pytorch_tpu.train.guard import (
    GuardRuntime,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    TrainState, create_train_state, init_health, make_epoch_fn, make_eval_fn,
    make_train_step, merge_health, update_health,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint
from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
    SingleProcessConfig, parse_config,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics as M
from csed_514_project_distributed_training_using_pytorch_tpu.utils import plotting
from csed_514_project_distributed_training_using_pytorch_tpu.utils.profiling import (
    annotate,
    maybe_profile,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    telemetry as T,
)


def main(config: SingleProcessConfig = SingleProcessConfig(), *,
         resume_from: str | None = None,
         datasets=None) -> tuple[TrainState, M.MetricsHistory]:
    """Run the full single-process workflow; returns final state + metric history.

    ``datasets`` optionally injects a pre-built ``(train, test)`` Dataset pair (tests,
    notebooks); by default MNIST is loaded from ``config.data_dir``.
    """
    watch = M.Stopwatch()                       # ≙ t0, reference src/train.py:10
    validate_model_config(config.model, remat=config.remat,
                          remat_policy=config.remat_policy, causal=config.causal,
                          attention_window=config.attention_window,
                          kv_heads=config.kv_heads, rope=config.rope)  # fail fast, pre-side-effects
    if config.grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {config.grad_accum}")
    if config.grad_accum > 1 and config.batch_size_train % config.grad_accum:
        raise ValueError(f"batch_size_train {config.batch_size_train} not divisible "
                         f"by grad_accum {config.grad_accum}")
    if config.health_stats and config.use_host_pipeline:
        raise ValueError("--health-stats rides the compiled scan carry "
                         "(train/step.py::HealthStats) — it is not available on the "
                         "per-batch --use-host-pipeline path")
    if config.health_stats and not config.telemetry:
        raise ValueError("--health-stats emits telemetry 'health' events and has no "
                         "other output — pass --telemetry PATH too")
    tele = T.TelemetryWriter(config.telemetry,
                             preserve=bool(config.resume_from))
    tele.emit(T.manifest_event(config, run_type="single"))
    # Resilience wiring (flag-gated, host-side only; with both flags off no step
    # fetch or syscall is added — same zero-cost discipline as --health-stats).
    rt = resilience.RunHooks(heartbeat_dir=config.heartbeat_dir,
                             handle_preemption=config.handle_preemption)
    # Numerical immune system (--guard): in-step verdict + identity update;
    # host side is epoch-boundary bookkeeping only.
    grt = GuardRuntime(config, tele=tele,
                       store_dir=os.path.join(config.results_dir, "checkpoints"))
    if config.download_data and datasets is None:
        download_mnist(config.data_dir)   # ≙ torchvision download=True, src/train.py:26-31
    train_ds, test_ds = datasets if datasets is not None else load_mnist(config.data_dir)
    train_ds = mnist.truncate(train_ds, config.max_train_examples)
    test_ds = mnist.truncate(test_ds, config.max_test_examples)

    M.log(f"Loaded MNIST ({train_ds.source}): {len(train_ds)} train / {len(test_ds)} test")
    root = jax.random.PRNGKey(config.seed)      # ≙ torch.manual_seed, src/train.py:19-21
    init_rng, dropout_rng = jax.random.split(root)
    train_loader = BatchLoader(train_ds, config.batch_size_train, shuffle=True,
                               seed=config.seed)

    # Sample grid before training (≙ reference src/train.py:43-57).
    plotting.save_sample_grid(test_ds.images, test_ds.labels,
                              os.path.join(config.images_dir, "train_images.png"))

    model = build_model(config.model, bf16=config.bf16, remat=config.remat,
                        remat_policy=config.remat_policy,
                        causal=config.causal,
                        attention_window=config.attention_window,
                        kv_heads=config.kv_heads, rope=config.rope)
    optimizer = optim.make_optimizer(config.optimizer,
                                     learning_rate=config.learning_rate,
                                     momentum=config.momentum,
                                     weight_decay=config.weight_decay)
    if config.optimizer != "sgd" and config.use_pallas_kernels:
        raise ValueError("--use-pallas-kernels fuses the SGD-momentum update — it "
                         "requires --optimizer sgd")
    state = create_train_state(model, init_rng, optimizer=optimizer,
                               ema=config.ema_decay > 0, guard=config.guard)
    resume_from = resume_from or config.resume_from or None
    if resume_from:                             # the restore path the reference lacks
        t_restore = time.perf_counter()
        state = checkpoint.restore_train_state(resume_from, state)
        if tele.enabled:
            tele.emit(T.checkpoint_event(
                op="restore", path=resume_from, kind="full",
                nbytes=os.path.getsize(resume_from),
                wall_s=time.perf_counter() - t_restore, step=int(state.step)))
        M.log(f"Resumed from {resume_from} at step {int(state.step)}")
        # Manifest cursor cross-check (DESIGN.md §26): a versioned checkpoint
        # carries the data position that produced it; a disagreeing config
        # resumes a DIFFERENT stream and should say so up front.
        note = checkpoint.check_cursor_resume(resume_from, seed=config.seed,
                                              step=int(state.step))
        if note:
            M.log(f"WARNING: {note}")
    grt.baseline(state)     # this attempt's anomaly-counter zero point
    # Schedule horizon = THIS invocation's planned end: the restored step plus
    # n_epochs of updates (single-trainer resume means "train n_epochs MORE", unlike
    # the distributed/composed trainers' skip-completed-epochs semantics). Anchoring
    # past the restored step keeps a resumed cosine run decaying over its own span
    # instead of evaluating beyond the original horizon at multiplier 0 (a silently
    # frozen run). drop_last=False: the ragged tail batch is still one update.
    total_steps = (int(state.step)
                   + config.n_epochs * (-(-len(train_ds) // config.batch_size_train)))
    lr_schedule = optim.make_lr_schedule(config.lr_schedule,
                                         warmup_steps=config.warmup_steps,
                                         total_steps=total_steps)
    if lr_schedule is not None and config.use_pallas_kernels:
        raise ValueError("--use-pallas-kernels bakes the learning rate into the "
                         "fused update kernel — use the default constant schedule "
                         "without warmup")

    # Device-resident datasets: the one and only host->device transfer.
    train_x, train_y = jnp.asarray(train_ds.images), jnp.asarray(train_ds.labels)
    test_x, test_y = jnp.asarray(test_ds.images), jnp.asarray(test_ds.labels)

    health = config.health_stats
    segment_fn = jax.jit(
        make_epoch_fn(model, learning_rate=config.learning_rate,
                      momentum=config.momentum,
                      use_pallas=config.use_pallas_kernels,
                      unroll=config.scan_unroll, pregather=config.pregather,
                      grad_accum=config.grad_accum, optimizer=optimizer,
                      lr_schedule=lr_schedule,
                      clip_grad_norm=config.clip_grad_norm,
                      ema_decay=config.ema_decay,
                      label_smoothing=config.label_smoothing,
                      health=health, guard=grt.spec),
        donate_argnums=(0,))
    step_fn = jax.jit(
        make_train_step(model, learning_rate=config.learning_rate,
                        momentum=config.momentum,
                        use_pallas=config.use_pallas_kernels,
                        grad_accum=config.grad_accum, optimizer=optimizer,
                        lr_schedule=lr_schedule,
                        clip_grad_norm=config.clip_grad_norm,
                        ema_decay=config.ema_decay,
                        label_smoothing=config.label_smoothing,
                        with_metrics=health, guard=grt.spec),
        donate_argnums=(0,))
    # The final partial batch (drop_last=False) is ragged and need not divide by
    # grad_accum; accumulation is a memory knob, so the tail just steps unaccumulated.
    if config.grad_accum == 1:
        tail_step_fn = step_fn
    else:
        tail_step_fn = jax.jit(
            make_train_step(model, learning_rate=config.learning_rate,
                            momentum=config.momentum,
                            use_pallas=config.use_pallas_kernels,
                            optimizer=optimizer, lr_schedule=lr_schedule,
                            clip_grad_norm=config.clip_grad_norm,
                            ema_decay=config.ema_decay,
                            label_smoothing=config.label_smoothing,
                            with_metrics=health, guard=grt.spec),
            donate_argnums=(0,))
    eval_fn = jax.jit(make_eval_fn(model, batch_size=config.batch_size_test))

    # Compile/execute split (telemetry): AOT-compile the epoch-segment program via
    # jit(...).lower().compile() so first-epoch wall time decomposes into compile_s
    # (here) + execute_s (the loop's honest-synced device time), and so XLA's
    # cost_analysis() prices the step for the MFU estimate. The compiled program is
    # then what the loop invokes — the jit cache never pays a second compile.
    segment_call = segment_fn
    compile_s = flops_per_step = None
    if config.telemetry and not config.use_host_pipeline:
        idx_struct = jax.ShapeDtypeStruct(
            (config.log_interval, config.batch_size_train), jnp.int32)
        compiled, aot = T.aot_compile(segment_fn, state, train_x, train_y,
                                      idx_struct, dropout_rng)
        if compiled is not None:
            segment_call = compiled
            compile_s = aot["lower_s"] + aot["compile_s"]
            if aot["flops"]:
                flops_per_step = aot["flops"] / config.log_interval
            tele.emit(T.compile_event("epoch_segment", aot,
                                      steps_per_call=config.log_interval))

    history = M.MetricsHistory()
    n_train, n_test = len(train_ds), len(test_ds)
    ckpt_path = os.path.join(config.results_dir, "model.ckpt")
    ckpt_store = os.path.join(config.results_dir, "checkpoints")
    saver = checkpoint.make_saver(config.async_checkpoint, tele=tele)

    def evaluate(state: TrainState, examples_seen: int) -> None:
        # EMA-enabled runs evaluate the averaged weights (the reason to keep an EMA).
        eval_params = state.ema if state.ema is not None else state.params
        sum_nll, correct = jax.device_get(eval_fn(eval_params, test_x, test_y))
        avg = float(sum_nll) / n_test           # ≙ sum-then-divide, src/train.py:94-97
        history.record_test(examples_seen, avg)
        M.log(M.test_summary_line(avg, int(correct), n_test, watch.elapsed()))

    def train_epoch(state: TrainState, epoch: int):
        times = {"execute": 0.0, "data": 0.0, "loss_sum": 0.0, "loss_steps": 0}
        t_data = time.perf_counter()
        train_loader.set_epoch(epoch)
        indices = train_loader.sampler.epoch_indices(epoch)
        idx_full = train_loader.epoch_index_matrix(epoch, allow_empty=True)
        times["data"] = time.perf_counter() - t_data
        full_steps = idx_full.shape[0]
        epoch_health = init_health() if health else None

        # log_interval-sized jit'd scan segments, then the ragged tail.
        li = config.log_interval
        for seg_start in range(0, full_steps, li):
            seg = idx_full[seg_start:seg_start + li]
            t_exec = time.perf_counter()
            if len(seg) == li:
                state, out = segment_call(state, train_x, train_y,
                                          jnp.asarray(seg), dropout_rng)
                if health:
                    losses, seg_health = out
                    epoch_health = merge_health(epoch_health, seg_health)
                else:
                    losses = out
                seg_losses = np.asarray(jax.device_get(losses))
            else:  # tail of < log_interval full batches — stepwise (same compiled step)
                step_losses = []
                for row in seg:
                    state, out = step_fn(state, train_x[jnp.asarray(row)],
                                         train_y[jnp.asarray(row)], dropout_rng)
                    if health:
                        loss, gnorm = out
                        epoch_health = update_health(epoch_health, loss, gnorm)
                    else:
                        loss = out
                    step_losses.append(loss)    # device scalars — ONE fetch below
                seg_losses = np.asarray(jax.device_get(step_losses))
            last_loss = float(seg_losses[-1])   # the tick's host sync, as before
            # Epoch-mean accumulation (telemetry): same per-epoch train_loss
            # definition as the distributed/LM/composed epoch events.
            times["loss_sum"] += float(seg_losses.sum())
            times["loss_steps"] += seg_losses.size
            times["execute"] += time.perf_counter() - t_exec  # closed by the fetch above
            batches_done = min(seg_start + li, full_steps)
            examples_seen = (epoch - 1) * n_train + batches_done * config.batch_size_train
            M.log(M.train_progress_line(epoch, batches_done * config.batch_size_train,
                                        n_train, last_loss))
            history.record_train(examples_seen, last_loss)
            # every-log-tick overwrite checkpoint (≙ reference src/train.py:84-85)
            saver.save_train_state(ckpt_path, state)

        # final partial batch (drop_last=False, ≙ torch DataLoader default)
        tail = indices[full_steps * config.batch_size_train:]
        if len(tail):
            t_exec = time.perf_counter()
            state, out = tail_step_fn(state, train_x[jnp.asarray(tail)],
                                      train_y[jnp.asarray(tail)], dropout_rng)
            if health:
                epoch_health = update_health(epoch_health, *out)
                tail_loss = out[0]
            else:
                tail_loss = out
            times["loss_sum"] += float(tail_loss)
            times["loss_steps"] += 1
            times["execute"] += time.perf_counter() - t_exec
        return state, epoch_health, times

    def train_epoch_host_pipeline(state: TrainState, epoch: int):
        """The reference-shaped loop: host batches through the native C++ threaded
        prefetcher (the DataLoader worker-pool analog), one device dispatch per batch.
        Identical step sequence (same index plan, same per-step RNG fold) to the scan fast
        path — only the feeding mechanism differs. (--health-stats is rejected up
        front on this path — the accumulators ride the scan carry.)"""
        t_epoch = time.perf_counter()
        train_loader.set_epoch(epoch)
        train_loader.pop_wait_s()       # this epoch's stall ledger starts at zero
        full_steps = train_loader.epoch_index_matrix(epoch, allow_empty=True).shape[0]
        step_losses = []      # device scalars — fetched ONCE at epoch end
        # Live per-batch bar (≙ the reference's tqdm, src/train_dist.py:76) — only
        # here, where a per-step dispatch already exists; tty/process-0 gated.
        with M.ProgressBar(full_steps, desc=f"Epoch {epoch} ") as bar:
            for b, (bx, by) in enumerate(train_loader.prefetch_iter(epoch),
                                         start=1):
                state, loss = step_fn(state, jnp.asarray(bx), jnp.asarray(by),
                                      dropout_rng)
                step_losses.append(loss)
                if b % config.log_interval == 0 or b == full_steps:
                    # The log line and the in-place bar share the terminal: finish
                    # the bar's line first (float(loss) syncs here anyway — the bar
                    # itself never forces a per-batch device sync).
                    bar.close()
                    examples_seen = ((epoch - 1) * n_train
                                     + b * config.batch_size_train)
                    M.log(M.train_progress_line(epoch,
                                                b * config.batch_size_train,
                                                n_train, float(loss)))
                    history.record_train(examples_seen, float(loss))
                    saver.save_train_state(ckpt_path, state)
                bar.update(1)
        tail = train_loader.sampler.epoch_indices(epoch)[
            full_steps * config.batch_size_train:]
        if len(tail):
            state, tail_loss = tail_step_fn(state, jnp.asarray(train_ds.images[tail]),
                                            jnp.asarray(train_ds.labels[tail]),
                                            dropout_rng)
            step_losses.append(tail_loss)
        losses = np.asarray(jax.device_get(step_losses)) if step_losses else np.zeros(0)
        # Per-batch host dispatch: device execution overlaps the feed, so the
        # compile/execute split doesn't decompose here — but the loader now
        # meters the seconds the CONSUMER actually blocked on it, so report
        # loop-minus-stall as execute and the stall as data (the goodput
        # data_wait input; before this the split read data=0 even on a
        # data-starved run, DESIGN.md §26).
        wait_s = train_loader.pop_wait_s()
        loop_s = time.perf_counter() - t_epoch
        return state, None, {"execute": max(0.0, loop_s - wait_s),
                             "data": wait_s,
                             "loss_sum": float(losses.sum()),
                             "loss_steps": int(losses.size)}

    if config.use_host_pipeline:
        train_epoch = train_epoch_host_pipeline

    try:
        with maybe_profile(config.profile, config.profile_dir):
            with annotate("eval"):
                evaluate(state, 0)              # baseline eval, ≙ src/train.py:106
            best_step_s = None
            for epoch in range(1, config.n_epochs + 1):
                # heartbeat (with the previous boundary's param fingerprint)
                # + armed faults; no-op off
                rt.epoch_tick(state, epoch, fingerprint=grt.fingerprint)
                step_before = int(state.step)
                t_epoch = time.perf_counter()
                with annotate(f"train_epoch_{epoch}"):
                    state, epoch_health, times = train_epoch(state, epoch)
                jax.block_until_ready(state.params)  # honest wall-clock (SURVEY.md §7c)
                wall_s = time.perf_counter() - t_epoch
                t_eval = time.perf_counter()
                with annotate("eval"):
                    evaluate(state, epoch * n_train)
                if epoch_health is not None:
                    # SPMD-entered by every process (the norm program would
                    # deadlock a fleet if only process 0 ran it); emission below
                    # stays process-0 gated.
                    health_host = jax.device_get(epoch_health)
                    param_norm = T.global_l2_norm(state.params)
                if tele.enabled:
                    eval_s = time.perf_counter() - t_eval
                    steps = int(state.step) - step_before
                    step_s = times["execute"] / steps if steps else None
                    if step_s and (best_step_s is None or step_s < best_step_s):
                        best_step_s = step_s
                    tele.emit(T.epoch_event(
                        epoch, examples=n_train, steps=steps, wall_s=wall_s,
                        execute_s=times["execute"], eval_s=eval_s,
                        data_s=times["data"], compile_s=compile_s,
                        flops_per_step=flops_per_step,
                        train_loss=times["loss_sum"] / times["loss_steps"]
                        if times["loss_steps"] else None,
                        val_loss=history.test_losses[-1],
                        mfu=T.estimate_mfu(flops_per_step, step_s)["mfu"]))
                    if epoch_health is not None:
                        tele.emit(T.health_event(epoch, health_host, steps,
                                                 param_norm=param_norm))
                # Guard boundary: anomaly verdict fetch + event + fingerprint,
                # then the manifest health stamp for the versioned save.
                stamp = grt.epoch_end(state, epoch,
                                      steps=int(state.step) - step_before)
                if config.keep_checkpoints:
                    # Versioned store (manifest + checksums + keep-last-N GC) for
                    # the supervisor's newest-HEALTHY resume scan.
                    checkpoint.save_versioned(
                        ckpt_store, state, keep=config.keep_checkpoints,
                        tele=tele, health=stamp,
                        # The manifest's data cursor: the (seed, epoch)-pure
                        # permutation's resume anchor (DESIGN.md §26).
                        cursor={"version": 1, "kind": "epoch",
                                "seed": config.seed, "epoch": epoch + 1,
                                "batch": 0, "step": int(state.step)})
                # Anomaly policy AFTER the stamped checkpoint is durable
                # (raises Poisoned; __main__ exits 65).
                grt.check_poisoned(state)
                # Cooperative preemption at the epoch boundary. The per-tick
                # overwrite checkpoint lags the tail batch, so save explicitly
                # before raising (raises Preempted; __main__ exits 75).
                rt.check_preempt(
                    epoch=epoch, state=state, checkpoint=ckpt_path, tele=tele,
                    save=lambda: saver.save_train_state(ckpt_path, state))
            if tele.enabled and best_step_s is not None:
                tele.emit(T.mfu_event(flops_per_step, best_step_s))

        plotting.save_loss_curves(
            history, os.path.join(config.images_dir, "train_test_curve.png"))
        M.save_metrics_jsonl(history, os.path.join(config.results_dir, "metrics.jsonl"))
        saver.save_train_state(ckpt_path, state)
    finally:
        # Drain the write-behind queue even when the loop raises or is signalled —
        # the queued checkpoint is exactly the killed-run artifact the per-tick
        # policy exists for, and flush() re-raises deferred background IO errors.
        # The preemption latch is uninstalled so in-process callers get their
        # signal semantics back.
        rt.uninstall()
        saver.flush()
    return state, history


if __name__ == "__main__":
    try:
        main(parse_config(SingleProcessConfig))
    except resilience.Preempted as e:
        M.log(f"preempted at step {e.step} (checkpoint {e.checkpoint or 'n/a'}); "
              f"exiting {resilience.EXIT_PREEMPTED} — resume with --resume-from")
        raise SystemExit(resilience.EXIT_PREEMPTED)
    except resilience.Poisoned as e:
        M.log(f"poisoned at step {e.step} (anomaly window "
              f"{e.window[0]}:{e.window[1]}); exiting "
              f"{resilience.EXIT_POISONED} — the supervisor rolls back to the "
              f"newest healthy checkpoint and skips the window")
        raise SystemExit(resilience.EXIT_POISONED)
