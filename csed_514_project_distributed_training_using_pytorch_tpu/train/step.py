"""The compiled train/eval step — forward, backward, and update as ONE XLA program.

This is the TPU-native replacement for the reference's per-batch sequence
``zero_grad → forward → nll → backward → optimizer.step`` (reference ``src/train.py:72-76``,
``src/train_dist.py:80-84``), which there spans the Python interpreter, the C++ autograd
engine, and (distributed) DDP's bucketed allreduce hooks. Here the whole thing — including the
gradient all-reduce when compiled over a multi-device mesh (see
``parallel/data_parallel.py``) — is a single jit-compiled, fused XLA program:

- ``make_train_step``: one optimizer step; the autograd-engine analog is ``jax.value_and_grad``.
- ``make_epoch_fn``: a ``lax.scan`` over a whole epoch (or a log-interval segment) of steps,
  gathering batches from the *device-resident* dataset by index — zero host↔device transfer
  and zero Python dispatch on the hot path, unlike the reference's per-step ``.item()`` sync
  (``src/train_dist.py:85``, SURVEY.md §7 hard part (c)).
- ``make_eval_fn``: full-split evaluation (sum-NLL + correct count) as one scanned program —
  the reference's ``test()`` loop (``src/train.py:87-104``, ``src/train_dist.py:92-109``)
  with its deprecated ``size_average=False`` sum-then-divide semantics.

Dropout randomness: a per-epoch PRNG key folded with the global step index gives every step a
fresh, reproducible key (SURVEY.md §7 hard part (b)); under SPMD the mask array itself is
batch-sharded, so replicas draw distinct masks from the same key.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.ops.optim import (
    Optimizer,
    clip_by_global_norm,
    global_l2_norm,
    sgd,
    sgd_init,
)


class HealthStats(NamedTuple):
    """Training-health accumulators that ride the epoch scan's CARRY.

    The compiled-``lax.scan`` epoch (DESIGN.md §1) makes per-step host logging
    impossible by construction — so the health signal is accumulated *inside* the
    compiled program (five f32 scalars threaded through the carry) and fetched
    ONCE at epoch end with the losses array: zero extra host syncs on the hot
    path. Gradient norms are measured PRE-clip — the explosion detector must see
    what clipping would otherwise hide. ``utils.telemetry.health_event`` turns one
    of these into the ``health`` JSONL event."""

    loss_min: jax.Array
    loss_max: jax.Array
    loss_sum: jax.Array
    grad_norm_sum: jax.Array
    grad_norm_max: jax.Array


def init_health() -> HealthStats:
    """Identity element for ``update_health`` (min over inf, max over -inf, sums over 0)."""
    inf = jnp.asarray(jnp.inf, jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    return HealthStats(inf, -inf, zero, zero, zero)


def update_health(h: HealthStats, loss, grad_norm) -> HealthStats:
    """Fold one step's (loss, pre-clip global grad norm) into the accumulators."""
    loss = loss.astype(jnp.float32)
    grad_norm = grad_norm.astype(jnp.float32)
    return HealthStats(jnp.minimum(h.loss_min, loss),
                       jnp.maximum(h.loss_max, loss),
                       h.loss_sum + loss,
                       h.grad_norm_sum + grad_norm,
                       jnp.maximum(h.grad_norm_max, grad_norm))


def merge_health(a: HealthStats, b: HealthStats) -> HealthStats:
    """Combine accumulators from two scan segments of the same epoch (the
    single-process trainer runs an epoch as log-interval-sized segments)."""
    return HealthStats(jnp.minimum(a.loss_min, b.loss_min),
                       jnp.maximum(a.loss_max, b.loss_max),
                       a.loss_sum + b.loss_sum,
                       a.grad_norm_sum + b.grad_norm_sum,
                       jnp.maximum(a.grad_norm_max, b.grad_norm_max))


class GuardSpec(NamedTuple):
    """Static knobs of the numerical guard (``--guard``): the anomaly verdict
    computed INSIDE the compiled step and the replay windows to skip.

    ``zscore``/``rel_floor`` parameterize the spike detector: a step whose
    pre-clip global grad norm exceeds ``ema_mean + zscore * max(ema_std,
    rel_floor * ema_mean)`` is a spike (the floor keeps a near-zero-variance
    warm stream from tripping on ordinary jitter). ``warmup_steps`` clean
    steps must be observed before the z-test arms — non-finite detection is
    always armed. ``ema_decay`` is the detector's window. ``skip`` is the
    static tuple of half-open ``(lo, hi)`` step windows a supervised restart
    replays as identity updates (``--skip-steps``; baked at trace time — each
    restart is a fresh process and compiles anyway)."""

    zscore: float = 8.0
    warmup_steps: int = 4
    ema_decay: float = 0.9
    rel_floor: float = 0.5
    skip: tuple = ()


class GuardState(NamedTuple):
    """The guard's scan-carry accumulators — nine scalars riding the
    ``TrainState`` pytree (an optional field, like ``ema``: absent = zero
    cost, and guard-off checkpoints stay byte-identical). Checkpointing the
    detector state is deliberate: a rollback resumes with the EMA it had at
    the healthy point, so the z-test re-arms exactly where the oracle's
    would — the bitwise-replay contract extends to the guard itself."""

    ema_mean: jax.Array            # EMA of clean pre-clip grad norms
    ema_sq: jax.Array              # EMA of their squares (variance source)
    count: jax.Array               # clean steps folded into the EMA (i32)
    anomalies: jax.Array           # detected anomalies (nonfinite + spikes)
    nonfinite: jax.Array           # non-finite loss/grad verdicts
    spikes: jax.Array              # z-score verdicts
    skipped: jax.Array             # identity updates applied (anomaly + window)
    first_anomaly_step: jax.Array  # -1 until the first anomaly
    last_anomaly_step: jax.Array   # -1 until the first anomaly


def init_guard() -> GuardState:
    # One fresh array per field: the state is donated into the compiled step,
    # and aliased leaves would be the same buffer donated twice.
    f0 = lambda: jnp.zeros((), jnp.float32)
    i0 = lambda: jnp.zeros((), jnp.int32)
    none = lambda: jnp.asarray(-1, jnp.int32)
    return GuardState(f0(), f0(), i0(), i0(), i0(), i0(), i0(), none(), none())


def _grad_poison_fn():
    """Trace-time fold of any armed grad-poison faults (``resilience/faults.py``
    ``nan``/``spike``/``bitflip``) into the step: returns ``None`` (zero added
    ops — the flag-off bitwise pin) unless ``RESILIENCE_FAULTS`` arms a poison
    matching this process. Poison fires at EXACT step equality, so a resumed
    attempt replaying the step reproduces it — determinism is what makes the
    skip set a complete cure."""
    from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
        faults,
    )

    specs = faults.grad_poisons()
    if not specs:
        return None

    def poison(grads, step):
        for f in specs:
            hit = step == f.step
            if f.kind == "nan":
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(hit, jnp.full_like(g, jnp.nan), g),
                    grads)
            elif f.kind == "spike":
                scale = jnp.asarray(f.scale, jnp.float32)
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(hit, (g.astype(jnp.float32)
                                              * scale).astype(g.dtype), g),
                    grads)
            else:                          # bitflip: one element of one leaf
                def flip(path, g, f=f):
                    if f.leaf not in jax.tree_util.keystr(path):
                        return g
                    flat = g.reshape(-1)
                    planted = jnp.where(hit, jnp.asarray(f.scale, g.dtype),
                                        flat[0])
                    return flat.at[0].set(planted).reshape(g.shape)

                grads = jax.tree_util.tree_map_with_path(flip, grads)
        return grads

    return poison


class TrainState(NamedTuple):
    """Model + optimizer state as one pytree (params, optimizer state, global step).

    ``velocity`` is the optimizer state: the SGD velocity tree historically (and for
    ``--optimizer sgd`` today), or the AdamW moment state — see the state-shape
    contract in ``ops/optim.py``. The field name stays for checkpoint compatibility.

    ``ema`` is the optional params-shaped exponential-moving-average tree
    (``--ema-decay``); ``None`` (the default, and the reference-parity surface) keeps
    the pytree free of it. It shards exactly like ``params`` under every layout, and
    ``utils.checkpoint.restore_train_state`` reconciles checkpoints written on either
    side of the flag.

    ``guard`` is the optional :class:`GuardState` (``--guard``): nine scalar
    anomaly-detector accumulators that ride the same optional-field contract —
    ``None`` keeps the pytree (and the checkpoint bytes) identical to before
    the guard existed; the restore paths reconcile across the flag exactly
    like ``ema``."""

    params: dict
    velocity: dict
    step: jax.Array  # int32 scalar
    ema: dict | None = None
    guard: GuardState | None = None


def create_train_state(model, rng: jax.Array,
                       sample_input_shape=(1, 28, 28, 1), *,
                       optimizer: Optimizer | None = None,
                       ema: bool = False, guard: bool = False) -> TrainState:
    """Initialize params (PyTorch-default distributions, see ``ops/initializers.py``) and
    zero optimizer state (SGD velocity by default). Under SPMD every process derives
    identical state from the same seed — the replica-consistency analog of DDP's initial
    parameter broadcast (reference ``src/train_dist.py:63``).

    ``ema=True`` seeds the EMA tree as a copy of the initial params (torch
    ``swa_utils.AveragedModel``'s construction-time copy). ``guard=True``
    attaches a fresh :class:`GuardState` (the ``--guard`` anomaly detector)."""
    variables = model.init({"params": rng}, jnp.zeros(sample_input_shape))
    params = variables["params"]
    opt_init = optimizer.init if optimizer is not None else sgd_init
    return TrainState(params=params, velocity=opt_init(params),
                      step=jnp.zeros((), jnp.int32),
                      ema=jax.tree_util.tree_map(jnp.array, params) if ema else None,
                      guard=init_guard() if guard else None)


def make_train_step(model, *, learning_rate: float, momentum: float,
                    use_pallas: bool = False, grad_accum: int = 1,
                    aux_loss_weight: float = 0.01,
                    optimizer: Optimizer | None = None,
                    lr_schedule: Callable | None = None,
                    clip_grad_norm: float = 0.0,
                    ema_decay: float = 0.0,
                    label_smoothing: float = 0.0,
                    loss_fn: Callable | None = None,
                    with_metrics: bool = False,
                    guard: GuardSpec | None = None) -> Callable:
    """Build ``step(state, images, labels, rng) -> (state, loss)``.

    The loss is the canonical ``nll(log_probs)`` formulation (see
    ``ops.cross_entropy_loss`` for why this also covers the reference's distributed
    CrossEntropyLoss objective). Wrap in ``jax.jit`` (or compile over a mesh via
    ``parallel.data_parallel.compile_step``) before use.

    ``use_pallas=True`` swaps in the fused Pallas loss and optimizer kernels
    (``ops/pallas_kernels.py``) — numerically equivalent to float32 round-off; intended for
    the single-device step path (a Pallas call is an opaque unit to the GSPMD partitioner,
    so the multi-mesh ``compile_epoch`` path keeps the XLA-fused default).

    ``grad_accum=N`` splits the batch into N equal microbatches, accumulates their
    gradients in a ``lax.scan``, and applies ONE optimizer update on the mean — peak
    activation memory shrinks N× while the update equals the full-batch step exactly
    (equal-size microbatch means average to the batch mean; pinned in
    ``tests/test_train_step.py``). Dropout draws a distinct mask per microbatch.

    Models that ``sow`` auxiliary losses into the ``"aux_loss"`` collection (the MoE
    transformer's load-balance term, ``models/transformer.py``) have their sum added to
    the objective scaled by ``aux_loss_weight``; for every other model the collection is
    empty and the term is exactly zero.

    ``optimizer`` (an ``ops.optim.Optimizer``) swaps the update rule — e.g.
    ``optim.adamw(...)``; ``None`` keeps the reference-parity SGD built from
    ``learning_rate``/``momentum``. The state passed in must come from the matching
    ``create_train_state(..., optimizer=...)``.

    ``lr_schedule`` (from ``optim.make_lr_schedule``) maps ``state.step`` to a
    learning-rate multiplier inside the compiled step — warmup/cosine cost zero host
    round-trips. Not supported with ``use_pallas`` (the fused kernel bakes the rate).

    ``clip_grad_norm > 0`` clips the (microbatch-averaged) gradients to that global
    norm before the update, with torch ``clip_grad_norm_`` semantics
    (``optim.clip_by_global_norm``); 0 disables. Under SPMD the clip sees the
    all-reduced global gradient, so every replica scales identically.

    ``ema_decay > 0`` maintains ``state.ema`` — an exponential moving average of the
    params updated INSIDE the compiled step after each optimizer update, with torch
    ``swa_utils.AveragedModel(avg_fn=get_ema_multi_avg_fn(decay))`` semantics (pinned
    against real torch in ``tests/test_optim.py``): the first update copies the fresh
    params, later updates apply ``ema ← decay·ema + (1−decay)·params``. The state must
    come from ``create_train_state(..., ema=True)``.

    ``loss_fn(params, xs, ys, rng) -> scalar`` overrides the classification objective
    entirely (e.g. the LM's next-token loss, ``train/lm.py``) while keeping every
    other mechanism — grad-accum, clipping, schedules, optimizers — unchanged. Not
    supported with ``use_pallas`` (the fused kernels implement the standard loss).

    ``with_metrics=True`` changes the return to ``(state, (loss, grad_norm))``,
    where ``grad_norm`` is the PRE-clip global L2 norm of the (microbatch-averaged)
    gradients — the ``--health-stats`` signal accumulated by the scanned epoch
    (``HealthStats``). The flag-off path is byte-for-byte the unmetered step: no
    new ops enter the compiled program (pinned in ``tests/test_telemetry.py``),
    and the update math is identical either way (the norm only READS the grads),
    so metered and unmetered training produce bitwise-identical params.

    ``guard`` (a :class:`GuardSpec`) arms the numerical immune system: the step
    computes a fixed-shape anomaly verdict (non-finite loss/grads, grad-norm
    z-score against the EMA threaded through ``state.guard``) and a poisoned
    step deterministically selects the IDENTITY update — params/opt-state/EMA
    unchanged, skip counters bumped, ``step`` still advanced so the data order
    and per-step RNG folds of a run with skips stay aligned with one without.
    Steps inside ``guard.skip`` windows take the identity update without
    counting as anomalies (the supervised-replay contract). The state must
    come from ``create_train_state(..., guard=True)``. ``guard=None`` adds
    zero ops (bitwise flag-off pin), and a guard whose verdict never fires
    selects the freshly-computed update exactly (``jnp.where`` on a false
    predicate is bitwise the false branch) — anomaly-free guard-on training is
    bitwise identical to guard-off.
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if optimizer is None:
        optimizer = sgd(learning_rate, momentum)
    if use_pallas and optimizer.name != "sgd":
        raise ValueError("use_pallas fuses the SGD-momentum update kernel — "
                         f"optimizer {optimizer.name!r} is not supported there")
    if use_pallas and lr_schedule is not None:
        raise ValueError("use_pallas bakes the learning rate into the fused kernel — "
                         "lr_schedule is not supported there")
    if use_pallas and label_smoothing:
        raise ValueError("use_pallas fuses the plain NLL loss kernel — "
                         "label_smoothing is not supported there")
    if use_pallas and loss_fn is not None:
        raise ValueError("use_pallas fuses the standard NLL loss kernel — a custom "
                         "loss_fn is not supported there")
    if use_pallas:
        from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
            pallas_kernels as pk,
        )

    def default_loss_fn(params, images, labels, rng):
        log_probs, variables = model.apply(
            {"params": params}, images, deterministic=False,
            rngs={"dropout": rng}, mutable=["aux_loss"])
        aux_leaves = jax.tree_util.tree_leaves(variables.get("aux_loss", {}))
        aux = (aux_loss_weight * sum(aux_leaves)) if aux_leaves else 0.0
        if use_pallas:
            # log_softmax is idempotent: fused nll-from-logits on log-probs is identical.
            return pk.nll_from_logits(log_probs, labels) + aux
        return ops.nll_loss(log_probs, labels,
                            label_smoothing=label_smoothing) + aux

    if loss_fn is None:
        loss_fn = default_loss_fn

    poison = _grad_poison_fn()

    def apply_update(state, grads, loss):
        if poison is not None:
            # Armed grad-poison injection (deterministic, exact-step) — applied
            # to the (accumulation-averaged) grads BEFORE the norm is measured,
            # so the detector sees exactly what the update would apply.
            grads = poison(grads, state.step)
        # The health-stats grad norm is PRE-clip (clipping must not hide an
        # explosion) — which is exactly the norm the clip computes and returns, so
        # the metered clipped step measures it once.
        gnorm = None
        if clip_grad_norm > 0.0:
            grads, gnorm = clip_by_global_norm(grads, clip_grad_norm)
        elif with_metrics or guard is not None:
            gnorm = global_l2_norm(grads)
        if use_pallas:
            # Hyperparams come from the Optimizer (not this function's kwargs) so an
            # explicitly passed optim.sgd(...) can never silently diverge from what
            # the kernel applies.
            params, velocity = pk.sgd_momentum_step(
                state.params, state.velocity, grads,
                learning_rate=optimizer.hyperparams["learning_rate"],
                momentum=optimizer.hyperparams["momentum"])
        else:
            scale = lr_schedule(state.step) if lr_schedule is not None else 1.0
            params, velocity = optimizer.update(state.params, state.velocity, grads,
                                                lr_scale=scale)
        ema = state.ema
        if ema_decay > 0.0:
            if ema is None:
                raise ValueError("ema_decay needs create_train_state(..., ema=True)")
            # torch AveragedModel.update_parameters: the first call (n_averaged == 0)
            # copies the params; later calls apply the EMA rule. state.step is the
            # pre-increment counter, so it doubles as n_averaged.
            first = state.step == 0
            ema = jax.tree_util.tree_map(
                lambda e, p: jnp.where(first, p,
                                       ema_decay * e + (1.0 - ema_decay) * p),
                ema, params)
        new_guard = state.guard
        if guard is not None:
            if state.guard is None:
                raise ValueError("a guarded step needs "
                                 "create_train_state(..., guard=True)")
            g = state.guard
            loss32 = loss.astype(jnp.float32)
            gnorm32 = gnorm.astype(jnp.float32)
            finite = jnp.isfinite(loss32) & jnp.isfinite(gnorm32)
            # Spike test: deviation from the clean-step EMA, with a relative
            # floor under the std so a flat warm stream's jitter cannot trip.
            std = jnp.sqrt(jnp.maximum(g.ema_sq - g.ema_mean * g.ema_mean, 0.0))
            threshold = g.ema_mean + guard.zscore * jnp.maximum(
                std, guard.rel_floor * g.ema_mean)
            warm = g.count >= guard.warmup_steps
            spike = warm & finite & (gnorm32 > threshold)
            in_window = jnp.zeros((), bool)
            for lo, hi in guard.skip:
                in_window = in_window | ((state.step >= lo) & (state.step < hi))
            # Replay-window steps are deliberate skips, never anomalies — a
            # resumed attempt re-detecting the poison it is skipping would
            # immediately re-trip the --anomaly-exit policy.
            nonfinite = ~finite & ~in_window
            spike = spike & ~in_window
            anomaly = nonfinite | spike
            skip = anomaly | in_window
            # A poisoned/window step selects the IDENTITY update. jnp.where
            # selects exactly (no arithmetic on the unselected branch), so a
            # NaN update can never leak and a clean step is bitwise the
            # unguarded update.
            keep = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(skip, o, n), new, old)
            params = keep(params, state.params)
            velocity = keep(velocity, state.velocity)
            if ema_decay > 0.0:
                ema = keep(ema, state.ema)
            clean = ~skip
            gsafe = jnp.where(finite, gnorm32, 0.0)
            d = jnp.asarray(guard.ema_decay, jnp.float32)
            seeded = g.count > 0   # first clean sample seeds the EMA directly
            new_mean = jnp.where(
                clean, jnp.where(seeded, d * g.ema_mean + (1.0 - d) * gsafe,
                                 gsafe), g.ema_mean)
            new_sq = jnp.where(
                clean, jnp.where(seeded, d * g.ema_sq
                                 + (1.0 - d) * gsafe * gsafe,
                                 gsafe * gsafe), g.ema_sq)
            one = jnp.ones((), jnp.int32)
            zero = jnp.zeros((), jnp.int32)
            new_guard = GuardState(
                ema_mean=new_mean, ema_sq=new_sq,
                count=g.count + jnp.where(clean, one, zero),
                anomalies=g.anomalies + jnp.where(anomaly, one, zero),
                nonfinite=g.nonfinite + jnp.where(nonfinite, one, zero),
                spikes=g.spikes + jnp.where(spike, one, zero),
                skipped=g.skipped + jnp.where(skip, one, zero),
                first_anomaly_step=jnp.where(
                    anomaly & (g.first_anomaly_step < 0),
                    state.step.astype(jnp.int32), g.first_anomaly_step),
                last_anomaly_step=jnp.where(anomaly,
                                            state.step.astype(jnp.int32),
                                            g.last_anomaly_step))
        new_state = TrainState(params, velocity, state.step + 1, ema, new_guard)
        if with_metrics:
            return new_state, (loss, gnorm)
        return new_state, loss

    def step(state: TrainState, images, labels, rng) -> tuple[TrainState, jax.Array]:
        step_rng = jax.random.fold_in(rng, state.step)
        loss, grads = jax.value_and_grad(loss_fn)(state.params, images, labels, step_rng)
        return apply_update(state, grads, loss)

    if grad_accum == 1:
        return step

    def accum_step(state: TrainState, images, labels, rng) -> tuple[TrainState, jax.Array]:
        b = images.shape[0]
        if b % grad_accum:
            raise ValueError(f"batch {b} not divisible by grad_accum {grad_accum}")
        micro = b // grad_accum
        xs = images.reshape((grad_accum, micro) + images.shape[1:])
        ys = labels.reshape(grad_accum, micro)
        step_rng = jax.random.fold_in(rng, state.step)

        def body(carry, chunk):
            grads_sum, loss_sum = carry
            x, y, i = chunk
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, x, y, jax.random.fold_in(step_rng, i))
            return (jax.tree_util.tree_map(jnp.add, grads_sum, grads),
                    loss_sum + loss), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
        (grads_sum, loss_sum), _ = lax.scan(
            body, (zeros, jnp.zeros((), jnp.float32)),
            (xs, ys, jnp.arange(grad_accum)))
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum, grads_sum)
        return apply_update(state, grads, loss_sum / grad_accum)

    return accum_step


def make_epoch_fn(model, *, learning_rate: float, momentum: float,
                  use_pallas: bool = False, unroll: int = 1,
                  pregather: bool = False, grad_accum: int = 1,
                  optimizer: Optimizer | None = None,
                  lr_schedule: Callable | None = None,
                  clip_grad_norm: float = 0.0,
                  ema_decay: float = 0.0,
                  label_smoothing: float = 0.0,
                  health: bool = False,
                  guard: GuardSpec | None = None) -> Callable:
    """Build ``epoch(state, images, labels, idx_matrix, rng) -> (state, losses)``.

    ``images``/``labels`` are the full (device-resident) training split; ``idx_matrix`` is a
    ``[num_steps, batch]`` int32 index plan (from ``BatchLoader.epoch_index_matrix`` — the
    sampler output). The scan runs ``num_steps`` optimizer steps with no host round-trip;
    per-step losses come back as one ``[num_steps]`` array for logging, replacing the
    reference's per-step ``loss.item()`` host syncs (``src/train_dist.py:85``).

    ``unroll`` replicates the step body that many times per scan iteration (semantics
    unchanged — SGD stays strictly sequential); on a tiny model, per-iteration control
    overhead can rival the step's compute, and unrolling amortizes it at the cost of
    compile time.

    ``pregather`` (semantics unchanged) gathers the whole epoch's batches ONCE before the
    scan — one big take instead of one small gather per step — and scans over the
    pre-batched arrays; trades HBM (one epoch-sized copy of the split) for per-step
    gather latency.

    ``health=True`` builds the step with ``with_metrics`` and threads
    ``HealthStats`` accumulators through the scan carry; the epoch then returns
    ``(state, (losses, health))`` — same program otherwise, bitwise-identical
    params (pinned in ``tests/test_telemetry.py``).

    ``guard`` (a :class:`GuardSpec`) arms the in-scan anomaly verdict +
    guarded identity update (see ``make_train_step``); the detector state
    rides ``state.guard`` through the carry — no signature change, no extra
    host syncs (the verdict is fetched with the epoch's one sanctioned
    ``state`` read).
    """
    train_step = make_train_step(model, learning_rate=learning_rate, momentum=momentum,
                                 use_pallas=use_pallas, grad_accum=grad_accum,
                                 optimizer=optimizer, lr_schedule=lr_schedule,
                                 clip_grad_norm=clip_grad_norm, ema_decay=ema_decay,
                                 label_smoothing=label_smoothing,
                                 with_metrics=health, guard=guard)
    return make_epoch_from_step(train_step, unroll=unroll, pregather=pregather,
                                health=health)


def make_epoch_from_step(train_step: Callable, *, unroll: int = 1,
                         pregather: bool = False, health: bool = False) -> Callable:
    """Wrap any ``step(state, images, labels, rng)`` into the scanned epoch program
    (same contract as ``make_epoch_fn`` — used for alternative step implementations,
    e.g. the LM trainer's next-token step, ``train/lm.py``).

    ``health=True`` expects a step built with ``with_metrics=True`` (returning
    ``(state, (loss, grad_norm))``), carries ``HealthStats`` through the scan, and
    returns ``(state, (losses, health))``."""

    def epoch(state: TrainState, images, labels, idx_matrix, rng):
        def apply(carry, x, y):
            if not health:
                return train_step(carry, x, y, rng)
            st, h = carry
            st, (loss, gnorm) = train_step(st, x, y, rng)
            return (st, update_health(h, loss, gnorm)), loss

        init = (state, init_health()) if health else state

        if pregather:
            def body(carry, batch):
                x, y = batch
                return apply(carry, x, y)

            xs = (jnp.take(images, idx_matrix.reshape(-1), axis=0)
                  .reshape(idx_matrix.shape + images.shape[1:]))
            ys = jnp.take(labels, idx_matrix.reshape(-1),
                          axis=0).reshape(idx_matrix.shape)
            out, losses = lax.scan(body, init, (xs, ys), unroll=unroll)
        else:
            def body(carry, idx):
                return apply(carry, jnp.take(images, idx, axis=0),
                             jnp.take(labels, idx, axis=0))

            out, losses = lax.scan(body, init, idx_matrix, unroll=unroll)

        if health:
            st, h = out
            return st, (losses, h)
        return out, losses

    return epoch


def make_eval_fn(model, *, batch_size: int = 1000) -> Callable:
    """Build ``evaluate(params, images, labels) -> (sum_nll, num_correct)``.

    Reproduces the reference ``test()`` semantics: deterministic forward, NLL summed over the
    split then divided by its size by the caller (``src/train.py:94-97``), plus argmax
    accuracy (``src/train.py:95-96``). The split size must divide by ``batch_size`` (MNIST
    test: 10,000 / 1,000, reference ``src/train.py:14``).
    """

    def evaluate(params, images, labels):
        n = images.shape[0]
        if n % batch_size:
            raise ValueError(f"eval split size {n} not divisible by eval batch "
                             f"{batch_size} — the tail would be silently dropped while "
                             f"callers divide by the full split size")
        num_batches = n // batch_size
        xs = images[:num_batches * batch_size].reshape(
            (num_batches, batch_size) + images.shape[1:])
        ys = labels[:num_batches * batch_size].reshape(num_batches, batch_size)

        def body(carry, batch):
            x, y = batch
            log_probs = model.apply({"params": params}, x)
            sum_nll, correct = carry
            sum_nll += ops.nll_loss(log_probs, y, reduction="sum")
            correct += jnp.sum(jnp.argmax(log_probs, axis=-1) == y)
            return (sum_nll, correct), None

        (sum_nll, correct), _ = lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (xs, ys))
        return sum_nll, correct

    return evaluate
