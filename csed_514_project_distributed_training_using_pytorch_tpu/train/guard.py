"""Host-side wiring of the numerical immune system — shared by all four trainers.

The guard itself lives inside the compiled step (``train/step.py``: the anomaly
verdict and the identity update are in-program, zero extra host syncs). What is
left for the host is epoch-boundary bookkeeping, identical across trainers and
owned here so the four loops stay four-line diffs:

- fetch the :class:`~..train.step.GuardState` carry ONCE per epoch (with the
  losses — the sanctioned fetch), emit the ``anomaly`` telemetry event;
- compute the cross-replica param fingerprint (host-LOCAL over this
  process's addressable shards — a global reduction would all-reduce the
  corruption into every replica's value) and hand it to the heartbeat via
  ``RunHooks.epoch_tick``;
- build the health stamp for ``save_versioned(health=)`` — ``clean`` meaning
  no anomaly was detected since the previous versioned save, which is what
  ``newest_healthy_checkpoint`` rolls back to;
- enforce the ``--anomaly-exit`` policy: once the attempt has detected that
  many anomalies, raise :class:`~..resilience.poison.Poisoned` (AFTER the
  epoch's stamped checkpoint is durable) with the step window to skip, and
  leave the poison marker for the supervisor.
"""

from __future__ import annotations

import jax

from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    poison,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    GuardSpec,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    metrics as M,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    telemetry as T,
)


class GuardRuntime:
    """One trainer run's guard bookkeeping. Construct unconditionally —
    every method is a cheap attribute check when ``--guard`` is off, so the
    flag-off trainer performs identical host and device work (the RunHooks
    discipline)."""

    def __init__(self, config, *, tele=None, store_dir: str = ""):
        self.enabled = bool(getattr(config, "guard", False))
        self.spec = None
        if self.enabled:
            self.spec = GuardSpec(
                zscore=config.guard_zscore,
                skip=poison.parse_skip_steps(config.skip_steps))
        self.anomaly_exit = int(getattr(config, "anomaly_exit", 0))
        self.skip_str = getattr(config, "skip_steps", "")
        self.tele = tele
        self.store_dir = store_dir
        self.fingerprint: float | None = None   # latest epoch-boundary value
        self.last = None                        # latest host GuardState
        self._base_anoms = 0                    # attempt-start anomaly counter
        self._base_first = -1                   # attempt-start first-anomaly step
        self._prev_anoms = 0                    # previous SAVE's counter (stamp)
        self._attempt_lo: int | None = None     # first NEW anomaly's lower bound

    def baseline(self, state) -> None:
        """Call once after (a possible) resume: the restored checkpoint's
        counters are this attempt's zero point — a rolled-back run must not be
        poisoned by the history its clean checkpoint already absorbed."""
        if not self.enabled:
            return
        gh = jax.device_get(state.guard)
        self._base_anoms = self._prev_anoms = int(gh.anomalies)
        self._base_first = int(gh.first_anomaly_step)

    def epoch_end(self, state, epoch: int, steps: int) -> dict | None:
        """The per-epoch boundary: fetch the carry, emit telemetry, compute
        the fingerprint. Returns the health stamp for ``save_versioned`` (None
        when the guard is off — legacy unstamped manifest entries)."""
        if not self.enabled:
            return None
        gh = jax.device_get(state.guard)
        self.last = gh
        self.fingerprint = T.param_fingerprint(state.params)
        if self.tele is not None and self.tele.enabled:
            self.tele.emit(T.anomaly_event(epoch, gh, steps,
                                           fingerprint=self.fingerprint,
                                           skip=self.skip_str))
        anoms = int(gh.anomalies)
        if anoms > self._prev_anoms and self._attempt_lo is None:
            # First epoch of THIS attempt with a fresh anomaly: pin the skip
            # window's lower bound. first_anomaly_step is exact when it was
            # set this attempt; a stale value (carried by a clean checkpoint
            # from already-skipped history) falls back to the epoch's start
            # step — a slightly wider window, never a hole.
            first = int(gh.first_anomaly_step)
            if first >= 0 and first != self._base_first:
                self._attempt_lo = first
            else:
                self._attempt_lo = max(int(state.step) - int(steps), 0)
        stamp = {"clean": anoms == self._prev_anoms, "anomalies": anoms,
                 "skipped": int(gh.skipped), "step": int(state.step),
                 "fingerprint": self.fingerprint}
        self._prev_anoms = anoms
        return stamp

    def check_poisoned(self, state) -> None:
        """Enforce ``--anomaly-exit`` at the epoch boundary, AFTER this
        epoch's (unclean-stamped) checkpoint is durable: write the poison
        marker naming the anomaly step window and raise :class:`Poisoned`
        (``__main__`` converts to ``SystemExit(EXIT_POISONED)``). The window
        spans this ATTEMPT's anomalies: exact when ``first_anomaly_step`` was
        set this attempt, bounded by the first offending epoch's start step
        when a clean checkpoint carried older (already-skipped) history — a
        wider window is safe (the oracle uses the same skip set), a hole
        would re-poison the replay."""
        if not self.enabled or not self.anomaly_exit or self.last is None:
            return
        gh = self.last
        if int(gh.anomalies) - self._base_anoms < self.anomaly_exit:
            return
        last = int(gh.last_anomaly_step)
        first = last if self._attempt_lo is None else min(self._attempt_lo,
                                                          last)
        window = (first, last + 1)
        if self.store_dir and M.is_logging_process():
            poison.write_marker(self.store_dir, window=window,
                                step=int(state.step),
                                anomalies=int(gh.anomalies))
        raise poison.Poisoned(int(state.step), window)
