"""Connectivity smoke test — the reference ``src/run1.py``/``src/run2.py`` analog.

The reference validates its cluster before training by sending a 1-element tensor rank0→rank1
over gloo and printing it on both sides (reference ``src/run1.py:8-17``; SURVEY.md §3.3). The
TPU-native equivalent: join the cluster (rendezvous ≙ ``init_process_group``), build the
mesh, and run one ``ppermute`` ring rotation — every device's value must arrive at its
neighbor, exercising rendezvous + ICI/DCN p2p in one shot. One launcher for every host
(no per-machine rank-edited files — the rank hardcoding at ``src/run1.py:31`` vs
``src/run2.py:31`` is exactly what this replaces).

Run: ``python -m csed_514_project_distributed_training_using_pytorch_tpu.train.smoke``
(identical command on every host of a fleet).
"""

from __future__ import annotations

import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.collectives import (
    ring_pass,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
    initialize_cluster, make_mesh,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics as M


def main(num_devices: int | None = None) -> bool:
    """Returns True iff the ring pass delivered every value to its neighbor."""
    info = initialize_cluster()
    mesh = make_mesh(num_devices)
    n = mesh.shape["data"]
    M.log(f"smoke: {info.process_count} process(es), {n}-device mesh {mesh.devices.ravel()}")

    values = np.arange(n, dtype=np.float32)       # device i holds value i (≙ the tensor
    rotated = ring_pass(mesh, dp.put_global(mesh, values, P("data")))  # rank0 sends, run1.py:13)
    # The result is sharded across every process's devices; allgather so each host can
    # print/verify the full ring (a plain np.asarray would see non-addressable shards).
    got = np.asarray(multihost_utils.process_allgather(rotated, tiled=True))
    want = np.roll(values, 1)

    ok = bool(np.array_equal(got, want))
    for i in range(n):                            # ≙ 'Rank k has data tensor(1.)', run1.py:17
        M.log(f"Device {i} has data {got[i]:.1f} (expected {want[i]:.1f})")
    M.log(f"smoke: {'OK — rendezvous + ring p2p verified' if ok else 'FAILED'}")
    return ok


if __name__ == "__main__":
    raise SystemExit(0 if main() else 1)
