"""Training drivers (the reference's L4/L5 layers): jit-compiled step/epoch functions plus the
three entry points — single-process (reference ``src/train.py``), distributed
(``src/train_dist.py``), and the connectivity smoke test (``src/run1.py``/``src/run2.py``).

Lazy exports (PEP 562), same pattern as ``serving/__init__``: ``train.step``
imports jax at module scope, but the backend-free fleet side (``serving/
router.py``, ``resilience/supervisor.py``) imports ``train.launch.Fleet`` —
pure stdlib process plumbing — and executing this ``__init__`` is part of that
import. An eager ``from .step import ...`` here made every ``train.*`` import
reach jax transitively, which graftlint's backend-purity checker caught when
it first ran; the attribute shim below keeps ``train.TrainState`` working
while charging jax's import only to the trainers that touch it.
"""

from __future__ import annotations

_STEP_EXPORTS = (
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_epoch_fn",
    "make_eval_fn",
)

__all__ = list(_STEP_EXPORTS)


def __getattr__(name: str):
    if name in _STEP_EXPORTS:
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step,
        )

        value = getattr(step, name)
        globals()[name] = value      # cache: subsequent lookups skip __getattr__
        return value
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
