"""Training drivers (the reference's L4/L5 layers): jit-compiled step/epoch functions plus the
three entry points — single-process (reference ``src/train.py``), distributed
(``src/train_dist.py``), and the connectivity smoke test (``src/run1.py``/``src/run2.py``)."""

from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    TrainState,
    create_train_state,
    make_train_step,
    make_epoch_fn,
    make_eval_fn,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_epoch_fn",
    "make_eval_fn",
]
