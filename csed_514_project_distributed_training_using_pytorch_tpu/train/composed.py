"""Composed-parallelism trainer: one CLI over an arbitrary named device mesh.

Beyond-parity user surface (the reference's only distributed mode is DP —
``src/train_dist.py``; the DP-parity trainer is ``train/distributed.py``): train the
transformer family with any combination of

- ``data``  — batch sharding + compiler-inserted gradient all-reduce (DP),
- ``seq``   — sequence/context parallelism over a sequence-sharded axis: ring attention
  (``parallel/ring_attention.py``, the default) or the head-scatter all-to-all schedule
  (``--seq-impl ulysses``, ``parallel/ulysses.py``),
- ``model`` — Megatron column/row weight sharding (TP, ``parallel/tensor_parallel.py``),
- ``expert`` — Switch MoE blocks with expert-sharded weights (EP,
  ``parallel/expert_parallel.py``; the axis size sets the expert count, and the
  load-balance aux loss flows into the objective via ``make_train_step``),

declared as one ``--mesh`` string, e.g. ``--mesh data=2,seq=2,model=2`` on 8 devices.
Axes of size 1 are legal (``--mesh data=8`` is plain DP). Everything else is the
standard machinery: same TrainState, same checkpoint format (interchangeable with the
unsharded trainers — pinned in tests), same metric lines.

- ``stage`` — GPipe pipeline parallelism over the transformer's block stack (PP,
  ``parallel/pipeline.py``): the run trains in the stage-stacked parameter layout
  (each device holds only its stages' layers) and the checkpoint bridge
  (``stack_transformer_blocks``/``unstack_transformer_blocks``) converts to/from the
  standard per-name layout at the boundary, so PP checkpoints interchange with every
  other mesh. Composes with ``data`` (``--mesh data=2,stage=2``) and with ``model``
  (``--mesh data=2,stage=2,model=2`` — the pipeline keeps stage/data manual and the
  model axis AUTO, so Megatron TP annotations still apply inside each stage) and
  with ``--flash-attention`` (the dispatcher's pallas kernel traces inside the
  pipeline body); ``seq``/``expert`` with ``stage`` would need nested shard_maps
  and are rejected up front.

This is deliberately a thin composition of the parallel/ primitives: the entire
"strategy" is the mesh declaration plus sharding rules; XLA inserts every collective.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    download_mnist, load_mnist, mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    validate_remat_policy,
    TransformerClassifier,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
    parse_mesh_spec,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    initialize_cluster,
    make_mesh,
    make_ring_attention_fn,
    make_ulysses_attention_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    pipeline,
)
from csed_514_project_distributed_training_using_pytorch_tpu import resilience
from csed_514_project_distributed_training_using_pytorch_tpu.ops import optim
from csed_514_project_distributed_training_using_pytorch_tpu.train.guard import (
    GuardRuntime,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    tensor_parallel as tp,
)
from jax.sharding import PartitionSpec as P
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    TrainState,
    create_train_state,
    make_epoch_fn,
    make_eval_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint
from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics as M
from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
    ComposedConfig, parse_config,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.profiling import (
    maybe_profile,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    telemetry as T,
)

def main(config: ComposedConfig = ComposedConfig(), *,
         datasets=None) -> tuple[TrainState, M.MetricsHistory]:
    """Run composed-mesh training; returns final (host-resident) state + history."""
    watch = M.Stopwatch()
    run_plan, plan_events = None, []
    if config.plan:
        # Resolve BEFORE the mesh spec is read: the plan rewrites mesh/fsdp/
        # grad_accum/pipeline_microbatches on the (frozen) config. Deterministic
        # across processes for auto/file; tune degrades to auto on a fleet.
        # Autotune trial events buffer until the telemetry writer exists below.
        from csed_514_project_distributed_training_using_pytorch_tpu import (
            plan as plan_mod,
        )
        initialize_cluster()     # idempotent; planning needs the global topology
        config, run_plan = plan_mod.apply_plan(config, "composed",
                                               emit=plan_events.append)
    axis_names, axis_sizes = parse_mesh_spec(config.mesh)
    if config.kv_heads and (
            config.kv_heads < 0
            or TransformerClassifier.num_heads % config.kv_heads):
        raise ValueError(f"--kv-heads {config.kv_heads} must be a positive divisor "
                         f"of the transformer's {TransformerClassifier.num_heads} "
                         f"heads")
    # r4: sliding windows compose with EVERY attention schedule — einsum ring,
    # ring-of-flash (static hop offsets, truncated ring), einsum zig-zag
    # (global-position chunk masks), flash zig-zag (traced SMEM-scalar offsets),
    # and ulysses (full sequence local). Only the width itself needs validating.
    if config.attention_window:
        from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
            validate_window,
        )
        validate_window(config.attention_window)
    n_mesh_devices = int(np.prod(axis_sizes))
    info = initialize_cluster()   # no-op single-process; multi-host rendezvous otherwise

    if config.download_data and datasets is None:
        download_mnist(config.data_dir)
    train_ds, test_ds = datasets if datasets is not None else load_mnist(config.data_dir)
    train_ds = mnist.truncate(train_ds, config.max_train_examples)
    test_ds = mnist.truncate(test_ds, config.max_test_examples)

    if config.dcn_data:
        # Multi-slice layout: the data axis's leading factor (one per slice/granule)
        # is the ONLY mesh dimension whose collectives cross DCN; everything else
        # rides ICI. Virtual granules let this compile/run on single-slice or CPU
        # platforms (the dryrun exercises it at 8 virtual devices).
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            make_hybrid_mesh,
        )
        if "data" not in axis_names:
            raise ValueError("--dcn-data needs a data axis in --mesh (it is the "
                             "axis whose leading factor spans slices)")
        mesh = make_hybrid_mesh(axis_names, axis_sizes, dcn_axis="data",
                                num_slices=config.dcn_data,
                                devices=jax.devices()[:n_mesh_devices])
    else:
        mesh = make_mesh(n_mesh_devices, axis_names=axis_names,
                         axis_shape=axis_sizes)
    if config.health_stats and not config.telemetry:
        raise ValueError("--health-stats emits telemetry 'health' events and has no "
                         "other output — pass --telemetry PATH too")
    tele = T.TelemetryWriter(config.telemetry,
                             preserve=bool(config.resume_from))
    tele.emit(T.manifest_event(config, mesh=mesh, run_type="composed"))
    if run_plan is not None:
        tele.emit(T.plan_event(run_plan))
        for ev in plan_events:
            tele.emit(ev)
    # Resilience wiring (flag-gated, host-side only — zero-cost when off).
    rt = resilience.RunHooks(heartbeat_dir=config.heartbeat_dir,
                             handle_preemption=config.handle_preemption,
                             process_index=info.process_index)
    # Numerical immune system (--guard): in-step verdict + identity update;
    # host side is epoch-boundary bookkeeping only.
    grt = GuardRuntime(config, tele=tele,
                       store_dir=os.path.join(config.results_dir, "checkpoints")
                       if config.results_dir else "")
    data_size = mesh.shape.get("data", 1)
    seq_size = mesh.shape.get("seq", 1)
    model_size = mesh.shape.get("model", 1)
    expert_size = mesh.shape.get("expert", 1)
    stage_size = mesh.shape.get("stage", 1)
    if config.batch_size % max(data_size, 1):
        raise ValueError(f"batch {config.batch_size} not divisible by data axis "
                         f"{data_size}")
    if config.grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {config.grad_accum}")
    validate_remat_policy(config.remat, config.remat_policy)
    if config.batch_size % config.grad_accum:
        raise ValueError(f"batch {config.batch_size} not divisible by grad_accum "
                         f"{config.grad_accum}")
    if (config.grad_accum > 1
            and (config.batch_size // config.grad_accum) % max(data_size, 1)):
        # Same fail-fast as train/distributed.py: an indivisible microbatch would make
        # GSPMD silently reshard inside the hot program, defeating DP scaling.
        raise ValueError(
            f"microbatch {config.batch_size // config.grad_accum} "
            f"(batch/grad_accum) not divisible by data axis {data_size} — each "
            f"microbatch must still shard evenly")
    if stage_size > 1:
        # r5: ``model`` composes with ``stage`` — the pipeline's shard_map keeps
        # only stage/data manual and leaves the model axis AUTO, so the Megatron
        # annotations still drive compiler-inserted TP collectives inside each
        # stage (parallel/pipeline.py). seq/expert stay rejected: their schedules
        # are shard_maps of their own and genuinely would need nesting.
        if seq_size > 1 or expert_size > 1:
            raise ValueError(
                "a stage axis composes with data and model only — seq/expert "
                "inside a pipeline stage would need nested shard_maps")
        if config.dropout_rate:
            raise ValueError("stage pipelining requires dropout_rate == 0 "
                             "(microbatch ticks do not thread dropout keys)")
        if config.remat:
            raise ValueError("--remat has no effect under a stage axis (the pipeline "
                             "engine applies blocks itself) — drop it")
        if config.zigzag_attention:
            raise ValueError(
                "--zigzag-attention needs a seq axis, which does not compose with "
                "a stage axis")
        if config.fsdp:
            raise ValueError(
                "--fsdp does not compose with a stage axis: the pipeline's "
                "shard_map keeps the data axis MANUAL, which conflicts with "
                "ZeRO's data-axis weight sharding")
        if config.flash_attention and model_size > 1:
            raise ValueError(
                "--flash-attention under stage x model is unsupported: the flash "
                "pallas_call cannot be partitioned by the AUTO model axis inside "
                "the pipeline body (drop model or flash)")
        if config.sharded_checkpoint:
            raise ValueError(
                "--sharded-checkpoint saves the device state's own layout, and the "
                "stage axis trains in the stacked layout — its shard keys would not "
                "interchange; use the default full-state checkpoint with stages")
        # The engine sees batch_size // grad_accum per call (the accumulation path
        # feeds microbatches), so the pipeline divisibility guards must use that.
        step_batch = config.batch_size // config.grad_accum
        if step_batch % config.pipeline_microbatches:
            raise ValueError(
                f"per-call batch {step_batch} (batch/grad_accum) not divisible by "
                f"{config.pipeline_microbatches} pipeline microbatches")
        if (step_batch // config.pipeline_microbatches) % data_size:
            raise ValueError(
                f"pipeline microbatch {step_batch // config.pipeline_microbatches} "
                f"not divisible by data axis {data_size}")
        if config.batch_size_test % config.pipeline_microbatches:
            raise ValueError(
                f"test batch {config.batch_size_test} not divisible by "
                f"{config.pipeline_microbatches} pipeline microbatches")

    attention_fn = None
    if config.seq_impl not in ("ring", "ulysses"):
        raise ValueError(
            f"--seq-impl must be 'ring' or 'ulysses', got {config.seq_impl!r}")
    if config.seq_impl == "ulysses" and config.zigzag_attention:
        raise ValueError("--zigzag-attention is a ring schedule — it does not "
                         "compose with --seq-impl ulysses")
    if config.seq_impl == "ulysses" and seq_size > 1:
        # Head-scatter all-to-all SP (parallel/ulysses.py); the wrapper enforces
        # seq_len/head divisibility with actionable messages. --flash-attention
        # selects the flash kernel as the full-sequence local op. Without a seq axis
        # the impl choice is moot and the flash/dense chain below applies unchanged.
        attention_fn = make_ulysses_attention_fn(
            mesh, use_flash=config.flash_attention,
            window=config.attention_window)
    elif config.zigzag_attention:
        if not config.causal:
            raise ValueError("--zigzag-attention is causal-only — add --causal")
        if "seq" not in mesh.shape:
            raise ValueError("--zigzag-attention needs a seq axis in --mesh")
        if config.flash_attention:
            # Both flags: the full long-context causal composition — zig-zag load
            # balance across chips, flash kernels within each live chunk pair.
            from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
                pallas_attention as pa,
            )
            chunk = 2 * max(seq_size, 1) * pa.BLOCK
            if config.seq_len % chunk:
                raise ValueError(
                    f"--zigzag-attention --flash-attention needs seq_len divisible "
                    f"by 2·seq_axis·BLOCK = {chunk}, got {config.seq_len} "
                    f"(e.g. --seq-len {chunk})")
            attention_fn = make_ring_attention_fn(
                mesh, use_flash=True, use_zigzag=True,
                window=config.attention_window)
        else:
            if config.seq_len % (2 * max(seq_size, 1)):
                raise ValueError(
                    f"--zigzag-attention needs seq_len divisible by 2·seq_axis = "
                    f"{2 * max(seq_size, 1)}, got {config.seq_len}")
            attention_fn = make_ring_attention_fn(
                mesh, use_zigzag=True, window=config.attention_window)
    elif config.flash_attention:
        from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
            pallas_attention as pa,
        )
        if config.seq_len % (max(seq_size, 1) * pa.BLOCK):
            raise ValueError(
                f"--flash-attention needs seq_len divisible by "
                f"seq_axis·BLOCK = {max(seq_size, 1)}·{pa.BLOCK}, got "
                f"{config.seq_len} (e.g. --seq-len {max(seq_size, 1) * pa.BLOCK})")
        # Ring-of-flash under a seq axis (flash kernels on every hop, trainable custom
        # VJP); the measured-crossover dispatcher otherwise (dense below
        # FLASH_MIN_SEQ, flash at and above — the flag can never regress throughput;
        # windowed/banded when requested).
        if seq_size > 1:
            attention_fn = make_ring_attention_fn(
                mesh, use_flash=True, window=config.attention_window)
        elif config.attention_window:
            import functools
            attention_fn = functools.partial(
                pa.dispatch_attention, window=config.attention_window)
        else:
            attention_fn = pa.dispatch_attention
    elif seq_size > 1:
        # Plain einsum ring; --attention-window binds the sliding band into the
        # hop schedule (windowed context parallelism — out-of-band hops skip).
        attention_fn = make_ring_attention_fn(mesh,
                                              window=config.attention_window)
    elif config.attention_window:
        from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
            windowed_attention_fn,
        )
        attention_fn = windowed_attention_fn(config.attention_window)
    model_kwargs = {"dropout_rate": config.dropout_rate,
                    "seq_len": config.seq_len,
                    "dtype": jnp.bfloat16 if config.bf16 else jnp.float32,
                    "remat": config.remat,
                    "remat_policy": config.remat_policy,
                    "causal": config.causal}
    if config.kv_heads:
        model_kwargs["num_kv_heads"] = config.kv_heads
    if config.rope:
        model_kwargs["rope"] = True
    if attention_fn is not None:
        model_kwargs["attention_fn"] = attention_fn
    if not 1 <= config.moe_top_k <= max(expert_size, 1):
        raise ValueError(f"--moe-top-k must be in [1, expert axis size], got "
                         f"{config.moe_top_k} with expert={expert_size}")
    if expert_size > 1:
        model_kwargs["num_experts"] = expert_size
        model_kwargs["expert_mesh"] = mesh
        model_kwargs["expert_top_k"] = config.moe_top_k
    model = TransformerClassifier(**model_kwargs)
    if seq_size > 1 and model.seq_len % seq_size:
        raise ValueError(f"model seq_len {model.seq_len} not divisible by seq axis "
                         f"{seq_size}")

    M.log(f"Composed training: mesh "
          f"{dict(zip(axis_names, axis_sizes))} over {n_mesh_devices} devices "
          f"on {info.process_count} process(es), "
          f"batch {config.batch_size}, data source: {train_ds.source}")

    rep = dp.replicated(mesh)
    n_train, n_test = len(train_ds), len(test_ds)
    steps_per_epoch = n_train // config.batch_size
    if steps_per_epoch == 0:
        raise ValueError(f"batch {config.batch_size} larger than the train split "
                         f"({n_train} examples) — nothing to step")
    optimizer = optim.make_optimizer(config.optimizer,
                                     learning_rate=config.learning_rate,
                                     momentum=config.momentum,
                                     weight_decay=config.weight_decay)
    base_state = create_train_state(model, jax.random.PRNGKey(config.seed),
                                    optimizer=optimizer,
                                    ema=config.ema_decay > 0,
                                    guard=config.guard)
    lr_schedule = optim.make_lr_schedule(config.lr_schedule,
                                         warmup_steps=config.warmup_steps,
                                         total_steps=config.epochs * steps_per_epoch)
    start_epoch = 0
    if config.resume_from:
        # Checkpoints are always in the standard per-name layout, so a composed run
        # resumes from ANY mesh's checkpoint — including across stage layouts (the
        # bridge below re-stacks).
        base_state, start_epoch, warning = checkpoint.restore_for_resume(
            config.resume_from, base_state,
            process_index=info.process_index, process_count=info.process_count,
            steps_per_epoch=steps_per_epoch, tele=tele)
        if warning:
            M.log(f"WARNING: {warning}")
        M.log(f"Resumed from {config.resume_from} at step {int(base_state.step)} "
              f"(starting epoch {start_epoch})")
        # Manifest cursor cross-check (DESIGN.md §26): the checkpoint's stamped
        # data position must agree with the derived start epoch.
        note = checkpoint.check_cursor_resume(config.resume_from,
                                              seed=config.seed,
                                              step=int(base_state.step),
                                              start_epoch=start_epoch)
        if note:
            M.log(f"WARNING: {note}")
    grt.baseline(base_state)    # this attempt's anomaly-counter zero point
    # Whole epochs run as ONE compiled scan under the composed shardings (same program
    # structure as train/distributed.py): per-step Python dispatch — an index-plan
    # upload, an on-device gather, a reshard, a step call — dominates at this model
    # size (SURVEY.md §7e), and previously made this trainer an order of magnitude
    # slower than the DP trainer it shares a flag surface with (r2 verdict, weak #3).
    if stage_size > 1:
        # PP path: train in the stage-stacked layout (each device holds only its
        # stages' layers); same init values via the checkpoint bridge, restored to the
        # standard per-name layout at the end.
        engine = pipeline.PipelinedClassifier(
            model, mesh, num_microbatches=config.pipeline_microbatches,
            batch_axis="data" if data_size > 1 else None,
            schedule=config.pipeline_schedule)
        def to_stacked(tree):
            stacked, rest = pipeline.stack_transformer_blocks(tree, model.num_layers)
            return {"blocks": stacked, "rest": rest}

        # The optimizer state bridges per params-congruent subtree (AdamW stacks each
        # moment tree like the params; SGD velocity IS one such tree).
        stacked_state = TrainState(to_stacked(base_state.params),
                                   optim.map_param_trees(base_state.velocity,
                                                         to_stacked),
                                   base_state.step,
                                   to_stacked(base_state.ema)
                                   if base_state.ema is not None else None,
                                   base_state.guard)   # scalars pass through
        state_sh = pipeline.stacked_state_shardings(mesh, stacked_state)
        state = jax.device_put(stacked_state, state_sh)
        idx_sh = (jax.sharding.NamedSharding(mesh, P(None, "data"))
                  if data_size > 1 else rep)
        epoch_fn = jax.jit(
            make_epoch_fn(engine, learning_rate=config.learning_rate,
                          momentum=config.momentum,
                          grad_accum=config.grad_accum, optimizer=optimizer,
                          lr_schedule=lr_schedule,
                          clip_grad_norm=config.clip_grad_norm,
                          ema_decay=config.ema_decay,
                          label_smoothing=config.label_smoothing,
                          health=config.health_stats, guard=grt.spec),
            in_shardings=(state_sh, rep, rep, idx_sh, rep),
            out_shardings=(state_sh, rep), donate_argnums=(0,))
        param_shardings = state_sh.params
        # Eval batches stay replicated (the reference's every-rank-evaluates
        # semantics), so the eval engine pipelines without data-sharded microbatches.
        eval_model = pipeline.PipelinedClassifier(
            model, mesh, num_microbatches=config.pipeline_microbatches,
            batch_axis=None, schedule=config.pipeline_schedule)
    else:
        epoch_body = make_epoch_fn(model, learning_rate=config.learning_rate,
                                   momentum=config.momentum,
                                   grad_accum=config.grad_accum,
                                   optimizer=optimizer,
                                   lr_schedule=lr_schedule,
                                   clip_grad_norm=config.clip_grad_norm,
                                   ema_decay=config.ema_decay,
                                   label_smoothing=config.label_smoothing,
                                   health=config.health_stats, guard=grt.spec)
        if config.fsdp:
            # ZeRO x TP hybrid (r5): params + optimizer state shard over BOTH the
            # data axis (largest free dim) and the Megatron model axis — memory
            # divides by data_size x model_size (parallel/fsdp.py).
            from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
                fsdp,
            )
            state_sh = fsdp.hybrid_state_shardings(mesh, base_state)
            state = jax.device_put(base_state, state_sh)
            epoch_fn = fsdp.compile_epoch_hybrid(
                epoch_body, mesh, data_axis="data" if data_size > 1 else None)
            param_shardings = state_sh.params
        else:
            state = tp.shard_train_state(mesh, base_state)
            epoch_fn = tp.compile_epoch_tp(
                epoch_body, mesh, data_axis="data" if data_size > 1 else None)
            param_shardings = tp.state_shardings(mesh, state).params
        eval_model = model
    # Eval consumes the sharded params in place (no host gather — multi-host safe);
    # sums/counts come back replicated, which every process can read.
    eval_fn = jax.jit(make_eval_fn(eval_model, batch_size=config.batch_size_test),
                      in_shardings=(param_shardings, rep, rep),
                      out_shardings=(rep, rep))

    # Every process holds the identical dataset (pure function of the seed / the same
    # files) and derives the identical permutation — the same contract parallel/sampler
    # documents. The split uploads ONCE, replicated; per-step batches are on-device
    # gathers (only the 64-int index plan crosses the host boundary each step).
    train_x = dp.put_global(mesh, train_ds.images, P())
    train_y = dp.put_global(mesh, train_ds.labels, P())
    test_x = dp.put_global(mesh, test_ds.images, P())
    test_y = dp.put_global(mesh, test_ds.labels, P())
    history = M.MetricsHistory()
    saver = checkpoint.make_saver(config.async_checkpoint, tele=tele)
    plan_spec = P(None, "data") if data_size > 1 else P()
    # One dropout key for the whole run, hoisted out of the loop (each step folds it
    # with state.step inside the compiled program — same per-step keys as before).
    dropout_rng = jax.random.PRNGKey(config.seed + 1)
    # Replicate shards on device (all-gather), then fetch — device_get on a sharded
    # array would fail on a multi-host fleet where no process addresses every shard.
    gather = dp.gather_replicated(mesh)

    def to_host_standard(state) -> TrainState:
        """Gathered host copy in the standard per-name checkpoint layout (the
        interchange contract with every other mesh — stage layouts bridge back)."""
        host_state = jax.device_get(gather(state))
        if stage_size > 1:
            unstack = lambda t: pipeline.unstack_transformer_blocks(t["blocks"],
                                                                    t["rest"])
            host_state = TrainState(
                unstack(host_state.params),
                optim.map_param_trees(host_state.velocity, unstack),
                host_state.step,
                unstack(host_state.ema)
                if host_state.ema is not None else None,
                host_state.guard)      # scalars pass through the bridge
        return host_state

    ckpt_path = (os.path.join(config.results_dir, "model_composed.ckpt")
                 if config.results_dir else "")
    if ckpt_path:
        os.makedirs(config.results_dir, exist_ok=True)

    # Compile/execute split (telemetry): AOT-compile + FLOP-price the epoch program
    # (stage/jit path; the TP/FSDP cached-sharding wrappers have no .lower —
    # compile_s stays null and folds into the first epoch's wall clock).
    # Gated on the CONFIG flag, not tele.enabled: every process must take the same
    # compile path (AOT-compiled vs jit) on a multi-host fleet.
    compile_s = flops_per_step = None
    if config.telemetry:
        plan_struct = jax.ShapeDtypeStruct(
            (steps_per_epoch, config.batch_size), np.int32)
        compiled, aot = T.aot_compile(epoch_fn, state, train_x, train_y,
                                      plan_struct, dropout_rng)
        if compiled is not None:
            epoch_fn = compiled
            compile_s = aot["lower_s"] + aot["compile_s"]
            if aot["flops"]:
                flops_per_step = aot["flops"] / steps_per_epoch
            tele.emit(T.compile_event("epoch", aot,
                                      steps_per_call=steps_per_epoch))

    try:
        host_state = _run_epochs(
            config, state, mesh, epoch_fn, eval_fn, train_x, train_y, test_x,
            test_y, dropout_rng, plan_spec, n_train, n_test, steps_per_epoch,
            start_epoch, history, watch, saver, ckpt_path, to_host_standard,
            tele, compile_s, flops_per_step, rt, grt)
    finally:
        # Drain the write-behind queue even on an exception/signal/preemption
        # mid-run — the queued per-epoch checkpoint is the resume artifact a killed
        # run needs, and flush() re-raises deferred background IO errors. The
        # preemption latch is uninstalled so in-process callers get their signal
        # semantics back.
        rt.uninstall()
        saver.flush()
    if ckpt_path:
        M.log(f"Saved {ckpt_path}")
    if config.results_dir:
        M.save_metrics_jsonl(history,
                             os.path.join(config.results_dir, "metrics.jsonl"))
    return host_state, history


def _run_epochs(config, state, mesh, epoch_fn, eval_fn, train_x, train_y, test_x,
                test_y, dropout_rng, plan_spec, n_train, n_test, steps_per_epoch,
                start_epoch, history, watch, saver, ckpt_path, to_host_standard,
                tele, compile_s, flops_per_step, rt, grt=None):
    """The composed trainer's epoch loop, split out so the caller can guarantee the
    async-checkpoint flush in a ``finally`` regardless of where the loop fails."""
    host_state = None
    best_step_s = None
    ckpt_store = (os.path.join(config.results_dir, "checkpoints")
                  if config.results_dir else "")
    with maybe_profile(config.profile, config.profile_dir):
        for epoch in range(start_epoch, config.epochs):
            # heartbeat (with the previous boundary's param fingerprint)
            # + armed faults; no-op off
            rt.epoch_tick(state, epoch,
                          fingerprint=grt.fingerprint if grt else None)
            t_epoch = time.perf_counter()
            # (seed, epoch)-keyed permutation — a pure function, so a resumed run
            # replays exactly the epochs it missed (same contract as
            # parallel/sampler.py's global_permutation).
            perm = np.random.default_rng(
                np.random.SeedSequence([config.seed, epoch])).permutation(n_train)
            plan = dp.put_global(
                mesh,
                perm[:steps_per_epoch * config.batch_size].astype(np.int32)
                .reshape(steps_per_epoch, config.batch_size), plan_spec)
            data_s = time.perf_counter() - t_epoch
            t_exec = time.perf_counter()
            state, out = epoch_fn(state, train_x, train_y, plan, dropout_rng)
            losses, epoch_health = (out if config.health_stats else (out, None))
            jax.block_until_ready(state.params)
            epoch_loss = float(np.asarray(jax.device_get(losses)).mean())
            execute_s = time.perf_counter() - t_exec
            t_eval = time.perf_counter()
            eval_params = state.ema if state.ema is not None else state.params
            sum_nll, correct = jax.device_get(eval_fn(eval_params, test_x, test_y))
            eval_s = time.perf_counter() - t_eval
            examples_trained = (epoch + 1) * steps_per_epoch * config.batch_size
            history.record_train(examples_trained, epoch_loss)
            history.record_test(examples_trained, float(sum_nll) / n_test)
            M.log(f"Epoch {epoch}: train_loss: {epoch_loss:.4f}, "
                  f"val_loss: {float(sum_nll) / n_test:.4f}, "
                  f"accuracy: {int(correct) / n_test:.4f}, "
                  f"time_elapsed: {watch.elapsed():.2f}s")
            if epoch_health is not None:
                # SPMD-entered by every process (the norm program would deadlock
                # a fleet if only process 0 ran it); emission below stays
                # process-0 gated.
                health_host = jax.device_get(epoch_health)
                param_norm = T.global_l2_norm(state.params)
            if tele.enabled:
                step_s = execute_s / steps_per_epoch if steps_per_epoch else None
                if step_s and (best_step_s is None or step_s < best_step_s):
                    best_step_s = step_s
                tele.emit(T.epoch_event(
                    epoch, examples=steps_per_epoch * config.batch_size,
                    steps=steps_per_epoch, wall_s=time.perf_counter() - t_epoch,
                    execute_s=execute_s, eval_s=eval_s, data_s=data_s,
                    compile_s=compile_s, flops_per_step=flops_per_step,
                    train_loss=epoch_loss, val_loss=float(sum_nll) / n_test,
                    mfu=T.estimate_mfu(flops_per_step, step_s)["mfu"]))
                if epoch_health is not None:
                    tele.emit(T.health_event(epoch, health_host, steps_per_epoch,
                                             param_norm=param_norm))
            # Guard boundary: anomaly verdict fetch + event + cross-replica
            # fingerprint, then the manifest health stamp for the save.
            stamp = (grt.epoch_end(state, epoch, steps_per_epoch)
                     if grt else None)
            # Per-epoch full-state checkpoint (standard layout, process-0 gated,
            # atomic) so a killed run resumes with --resume-from on ANY mesh. The
            # final epoch's host copy doubles as the return value — no second
            # gather/save after the loop.
            if ckpt_path:
                if config.sharded_checkpoint:
                    # Distributed writer: every process saves only the shards it
                    # addresses, straight from device — no all-gather, no host copy
                    # of the full state on any single process.
                    checkpoint.save_train_state_sharded(ckpt_path + ".sharded",
                                                        state)
                host_state = to_host_standard(state)
                saver.save_train_state(ckpt_path, host_state)
                if ckpt_store and config.keep_checkpoints:
                    # Versioned store (manifest + checksums + keep-last-N GC) for
                    # the supervisor's newest-HEALTHY resume scan.
                    checkpoint.save_versioned(
                        ckpt_store, host_state, keep=config.keep_checkpoints,
                        tele=tele, health=stamp,
                        # The manifest's data cursor: the (seed, epoch)-pure
                        # permutation's resume anchor (DESIGN.md §26).
                        cursor={"version": 1, "kind": "epoch",
                                "seed": config.seed, "epoch": epoch + 1,
                                "batch": 0, "step": int(host_state.step)})
            # Anomaly policy AFTER the stamped checkpoint is durable (raises
            # Poisoned; __main__ exits 65).
            if grt:
                grt.check_poisoned(state)
            # Cooperative preemption at the epoch boundary, with this epoch's
            # checkpoint durable (raises Preempted; __main__ exits 75).
            rt.check_preempt(epoch=epoch, state=state, checkpoint=ckpt_path,
                             tele=tele)

    if tele.enabled and best_step_s is not None:
        tele.emit(T.mfu_event(flops_per_step, best_step_s))
    if host_state is None:      # no results_dir, or the resume skipped every epoch
        host_state = to_host_standard(state)
        if ckpt_path:           # zero-epoch resume must still leave a checkpoint
            saver.save_train_state(ckpt_path, host_state)
    return host_state


if __name__ == "__main__":
    try:
        main(parse_config(ComposedConfig))
    except resilience.Preempted as e:
        M.log(f"preempted at step {e.step} (checkpoint {e.checkpoint or 'n/a'}); "
              f"exiting {resilience.EXIT_PREEMPTED} — resume with --resume-from")
        raise SystemExit(resilience.EXIT_PREEMPTED)
    except resilience.Poisoned as e:
        M.log(f"poisoned at step {e.step} (anomaly window "
              f"{e.window[0]}:{e.window[1]}); exiting "
              f"{resilience.EXIT_POISONED} — the supervisor rolls back to the "
              f"newest healthy checkpoint and skips the window")
        raise SystemExit(resilience.EXIT_POISONED)
