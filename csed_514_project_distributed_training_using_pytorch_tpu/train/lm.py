"""Autoregressive pixel-LM trainer: next-token training + on-device generation.

Beyond-parity surface (the reference trains one classifier and has no language model,
reference ``src/model.py:4-22``): teacher-forced next-token training of
``models/lm.py::TransformerLM`` over quantized MNIST pixel streams, data-parallel over
every addressable device, with the same machinery as the other trainers — scanned-epoch
compiled programs (``train/step.py``), the optimizer/schedule/clipping stack
(``ops/optim.py``), per-epoch checkpoints with ``--resume-from``, and the metric-line +
loss-curve conventions. After training it samples digits with the KV-cache decoder
(``models/lm.py::generate``) and saves them as an image grid — the generation path is a
first-class user surface, not a demo.

The LM reuses ``make_train_step`` wholesale via its ``loss_fn`` override: the epoch
program gathers ``[B, S]`` token batches from the device-resident token array by index
plan exactly like the classifier trainers gather images (zero per-step host traffic).
"""

from __future__ import annotations

import functools
import os
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    download_mnist, load_mnist, mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    stream as stream_mod,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models import lm as lm_mod
from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    validate_remat_policy,
)
from csed_514_project_distributed_training_using_pytorch_tpu import resilience
from csed_514_project_distributed_training_using_pytorch_tpu.ops import optim
from csed_514_project_distributed_training_using_pytorch_tpu.train.guard import (
    GuardRuntime,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
    initialize_cluster, make_mesh,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    TrainState, create_train_state, make_epoch_from_step, make_train_step,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint
from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics as M
from csed_514_project_distributed_training_using_pytorch_tpu.utils import plotting
from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
    LMConfig, parse_config,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    telemetry as T,
)


def make_eval_nll_fn(model: lm_mod.TransformerLM, *, batch_size: int):
    """``evaluate(params, tokens) -> sum_nll`` — summed next-token NLL over the split
    (divide by ``N·S`` for the mean; ``exp`` of that is perplexity), one scanned
    program like the classifier's eval."""

    def evaluate(params, tokens):
        n = tokens.shape[0]
        if n % batch_size:
            raise ValueError(f"eval split size {n} not divisible by eval batch "
                             f"{batch_size}")
        xs = tokens.reshape((n // batch_size, batch_size) + tokens.shape[1:])

        def body(carry, batch):
            log_probs = model.apply({"params": params}, model.shift_right(batch))
            nll = -jnp.sum(jnp.take_along_axis(log_probs, batch[..., None], axis=-1))
            return carry + nll, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), xs)
        return total

    return evaluate


def main(config: LMConfig = LMConfig(), *,
         datasets=None) -> tuple[TrainState, M.MetricsHistory]:
    """Run LM training over all addressable devices; returns final state + history."""
    watch = M.Stopwatch()
    if config.grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {config.grad_accum}")
    if config.health_stats and not config.telemetry:
        raise ValueError("--health-stats emits telemetry 'health' events and has no "
                         "other output — pass --telemetry PATH too")
    validate_remat_policy(config.remat, config.remat_policy)
    if config.attention_window:
        # Fail fast, pre-data/rendezvous (one owner for the message).
        from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
            validate_window,
        )
        validate_window(config.attention_window)
    if config.kv_heads and (config.kv_heads < 0
                            or config.num_heads % config.kv_heads):
        raise ValueError(f"--kv-heads {config.kv_heads} must be a positive divisor "
                         f"of --num-heads {config.num_heads}")
    info = initialize_cluster()
    run_plan, plan_events = None, []
    if config.plan:
        # Resolve BEFORE the mesh spec is read: the plan rewrites mesh/
        # grad_accum on the (frozen) config (data x model search — plan/).
        # Autotune trial events buffer until the telemetry writer exists below.
        from csed_514_project_distributed_training_using_pytorch_tpu import (
            plan as plan_mod,
        )
        config, run_plan = plan_mod.apply_plan(config, "lm",
                                               emit=plan_events.append)
    if config.mesh:
        # Optional named mesh: data (DP) x seq (context parallelism — ring or
        # zig-zag causal attention over the sequence-sharded pixel stream) x
        # model (Megatron TP over the blocks' column/row kernels — r5; the ring
        # spec already shards the head dim over `model`, so seq x model composes).
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
            parse_mesh_spec,
        )
        axis_names, axis_sizes = parse_mesh_spec(config.mesh)
        if (any(n not in ("data", "seq", "model") for n in axis_names)
                or "data" not in axis_names):
            raise ValueError("the LM trainer's --mesh needs a data axis and supports "
                             f"data, seq, and model axes only, got {config.mesh!r} "
                             f"(use data=1,seq=N for pure context parallelism)")
        mesh = make_mesh(int(np.prod(axis_sizes)), axis_names=axis_names,
                         axis_shape=axis_sizes)
    else:
        mesh = make_mesh()
    world = mesh.shape.get("data", 1)
    seq_size = mesh.shape.get("seq", 1)
    model_size = mesh.shape.get("model", 1)
    if config.zigzag_attention and seq_size < 2:
        raise ValueError("--zigzag-attention needs a seq axis in --mesh")
    # r4: --attention-window composes with the zig-zag schedule too (global-
    # position chunk-pair band masks in zigzag_ring_attention) — no guard needed.
    if config.batch_size % world:
        raise ValueError(f"batch {config.batch_size} not divisible by data axis "
                         f"{world}")

    loader = None
    eval_batch = config.eval_batch
    if config.corpus:
        # Streaming token-shard corpus (data/stream.py, DESIGN.md §26): the
        # epoch feed comes off disk through the deterministic cursor loader;
        # vocab/seq_len are the corpus's, not MNIST's. The scanned epoch
        # program is unchanged — each epoch's batches materialize into the
        # device-resident token array and the plan is the identity (the
        # loader already emitted them in stream order).
        loader = stream_mod.StreamLoader(config.corpus, config.batch_size,
                                         seed=config.seed,
                                         throttle_s=config.data_throttle_s)
        seq_len = loader.seq_len
        vocab = loader.vocab
        test_tokens = stream_mod.eval_tokens(config.corpus)
        if test_tokens is None or not len(test_tokens):
            raise ValueError(f"--corpus {config.corpus} has no eval split — "
                             f"rebuild with tools/build_corpus.py --eval-frac")
        n_train = loader.batches_per_epoch * config.batch_size
        eval_batch = min(config.eval_batch, len(test_tokens))
        n_test = len(test_tokens) - len(test_tokens) % eval_batch
        test_tokens = test_tokens[:n_test]
        train_tokens = None
        data_source = f"corpus:{config.corpus}"
    else:
        if config.download_data and datasets is None:
            download_mnist(config.data_dir)
        train_ds, test_ds = (datasets if datasets is not None
                             else load_mnist(config.data_dir))
        train_ds = mnist.truncate(train_ds, config.max_train_examples)
        test_ds = mnist.truncate(test_ds, config.max_test_examples)

        # Tokenize ONCE on host; the token arrays are the device-resident dataset.
        train_tokens = np.asarray(lm_mod.tokenize_images_to_ids(
            jnp.asarray(train_ds.images), num_levels=config.num_levels))
        test_tokens = np.asarray(lm_mod.tokenize_images_to_ids(
            jnp.asarray(test_ds.images), num_levels=config.num_levels))
        n_train, n_test = len(train_tokens), len(test_tokens)
        seq_len = train_tokens.shape[1]
        vocab = config.num_levels
        data_source = train_ds.source

    lm_kwargs = {}
    if seq_size > 1:
        # Context parallelism for the decoder: the ring (or zig-zag) causal core
        # plugs in without touching parameters, so seq-mesh checkpoints interchange
        # with DP runs (trajectory equality pinned in tests).
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            make_ring_attention_fn,
        )
        need = 2 * seq_size if config.zigzag_attention else seq_size
        if seq_len % need:
            raise ValueError(f"seq_len {seq_len} must divide by "
                             f"{'2*seq axis' if config.zigzag_attention else 'the seq axis'}"
                             f" = {need}")
        # --attention-window binds the sliding band into the ring schedule itself
        # (windowed context parallelism, r3: out-of-band hops skip their einsums);
        # the model's own attention_window field must then stay 0 — the decode
        # clone below re-adds it for the KV-cache mask.
        lm_kwargs["attention_fn"] = make_ring_attention_fn(
            mesh, use_zigzag=config.zigzag_attention,
            window=config.attention_window)
    # Fail fast on sampling knobs: generate() re-checks these, but its first call is
    # AFTER the full training loop — a bad flag must not cost the whole run.
    if not 0 <= config.top_k <= vocab + 1:
        raise ValueError(f"top_k {config.top_k} outside [0, {vocab + 1}]")
    if not 0.0 < config.top_p <= 1.0:
        raise ValueError(f"top_p {config.top_p} outside (0, 1]")
    model = lm_mod.TransformerLM(
        vocab_size=vocab + 1, seq_len=seq_len,
        embed_dim=config.embed_dim, num_layers=config.num_layers,
        num_heads=config.num_heads, dropout_rate=config.dropout_rate,
        num_kv_heads=config.kv_heads or None,
        attention_window=(0 if seq_size > 1 else config.attention_window),
        rope=config.rope,
        dtype=jnp.bfloat16 if config.bf16 else jnp.float32, remat=config.remat,
        remat_policy=config.remat_policy,
        **lm_kwargs)
    # Decoding is single-chip (host params): restore the default core, and the
    # window as a model field so the KV-cache decode mask applies the same band the
    # (possibly ring-windowed) training attention did — decode parity holds across
    # the mesh choice because attention has no window-dependent parameters.
    from csed_514_project_distributed_training_using_pytorch_tpu import ops as _ops
    decode_model = (model.clone(attention_fn=_ops.full_attention,
                                attention_window=config.attention_window)
                    if seq_size > 1 else model)
    M.log(f"LM training: mesh {dict(mesh.shape)} on {info.process_count} process(es), "
          f"batch {config.batch_size}, vocab {vocab}+BOS, "
          f"seq {seq_len}, data source: {data_source}")
    # Telemetry + resilience wiring live ABOVE the resume so the restore is recorded;
    # resilience hooks are flag-gated, host-side only (zero-cost when off).
    tele = T.TelemetryWriter(config.telemetry,
                             preserve=bool(config.resume_from))
    tele.emit(T.manifest_event(config, mesh=mesh, run_type="lm"))
    if run_plan is not None:
        tele.emit(T.plan_event(run_plan))
        for ev in plan_events:
            tele.emit(ev)
    rt = resilience.RunHooks(heartbeat_dir=config.heartbeat_dir,
                             handle_preemption=config.handle_preemption,
                             process_index=info.process_index)
    # Numerical immune system (--guard): in-step verdict + identity update;
    # host side is epoch-boundary bookkeeping only.
    grt = GuardRuntime(config, tele=tele,
                       store_dir=os.path.join(config.results_dir, "checkpoints")
                       if config.results_dir else "")

    optimizer = optim.make_optimizer(config.optimizer,
                                     learning_rate=config.learning_rate,
                                     momentum=config.momentum,
                                     weight_decay=config.weight_decay)
    state = create_train_state(model, jax.random.PRNGKey(config.seed),
                               sample_input_shape=(1, seq_len),
                               optimizer=optimizer, ema=config.ema_decay > 0,
                               guard=config.guard)
    steps_per_epoch = n_train // config.batch_size
    if steps_per_epoch == 0:
        raise ValueError(f"batch {config.batch_size} larger than the train split "
                         f"({n_train} examples) — nothing to step")
    lr_schedule = optim.make_lr_schedule(config.lr_schedule,
                                         warmup_steps=config.warmup_steps,
                                         total_steps=config.epochs * steps_per_epoch)
    start_epoch = 0
    if config.resume_from:
        state, start_epoch, warning = checkpoint.restore_for_resume(
            config.resume_from, state,
            process_index=info.process_index, process_count=info.process_count,
            steps_per_epoch=steps_per_epoch, tele=tele)
        if warning:
            M.log(f"WARNING: {warning}")
        M.log(f"Resumed from {config.resume_from} at step {int(state.step)} "
              f"(starting epoch {start_epoch})")
        # Manifest cursor (DESIGN.md §26): the checkpoint and the stream
        # position that produced it are one artifact. Stream cursors VERIFY
        # against this corpus (drift raises — silently resuming a reshuffled
        # or edited corpus would feed different bytes than the step count
        # paid for) and override the step-derived start epoch; epoch cursors
        # cross-check it.
        man_cursor = checkpoint.cursor_for(config.resume_from)
        if loader is not None and man_cursor is not None:
            cur_epoch, cur_batch = loader.verify_cursor(man_cursor)
            if cur_batch:
                M.log(f"WARNING: stream cursor resumes mid-epoch (batch "
                      f"{cur_batch}) but the epoch program replays whole "
                      f"epochs — starting at epoch {cur_epoch}")
            start_epoch = cur_epoch
        else:
            note = checkpoint.check_cursor_resume(
                config.resume_from, seed=config.seed, step=int(state.step),
                start_epoch=start_epoch)
            if note:
                M.log(f"WARNING: {note}")
    grt.baseline(state)     # this attempt's anomaly-counter zero point
    if model_size > 1:
        # Megatron TP (r5): column/row kernels shard over `model` (the LM blocks
        # reuse TransformerBlock's leaf names, so the classifier's partition rules
        # apply as-is); embeddings/head/LNs replicate. One block owns BOTH the
        # placement and the matching epoch compiler so they cannot diverge.
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            tensor_parallel as tp,
        )
        state = tp.shard_train_state(mesh, state)
        compile_lm_epoch = functools.partial(tp.compile_epoch_tp, mesh=mesh,
                                             data_axis="data")
    else:
        state = jax.device_put(state, dp.replicated(mesh))
        compile_lm_epoch = functools.partial(dp.compile_epoch, mesh=mesh)
    # Host fetches must replicate ON DEVICE first (all-gather) — device_get on a
    # TP-sharded array would fail on a multi-host fleet where no process
    # addresses every shard.
    gather = dp.gather_replicated(mesh)

    deterministic = config.dropout_rate == 0.0

    def lm_loss(params, xs, ys, rng):
        del ys  # the target stream IS the input stream, shifted inside the loss
        return lm_mod.next_token_loss(model, params, xs, rng,
                                      deterministic=deterministic,
                                      label_smoothing=config.label_smoothing)

    health = config.health_stats
    step_fn = make_train_step(model, learning_rate=config.learning_rate,
                              momentum=config.momentum, grad_accum=config.grad_accum,
                              optimizer=optimizer, lr_schedule=lr_schedule,
                              clip_grad_norm=config.clip_grad_norm,
                              ema_decay=config.ema_decay, loss_fn=lm_loss,
                              with_metrics=health, guard=grt.spec)
    epoch_fn = compile_lm_epoch(make_epoch_from_step(step_fn, health=health))
    eval_fn = jax.jit(make_eval_nll_fn(model, batch_size=eval_batch))

    # Corpus mode: the device token array is REFILLED per epoch from the
    # streaming loader (same shape every epoch — the compiled program is
    # oblivious); seed it with zeros so AOT compile below sees real arrays.
    tokens_d = dp.put_global(
        mesh, (np.zeros((n_train, seq_len), np.int32) if loader is not None
               else train_tokens), P())
    # ys is unused by the LM loss; a zero vector keeps the epoch program's
    # (images, labels, plan) signature without a second token gather per step.
    zeros_d = dp.put_global(mesh, np.zeros(n_train, np.int32), P())
    test_d = dp.put_global(mesh, test_tokens, P())
    dropout_rng = jax.random.PRNGKey(config.seed + 1)
    # Compile/execute split (telemetry): AOT-compile + FLOP-price the epoch program
    # (DP path; the TP cached-sharding wrapper has no .lower — compile_s stays null
    # and folds into the first epoch).
    # Gated on the CONFIG flag, not tele.enabled: every process must take the same
    # compile path (AOT-compiled vs jit) on a multi-host fleet.
    compile_s = flops_per_step = bytes_per_step = None
    if config.telemetry:
        plan_struct = jax.ShapeDtypeStruct(
            (steps_per_epoch, config.batch_size), np.int32)
        compiled, aot = T.aot_compile(epoch_fn, state, tokens_d, zeros_d,
                                      plan_struct, dropout_rng)
        if compiled is not None:
            epoch_fn = compiled
            compile_s = aot["lower_s"] + aot["compile_s"]
            if aot["flops"]:
                flops_per_step = aot["flops"] / steps_per_epoch
            if aot.get("bytes_accessed"):
                bytes_per_step = aot["bytes_accessed"] / steps_per_epoch
            tele.emit(T.compile_event("epoch", aot,
                                      steps_per_call=steps_per_epoch))
    history = M.MetricsHistory()
    saver = checkpoint.make_saver(config.async_checkpoint, tele=tele)

    ckpt_path = (os.path.join(config.results_dir, "model_lm.ckpt")
                 if config.results_dir else "")
    if ckpt_path:
        os.makedirs(config.results_dir, exist_ok=True)

    try:
        state = _run_epochs(config, state, mesh, epoch_fn, eval_fn, tokens_d,
                            zeros_d, test_d, dropout_rng, n_train, n_test, seq_len,
                            steps_per_epoch, start_epoch, history, watch, saver,
                            ckpt_path, gather, tele, compile_s, flops_per_step,
                            rt, bytes_per_step, grt, loader)
    finally:
        # Drain the write-behind queue even on an exception/signal/preemption
        # mid-run — the queued per-epoch checkpoint is the resume artifact a killed
        # run needs, and flush() re-raises deferred background IO errors. The
        # preemption latch is uninstalled so in-process callers get their signal
        # semantics back.
        rt.uninstall()
        saver.flush()

    host_state = jax.device_get(gather(state))
    if ckpt_path:
        M.log(f"Saved {ckpt_path}")
    if config.generate > 0 and loader is None:
        # Corpus-trained models skip the digit grids: ids_to_images only means
        # something for the pixel-stream tokenizer.
        def sample_grid(filename: str, seed_offset: int, batch: int, **gen_kw):
            gen_params = (host_state.ema if host_state.ema is not None
                          else host_state.params)
            # Cold path: runs once per figure AFTER training, and each call's
            # closure (batch/gen_kw) differs — a cached wrapper would never
            # be reused, so the per-call jit is sanctioned here.
            ids = jax.jit(lambda key: lm_mod.generate(  # graftlint: disable=retrace-hazard
                decode_model, gen_params, key, batch=batch,
                temperature=config.temperature, top_k=config.top_k,
                top_p=config.top_p, **gen_kw))(
                    jax.random.PRNGKey(config.seed + seed_offset))
            path = os.path.join(config.images_dir, filename)
            if plotting.save_generated_grid(
                    np.asarray(lm_mod.ids_to_images(ids,
                                                    num_levels=config.num_levels)),
                    path, n=batch) is not None:
                M.log(f"Saved {path}")

        sample_grid("lm_samples.png", 2, config.generate)
        # Digit completion: teacher-force the top half of real test images, sample
        # the bottom half — the prompt-conditioned generation surface.
        n_c = min(config.generate, n_test)
        sample_grid("lm_completions.png", 3, n_c,
                    prompt=jnp.asarray(test_tokens[:n_c]),
                    prompt_len=seq_len // 2)
    plotting.save_loss_curves(history,
                              os.path.join(config.images_dir, "lm_loss_curve.png"))
    if config.results_dir:
        M.save_metrics_jsonl(history,
                             os.path.join(config.results_dir, "metrics.jsonl"))
    return host_state, history


def _run_epochs(config, state, mesh, epoch_fn, eval_fn, tokens_d, zeros_d, test_d,
                dropout_rng, n_train, n_test, seq_len, steps_per_epoch, start_epoch,
                history, watch, saver, ckpt_path, gather, tele, compile_s,
                flops_per_step, rt, bytes_per_step=None, grt=None, loader=None):
    """The LM trainer's epoch loop, split out so the caller can guarantee the
    async-checkpoint flush in a ``finally`` regardless of where the loop fails."""
    best_step_s = None
    ckpt_store = (os.path.join(config.results_dir, "checkpoints")
                  if config.results_dir else "")
    for epoch in range(start_epoch, config.epochs):
        # heartbeat (with the previous boundary's param fingerprint) + armed
        # faults; no-op off
        rt.epoch_tick(state, epoch,
                      fingerprint=grt.fingerprint if grt else None)
        t_epoch = time.perf_counter()
        stream_wait_s = stream_digest = None
        if loader is not None:
            # Streaming corpus feed (data/stream.py): the loader's
            # (seed, epoch)-pure shard shuffle IS the permutation, already in
            # batch order — refill the device token array and run the identity
            # plan. Loader stall (shard IO, sha256, --data-throttle-s) lands in
            # this epoch's data_s and therefore in goodput's data_wait.
            epoch_np = loader.epoch_tokens(epoch)
            stream_wait_s = loader.pop_wait_s()
            stream_digest = zlib.crc32(epoch_np.tobytes())
            tokens_d = dp.put_global(mesh, epoch_np, P())
            plan = dp.put_global(
                mesh,
                np.arange(steps_per_epoch * config.batch_size, dtype=np.int32)
                .reshape(steps_per_epoch, config.batch_size), P(None, "data"))
        else:
            # (seed, epoch)-keyed permutation — the parallel/sampler contract,
            # so resumed runs replay exactly the epochs they missed.
            perm = np.random.default_rng(
                np.random.SeedSequence([config.seed, epoch])).permutation(n_train)
            plan = dp.put_global(
                mesh,
                perm[:steps_per_epoch * config.batch_size].astype(np.int32)
                .reshape(steps_per_epoch, config.batch_size), P(None, "data"))
        data_s = time.perf_counter() - t_epoch
        t_exec = time.perf_counter()
        state, out = epoch_fn(state, tokens_d, zeros_d, plan, dropout_rng)
        losses, epoch_health = out if config.health_stats else (out, None)
        jax.block_until_ready(state.params)
        train_loss = float(np.asarray(jax.device_get(losses)).mean())
        execute_s = time.perf_counter() - t_exec
        t_eval = time.perf_counter()
        eval_params = state.ema if state.ema is not None else state.params
        sum_nll = float(jax.device_get(eval_fn(eval_params, test_d)))
        eval_s = time.perf_counter() - t_eval
        val_nll = sum_nll / (n_test * seq_len)
        examples = (epoch + 1) * steps_per_epoch * config.batch_size
        history.record_train(examples, train_loss)
        history.record_test(examples, val_nll)
        M.log(f"Epoch {epoch}: train_loss: {train_loss:.4f}, "
              f"val_nll/token: {val_nll:.4f}, val_ppl: {float(np.exp(val_nll)):.3f}, "
              f"time_elapsed: {watch.elapsed():.2f}s")
        if epoch_health is not None:
            # SPMD-entered by every process (the norm program would deadlock a
            # fleet if only process 0 ran it); emission below stays process-0 gated.
            health_host = jax.device_get(epoch_health)
            param_norm = T.global_l2_norm(state.params)
        if tele.enabled:
            step_s = execute_s / steps_per_epoch if steps_per_epoch else None
            if step_s and (best_step_s is None or step_s < best_step_s):
                best_step_s = step_s
            tele.emit(T.epoch_event(
                epoch, examples=steps_per_epoch * config.batch_size,
                steps=steps_per_epoch, wall_s=time.perf_counter() - t_epoch,
                execute_s=execute_s, eval_s=eval_s, data_s=data_s,
                compile_s=compile_s, flops_per_step=flops_per_step,
                train_loss=train_loss, val_loss=val_nll,
                mfu=T.estimate_mfu(flops_per_step, step_s)["mfu"]))
            if epoch_health is not None:
                tele.emit(T.health_event(epoch, health_host, steps_per_epoch,
                                         param_norm=param_norm))
            if loader is not None:
                # The stream ledger next to the epoch event: stall wall,
                # next-epoch cursor (the one the checkpoint below stamps),
                # and the epoch's token CRC — the bitwise pin the
                # deterministic-resume tests compare across a kill.
                tele.emit(T.data_event(
                    epoch, batches=steps_per_epoch,
                    sequences=steps_per_epoch * config.batch_size,
                    wait_s=stream_wait_s, throttle_s=config.data_throttle_s,
                    cursor=loader.cursor(epoch + 1, 0),
                    stream_digest=stream_digest))
        # Guard boundary: anomaly verdict fetch + event + cross-replica
        # fingerprint, then the manifest health stamp for the versioned save.
        stamp = grt.epoch_end(state, epoch, steps_per_epoch) if grt else None
        if ckpt_path:
            # Device-resident gathered state: the saver is process-0 gated and
            # device_gets internally — non-0 processes must not pay a host fetch.
            ck_state = gather(state)
            saver.save_train_state(ckpt_path, ck_state)
            if ckpt_store and config.keep_checkpoints:
                # Versioned store (manifest + checksums + keep-last-N GC) for the
                # supervisor's newest-HEALTHY resume scan. The cursor stamps the
                # NEXT epoch's stream position into the manifest (DESIGN.md §26).
                cursor = (loader.cursor(epoch + 1, 0) if loader is not None
                          else {"version": 1, "kind": "epoch",
                                "seed": config.seed, "epoch": epoch + 1,
                                "batch": 0, "step": int(ck_state.step)})
                checkpoint.save_versioned(ckpt_store, ck_state,
                                          keep=config.keep_checkpoints, tele=tele,
                                          health=stamp, cursor=cursor)
        # Anomaly policy AFTER the stamped checkpoint is durable (raises
        # Poisoned; __main__ exits 65).
        if grt:
            grt.check_poisoned(state)
        # Cooperative preemption at the epoch boundary, with this epoch's
        # checkpoint durable (raises Preempted; __main__ exits 75).
        rt.check_preempt(epoch=epoch, state=state, checkpoint=ckpt_path, tele=tele)
    if tele.enabled and best_step_s is not None:
        # bytes_per_step is XLA's own bytes-accessed count for the compiled
        # step (byte-true under quantized dtypes): the mfu event carries the
        # bandwidth roofline side alongside the FLOP side.
        tele.emit(T.mfu_event(flops_per_step, best_step_s, bytes_per_step))
    return state


if __name__ == "__main__":
    try:
        main(parse_config(LMConfig))
    except resilience.Preempted as e:
        M.log(f"preempted at step {e.step} (checkpoint {e.checkpoint or 'n/a'}); "
              f"exiting {resilience.EXIT_PREEMPTED} — resume with --resume-from")
        raise SystemExit(resilience.EXIT_PREEMPTED)
    except resilience.Poisoned as e:
        M.log(f"poisoned at step {e.step} (anomaly window "
              f"{e.window[0]}:{e.window[1]}); exiting "
              f"{resilience.EXIT_POISONED} — the supervisor rolls back to the "
              f"newest healthy checkpoint and skips the window")
        raise SystemExit(resilience.EXIT_POISONED)
