"""Local multi-process fleet launcher — run one command as N rendezvous'd processes.

The reference launches its fleet by hand: SSH into each VM, run a per-machine file whose
source encodes the rank (``src/run1.py:31`` vs ``src/run2.py:31``) or pass ``--local_rank``
to ``src/train_dist.py:121``, with the coordinator IP hardcoded in the program
(``src/train_dist.py:144``). Here the launch contract is: **every process runs the same
command**; its cluster coordinates arrive via environment (``JAX_COORDINATOR_ADDRESS``,
``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``), which ``parallel.mesh.initialize_cluster`` reads.
On a real TPU pod none of this is needed — slice metadata supplies everything — so this
launcher's jobs are (a) multi-host *emulation* on one machine (N processes × M virtual CPU
devices each — the fake-backend analog, SURVEY.md §4) and (b) documenting the env contract a
non-TPU fleet runner must provide.

Usage (≙ running run1.py and run2.py on two VMs, but one command, no editing)::

    python -m csed_514_project_distributed_training_using_pytorch_tpu.train.launch \
        --num-processes 2 -- \
        -m csed_514_project_distributed_training_using_pytorch_tpu.train.smoke

Everything after ``--`` is passed to ``python`` in each process. Exit status is 0 iff every
process exits 0. Under ``--fail-fast`` (the default) the first nonzero child exit SIGTERMs
the rest of the fleet immediately — peers blocked on a dead partner's rendezvous or
collective are torn down, not waited out (the clean-abort behavior the reference's
all-or-nothing gloo world lacks, SURVEY.md §5 "failure detection"); ``--no-fail-fast``
restores let-them-finish semantics (every child runs to its own exit; the first nonzero
code is still reported). The :class:`Fleet` handle this module is built on is also the
unit ``resilience/supervisor.py`` watches and restarts — this file stays jax-free so
supervisors importing it never touch the accelerator.
"""

from __future__ import annotations

import argparse
import os
import re
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _child_env(base: dict, *, port: int, num_processes: int, process_id: int,
               platform: str | None, devices_per_process: int) -> dict:
    env = dict(base)
    env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
    env["JAX_NUM_PROCESSES"] = str(num_processes)
    env["JAX_PROCESS_ID"] = str(process_id)
    if platform:
        env["JAX_PLATFORMS"] = platform
    if (platform or env.get("JAX_PLATFORMS")) == "cpu":
        # Each emulated host owns its own virtual device set; replace any inherited count.
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={devices_per_process}"
        ).strip()
    return env


class Fleet:
    """A running fleet as one supervisable unit: spawn, poll, signal, teardown.

    ``launch()`` drives one for the simple run-to-completion case; the resilience
    supervisor holds one across its watch loop (heartbeat staleness checks, SIGTERM
    forwarding) — both get identical spawn env and teardown semantics because there
    is exactly one implementation of each."""

    def __init__(self, command: list[str], *, num_processes: int,
                 platform: str | None = None, devices_per_process: int = 1,
                 port: int | None = None, env: dict | None = None,
                 process_id_base: int = 0):
        """``process_id_base`` offsets the children's ``JAX_PROCESS_ID``: the
        serving router runs one single-process Fleet PER replica (so replicas
        crash, restart, and get supervised independently), and the offset keeps
        each replica's fleet-wide identity — heartbeat file index, fault-spec
        ``proc=`` matching — intact even though every such fleet is size 1.
        Rendezvous'd multi-process fleets keep the default 0 (a nonzero base
        would break ``initialize_cluster``'s contiguous-rank contract)."""
        self.port = port or _free_port()
        base = dict(os.environ if env is None else env)
        self.procs = [
            subprocess.Popen(
                [sys.executable, *command],
                env=_child_env(base, port=self.port, num_processes=num_processes,
                               process_id=process_id_base + i, platform=platform,
                               devices_per_process=devices_per_process),
            )
            for i in range(num_processes)
        ]
        self._first_failure: int | None = None

    def poll(self) -> int | None:
        """Reap finished children; return the first nonzero exit code observed so far
        (sticky), or None while none has failed."""
        for p in self.procs:
            rc = p.poll()
            if rc is not None and rc != 0 and self._first_failure is None:
                self._first_failure = rc
        return self._first_failure

    @property
    def running(self) -> bool:
        return any(p.poll() is None for p in self.procs)

    @property
    def exit_codes(self) -> list[int | None]:
        return [p.poll() for p in self.procs]

    def send_signal(self, sig) -> None:
        """Deliver ``sig`` to every live child (e.g. forwarding a preemption SIGTERM)."""
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except (ProcessLookupError, OSError):
                    pass

    def terminate(self, grace: float = 10.0) -> None:
        """SIGTERM every live child, give the fleet ``grace`` seconds collectively to
        exit (a cooperative preemption stop may need it), then SIGKILL stragglers and
        reap everything — a hung or failed peer must not leave zombies behind."""
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + grace
        for p in self.procs:
            try:
                p.wait(timeout=max(0.01, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def launch(command: list[str], *, num_processes: int, platform: str | None = None,
           devices_per_process: int = 1, port: int | None = None,
           timeout: float | None = None, fail_fast: bool = True) -> int:
    """Spawn ``python <command>`` ``num_processes`` times with rendezvous env; returns the
    first nonzero child exit code, else 0. Output streams through inherited stdout/stderr
    (process-0 gating in ``utils.metrics.log`` keeps it single-voiced).

    ``fail_fast`` (default): the first nonzero exit tears the fleet down immediately —
    peers blocked on a dead partner's rendezvous/collective get terminated rather than
    waited out. ``fail_fast=False`` lets every child run to its own exit first. Either
    way a shared ``timeout`` deadline bounds total wall time (exit 124, the coreutils
    ``timeout`` convention)."""
    fleet = Fleet(command, num_processes=num_processes, platform=platform,
                  devices_per_process=devices_per_process, port=port)
    deadline = None if timeout is None else time.monotonic() + timeout
    result: int | None = None
    try:
        while fleet.running:
            rc = fleet.poll()
            if rc is not None and fail_fast:
                result = rc
                break
            if deadline is not None and time.monotonic() > deadline:
                result = 124
                break
            time.sleep(0.05)
        if result is None:       # clean drain, or --no-fail-fast ran everyone to exit
            result = fleet.poll()
    finally:
        fleet.terminate()
    return result or 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n", 1)[0],
        usage="python -m ....train.launch --num-processes N [options] -- <python args>")
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform in children (e.g. cpu for emulation)")
    parser.add_argument("--devices-per-process", type=int, default=1,
                        help="virtual devices per emulated host (cpu platform only)")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="wall-clock seconds before the whole fleet is killed "
                             "(exit 124); default: wait forever")
    parser.add_argument("--fail-fast", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="SIGTERM the rest of the fleet the moment any child "
                             "exits nonzero (peers hung on dead collectives are torn "
                             "down, not waited out); --no-fail-fast lets every child "
                             "run to its own exit")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="everything after -- is run as: python <command>")
    args = parser.parse_args(argv)
    command = args.command[1:] if args.command[:1] == ["--"] else args.command
    if not command:
        parser.error("no command given — pass e.g. `-- -m <module> [args]`")
    return launch(command, num_processes=args.num_processes, platform=args.platform,
                  devices_per_process=args.devices_per_process, port=args.port,
                  timeout=args.timeout, fail_fast=args.fail_fast)


if __name__ == "__main__":
    raise SystemExit(main())
