"""Local multi-process fleet launcher — run one command as N rendezvous'd processes.

The reference launches its fleet by hand: SSH into each VM, run a per-machine file whose
source encodes the rank (``src/run1.py:31`` vs ``src/run2.py:31``) or pass ``--local_rank``
to ``src/train_dist.py:121``, with the coordinator IP hardcoded in the program
(``src/train_dist.py:144``). Here the launch contract is: **every process runs the same
command**; its cluster coordinates arrive via environment (``JAX_COORDINATOR_ADDRESS``,
``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``), which ``parallel.mesh.initialize_cluster`` reads.
On a real TPU pod none of this is needed — slice metadata supplies everything — so this
launcher's jobs are (a) multi-host *emulation* on one machine (N processes × M virtual CPU
devices each — the fake-backend analog, SURVEY.md §4) and (b) documenting the env contract a
non-TPU fleet runner must provide.

Usage (≙ running run1.py and run2.py on two VMs, but one command, no editing)::

    python -m csed_514_project_distributed_training_using_pytorch_tpu.train.launch \
        --num-processes 2 -- \
        -m csed_514_project_distributed_training_using_pytorch_tpu.train.smoke

Everything after ``--`` is passed to ``python`` in each process. Exit status is 0 iff every
process exits 0 (a failed peer also causes the others to fail their collectives — the same
all-or-nothing failure model as the reference's gloo world, SURVEY.md §5 "failure detection").
"""

from __future__ import annotations

import argparse
import os
import re
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _child_env(base: dict, *, port: int, num_processes: int, process_id: int,
               platform: str | None, devices_per_process: int) -> dict:
    env = dict(base)
    env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
    env["JAX_NUM_PROCESSES"] = str(num_processes)
    env["JAX_PROCESS_ID"] = str(process_id)
    if platform:
        env["JAX_PLATFORMS"] = platform
    if (platform or env.get("JAX_PLATFORMS")) == "cpu":
        # Each emulated host owns its own virtual device set; replace any inherited count.
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={devices_per_process}"
        ).strip()
    return env


def launch(command: list[str], *, num_processes: int, platform: str | None = None,
           devices_per_process: int = 1, port: int | None = None,
           timeout: float | None = None) -> int:
    """Spawn ``python <command>`` ``num_processes`` times with rendezvous env; returns the
    first nonzero child exit code, else 0. Output streams through inherited stdout/stderr
    (process-0 gating in ``utils.metrics.log`` keeps it single-voiced)."""
    port = port or _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, *command],
            env=_child_env(os.environ, port=port, num_processes=num_processes,
                           process_id=i, platform=platform,
                           devices_per_process=devices_per_process),
        )
        for i in range(num_processes)
    ]
    # Poll all children together: the first nonzero exit wins immediately (peers blocked on
    # a dead partner's rendezvous/collective get terminated rather than waited out), and a
    # shared deadline bounds total wall time instead of letting each child consume its own.
    deadline = None if timeout is None else time.monotonic() + timeout
    result: int | None = None
    try:
        live = list(procs)
        while live and result is None:
            for p in list(live):
                if p.poll() is not None:
                    live.remove(p)
                    if p.returncode != 0:
                        result = p.returncode
                        break
            if result is None and live:
                if deadline is not None and time.monotonic() > deadline:
                    result = 124        # timeout convention of coreutils `timeout`
                    break
                time.sleep(0.05)
    finally:
        for p in procs:          # a hung or failed peer must not leave zombies behind
            if p.poll() is None:
                p.terminate()
        for p in procs:          # reap everything; escalate if SIGTERM is ignored
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
    return result or 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n", 1)[0],
        usage="python -m ....train.launch --num-processes N [options] -- <python args>")
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform in children (e.g. cpu for emulation)")
    parser.add_argument("--devices-per-process", type=int, default=1,
                        help="virtual devices per emulated host (cpu platform only)")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port (default: pick a free one)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="wall-clock seconds before the whole fleet is killed "
                             "(exit 124); default: wait forever")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="everything after -- is run as: python <command>")
    args = parser.parse_args(argv)
    command = args.command[1:] if args.command[:1] == ["--"] else args.command
    if not command:
        parser.error("no command given — pass e.g. `-- -m <module> [args]`")
    return launch(command, num_processes=args.num_processes, platform=args.platform,
                  devices_per_process=args.devices_per_process, port=args.port,
                  timeout=args.timeout)


if __name__ == "__main__":
    raise SystemExit(main())
