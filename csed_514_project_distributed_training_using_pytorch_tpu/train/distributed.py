"""Distributed data-parallel trainer — the reference ``src/train_dist.py`` workflow, SPMD.

Reproduces the workflow of SURVEY.md §3.2: rendezvous, per-replica data sharding with
per-epoch reshuffle (``DistributedSampler(seed=42)`` + ``set_epoch``, reference
``src/train_dist.py:33-37,72``), ``epochs`` rounds of (train over the sharded global batch,
evaluate, print an epoch summary with train/val loss, accuracy, elapsed), then a
process-0-only final params save and the distributed loss-curve figure
(``src/train_dist.py:70-116,161-164``).

What is *not* here, by design (the TPU-native re-expression):

- no ``DDP(model)`` wrapper and no backend string — parallelism is the mesh + sharding
  annotations on ONE jit-compiled epoch program; XLA inserts the gradient all-reduce
  (``src/train_dist.py:63,146`` have no equivalent lines);
- no per-machine launcher files with a hand-assigned rank (``src/run1.py:31`` vs
  ``src/run2.py:31``) — every host runs this same module; coordinates come from
  ``jax.distributed`` metadata;
- no per-step ``loss.item()`` host sync or tqdm tick (``src/train_dist.py:85-87``) — losses
  come back per epoch as one array (the cadence of printed *epoch* summaries is identical);
- the per-worker batch is ``global_batch_size // world`` exactly as the reference computes it
  (``src/train_dist.py:133``: fixed global batch, weak per-worker scaling).

Sharding layout: per-replica example order comes from the same ``ShardedSampler`` contract,
laid out as a ``[steps, global_batch]`` index plan whose column-block ``r`` is replica ``r``'s
shard, so sharding the plan's second axis over the mesh reproduces DistributedSampler's
division of labor exactly. The final sub-global-batch remainder of each epoch is dropped
(static shapes; ≤ world-1 examples/epoch, re-covered by the next epoch's reshuffle).
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    download_mnist, load_mnist, mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data.loader import (
    iter_plan_batches,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    build_model,
    validate_model_config,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
    initialize_cluster, make_mesh,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)
from csed_514_project_distributed_training_using_pytorch_tpu import resilience
from csed_514_project_distributed_training_using_pytorch_tpu.train.guard import (
    GuardRuntime,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    TrainState, create_train_state, make_epoch_fn, make_eval_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops import optim
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint
from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics as M
from csed_514_project_distributed_training_using_pytorch_tpu.utils import plotting
from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
    DistributedConfig, parse_config,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.determinism import (
    assert_replicas_synced,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.profiling import (
    maybe_profile,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
    telemetry as T,
)


def epoch_index_plan(samplers: list[ShardedSampler], epoch: int,
                     per_replica_batch: int) -> np.ndarray:
    """Build the ``[steps, world * per_replica_batch]`` index plan for one epoch.

    Column-block ``r`` holds replica ``r``'s examples in its sampler order, so a
    ``P(None, 'data')`` sharding gives each device exactly its DistributedSampler shard.
    """
    per = [s.epoch_indices(epoch) for s in samplers]
    steps = len(per[0]) // per_replica_batch
    blocks = [p[:steps * per_replica_batch].reshape(steps, per_replica_batch) for p in per]
    return np.concatenate(blocks, axis=1)


def _host_local_columns(mesh, per_replica_batch: int) -> tuple[int, int]:
    """This process's contiguous column block of the ``[steps, global_batch]`` plan: the
    rows owned by its addressable devices under the ``P('data')`` batch sharding. The
    device order of the mesh groups devices by process (jax.devices() ordering), which the
    host-local feed contract requires (``dp.global_batch_from_host_local``); asserted, not
    assumed."""
    mesh_devs = list(mesh.devices.flat)
    local_ids = {d.id for d in jax.local_devices()}
    positions = [i for i, d in enumerate(mesh_devs) if d.id in local_ids]
    if positions != list(range(positions[0], positions[0] + len(positions))):
        raise RuntimeError(
            f"addressable devices are not contiguous in the mesh ({positions}) — the "
            f"host-local feed path requires process-contiguous device order")
    return positions[0] * per_replica_batch, (positions[-1] + 1) * per_replica_batch


def main(config: DistributedConfig = DistributedConfig(), *,
         num_devices: int | None = None,
         datasets=None) -> tuple[TrainState, M.MetricsHistory]:
    """Run distributed training over all (or ``num_devices``) addressable devices; every host
    in a multi-host fleet runs this same function."""
    watch = M.Stopwatch()                         # ≙ t0, reference src/train_dist.py:119
    validate_model_config(config.model, remat=config.remat,
                          remat_policy=config.remat_policy, causal=config.causal,
                          attention_window=config.attention_window,
                          kv_heads=config.kv_heads, rope=config.rope)  # fail fast, pre-rendezvous
    if config.grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {config.grad_accum}")
    if config.health_stats and config.host_local_feed:
        raise ValueError("--health-stats rides the compiled scan carry "
                         "(train/step.py::HealthStats) — it is not available on the "
                         "per-batch --host-local-feed path")
    if config.health_stats and not config.telemetry:
        raise ValueError("--health-stats emits telemetry 'health' events and has no "
                         "other output — pass --telemetry PATH too")
    info = initialize_cluster()                   # ≙ init_process_group, :146
    mesh = make_mesh(num_devices)
    tele = T.TelemetryWriter(config.telemetry,
                             preserve=bool(config.resume_from))
    tele.emit(T.manifest_event(config, mesh=mesh, run_type="distributed"))
    # Resilience wiring (flag-gated, host-side only — the compiled epoch program is
    # untouched, and with both flags off no step fetch or syscall is added).
    rt = resilience.RunHooks(heartbeat_dir=config.heartbeat_dir,
                             handle_preemption=config.handle_preemption,
                             process_index=info.process_index)
    # Numerical immune system (--guard): in-step anomaly verdict + guarded
    # identity update; host side is epoch-boundary bookkeeping only.
    grt = GuardRuntime(config, tele=tele,
                       store_dir=os.path.join(config.results_dir, "checkpoints"))
    world = mesh.shape["data"]                    # ≙ world_size, :131 — but discovered
    if config.global_batch_size % world:
        raise ValueError(f"global batch {config.global_batch_size} not divisible by "
                         f"world size {world}")
    per_replica_batch = config.global_batch_size // world   # ≙ :133
    if config.grad_accum > 1 and per_replica_batch % config.grad_accum:
        raise ValueError(
            f"per-replica batch {per_replica_batch} not divisible by grad_accum "
            f"{config.grad_accum} — each microbatch must still shard evenly")

    root = jax.random.PRNGKey(config.seed)        # ≙ torch.manual_seed, :135-137
    init_rng, dropout_rng = jax.random.split(root)

    if config.download_data and datasets is None:
        download_mnist(config.data_dir)   # ≙ download=True, src/train_dist.py:22-30;
        #                                   atomic per-file install → fleet-safe
    train_ds, test_ds = datasets if datasets is not None else load_mnist(config.data_dir)
    train_ds = mnist.truncate(train_ds, config.max_train_examples)
    test_ds = mnist.truncate(test_ds, config.max_test_examples)
    n_train, n_test = len(train_ds), len(test_ds)
    M.log(f"Distributed training: {world} devices on {info.process_count} process(es), "
          f"global batch {config.global_batch_size} "
          f"(per-replica {per_replica_batch}), data source: {train_ds.source}")

    samplers = [ShardedSampler(n_train, num_replicas=world, rank=r,
                               seed=config.sampler_seed) for r in range(world)]

    model = build_model(config.model, bf16=config.bf16, remat=config.remat,
                        remat_policy=config.remat_policy,
                        causal=config.causal,
                        attention_window=config.attention_window,
                        kv_heads=config.kv_heads, rope=config.rope)
    optimizer = optim.make_optimizer(config.optimizer,
                                     learning_rate=config.learning_rate,
                                     momentum=config.momentum,
                                     weight_decay=config.weight_decay)
    state = create_train_state(model, init_rng, optimizer=optimizer,
                               ema=config.ema_decay > 0, guard=config.guard)
    steps_per_epoch = samplers[0].num_samples // per_replica_batch
    lr_schedule = optim.make_lr_schedule(config.lr_schedule,
                                         warmup_steps=config.warmup_steps,
                                         total_steps=config.epochs * steps_per_epoch)
    start_epoch = 0
    if config.resume_from:                        # the resume path the reference lacks
        state, start_epoch, warning = checkpoint.restore_for_resume(
            config.resume_from, state,
            process_index=info.process_index, process_count=info.process_count,
            steps_per_epoch=steps_per_epoch, tele=tele)
        if warning:
            M.log(f"WARNING: {warning}")
        M.log(f"Resumed from {config.resume_from} at step {int(state.step)} "
              f"(starting epoch {start_epoch})")
        # Manifest cursor cross-check (DESIGN.md §26): the checkpoint's stamped
        # data position must agree with the derived start epoch.
        note = checkpoint.check_cursor_resume(config.resume_from,
                                              seed=config.seed,
                                              step=int(state.step),
                                              start_epoch=start_epoch)
        if note:
            M.log(f"WARNING: {note}")
    grt.baseline(state)     # this attempt's anomaly-counter zero point
    if config.fsdp:
        # ZeRO/FSDP mode (r5): params + SGD/AdamW state shard over the data axis;
        # XLA inserts the per-use all-gathers and gradient reduce-scatters from
        # the annotations (parallel/fsdp.py). Same trajectory as plain DP.
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            fsdp,
        )
        state = fsdp.shard_train_state(mesh, state)
    else:
        state = jax.device_put(state, dp.replicated(mesh))
    # Host fetches replicate ON DEVICE first — device_get on an FSDP-sharded array
    # would fail on a multi-host fleet where no process addresses every shard.
    gather = dp.gather_replicated(mesh)
    ckpt_path = os.path.join(config.results_dir, "model_dist.ckpt")

    if not config.host_local_feed:
        train_x = dp.put_global(mesh, train_ds.images, P())
        train_y = dp.put_global(mesh, train_ds.labels, P())
    eval_spec = P("data") if config.shard_eval else P()
    test_x = dp.put_global(mesh, test_ds.images, eval_spec)
    test_y = dp.put_global(mesh, test_ds.labels, eval_spec)

    health = config.health_stats
    epoch_body = make_epoch_fn(model, learning_rate=config.learning_rate,
                               momentum=config.momentum,
                               unroll=config.scan_unroll,
                               pregather=config.pregather,
                               grad_accum=config.grad_accum, optimizer=optimizer,
                               lr_schedule=lr_schedule,
                               clip_grad_norm=config.clip_grad_norm,
                               ema_decay=config.ema_decay,
                               label_smoothing=config.label_smoothing,
                               health=health, guard=grt.spec)
    if config.fsdp:
        epoch_fn = fsdp.compile_epoch_fsdp(epoch_body, mesh)
    else:
        epoch_fn = dp.compile_epoch(epoch_body, mesh)
    # Compile/execute split (telemetry): AOT-compile the whole-epoch program and
    # price its FLOPs; the compiled program replaces the jit path so nothing
    # compiles twice. The FSDP wrapper resolves shardings from the first call's
    # state and has no .lower — aot_compile then returns None and compile time
    # folds into the first epoch's wall clock (compile_s stays null).
    # Gated on the CONFIG flag, not tele.enabled: every process must take the same
    # compile path (AOT-compiled vs jit) on a multi-host fleet; only emission is
    # process-0 gated.
    compile_s = flops_per_step = None
    if config.telemetry and not config.host_local_feed:
        plan_struct = jax.ShapeDtypeStruct(
            (steps_per_epoch, config.global_batch_size), np.int32)
        compiled, aot = T.aot_compile(epoch_fn, state, train_x, train_y,
                                      plan_struct, dropout_rng)
        if compiled is not None:
            epoch_fn = compiled
            compile_s = aot["lower_s"] + aot["compile_s"]
            if aot["flops"]:
                flops_per_step = aot["flops"] / steps_per_epoch
            tele.emit(T.compile_event("epoch", aot,
                                      steps_per_call=steps_per_epoch))
    eval_fn = dp.compile_eval(
        make_eval_fn(model, batch_size=config.batch_size_test), mesh,
        shard=config.shard_eval)

    if config.host_local_feed:
        from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
            make_train_step,
        )
        step_body = make_train_step(model, learning_rate=config.learning_rate,
                                    momentum=config.momentum,
                                    grad_accum=config.grad_accum,
                                    optimizer=optimizer, lr_schedule=lr_schedule,
                                    clip_grad_norm=config.clip_grad_norm,
                                    ema_decay=config.ema_decay,
                                    label_smoothing=config.label_smoothing,
                                    guard=grt.spec)
        step_fn = (fsdp.compile_step_fsdp(step_body, mesh) if config.fsdp
                   else dp.compile_step(step_body, mesh))
        col_lo, col_hi = _host_local_columns(mesh, per_replica_batch)
        M.log(f"Host-local feed: this process feeds global-batch columns "
              f"[{col_lo}:{col_hi}]")

    def run_epoch_device_resident(state, plan):
        """Fast path: whole epoch as one compiled scan over the device-resident split."""
        plan_d = dp.put_global(mesh, plan, P(None, "data"))
        return epoch_fn(state, train_x, train_y, plan_d, dropout_rng)

    def run_epoch_host_local(state, plan):
        """Multi-host input pipeline (SURVEY.md §7 hard part (d)): per step, this process
        gathers ONLY its addressable devices' rows of the global batch on host and
        assembles the globally-sharded arrays from per-process shards — the dataset never
        needs to be resident on (or even known to) other hosts. Identical plan and step
        math to the fast path; only the feeding mechanism differs. Host batches come
        through the native threaded prefetcher when built (the reference's distributed
        loader is exactly where its ``num_workers=4`` pool lives,
        ``src/train_dist.py:43-45``): workers gather step s+1's shard while step s runs
        on device."""
        losses = []
        # Live per-batch bar (≙ the reference's tqdm, src/train_dist.py:76) — only
        # on this host-fed path, where a per-step dispatch already exists; the bar
        # never forces a device sync (no per-step loss fetch), and it renders only
        # on a process-0 tty.
        with M.ProgressBar(plan.shape[0], desc="train ") as bar:
            for bx, by in iter_plan_batches(train_ds, plan[:, col_lo:col_hi]):
                gi, gl = dp.global_batch_from_host_local(mesh, bx, by)
                state, loss = step_fn(state, gi, gl, dropout_rng)
                losses.append(loss)
                bar.update(1)
        return state, jax.numpy.stack(losses)

    history = M.MetricsHistory()
    saver = checkpoint.make_saver(config.async_checkpoint, tele=tele)
    ckpt_store = os.path.join(config.results_dir, "checkpoints")

    try:
        with maybe_profile(config.profile, config.profile_dir):
            best_step_s = None
            for epoch in range(start_epoch, config.epochs):   # ≙ the epoch loop, :70
                # heartbeat (with the previous boundary's param fingerprint)
                # + armed faults; no-op off
                rt.epoch_tick(state, epoch, fingerprint=grt.fingerprint)
                t_epoch = time.perf_counter()
                plan = epoch_index_plan(samplers, epoch, per_replica_batch)  # ≙ set_epoch, :72
                data_s = time.perf_counter() - t_epoch
                t_exec = time.perf_counter()
                if config.host_local_feed:
                    state, losses = run_epoch_host_local(state, plan)
                else:
                    state, out = run_epoch_device_resident(state, plan)
                    losses, epoch_health = out if health else (out, None)

                losses = np.asarray(jax.device_get(losses))  # the honest sync point
                execute_s = time.perf_counter() - t_exec
                train_loss = float(losses.mean())     # per-epoch mean of per-step global means
                examples = (epoch + 1) * plan.size
                for i, l in enumerate(losses[::config.log_interval]):
                    history.record_train(epoch * plan.size +
                                         i * config.log_interval * plan.shape[1],
                                         float(l))

                t_eval = time.perf_counter()
                eval_params = state.ema if state.ema is not None else state.params
                if config.fsdp:
                    # compile_eval pins replicated param shardings; jit rejects a
                    # mismatched committed layout, so gather the shards on device.
                    eval_params = gather(eval_params)
                sum_nll, correct = jax.device_get(
                    eval_fn(eval_params, test_x, test_y))   # ≙ eval loop, :92-109
                eval_s = time.perf_counter() - t_eval
                val_loss = float(sum_nll) / n_test
                accuracy = float(correct) / n_test
                history.record_test(examples, val_loss)
                M.log(M.dist_epoch_summary_line(epoch, train_loss, val_loss, accuracy,
                                                watch.elapsed()))  # ≙ :113-114
                if health:
                    # SPMD-entered by every process (the norm program would
                    # deadlock a fleet if only process 0 ran it); emission below
                    # stays process-0 gated.
                    health_host = jax.device_get(epoch_health)
                    param_norm = T.global_l2_norm(state.params)
                if tele.enabled:
                    steps = int(losses.shape[0])
                    step_s = execute_s / steps if steps else None
                    if step_s and (best_step_s is None or step_s < best_step_s):
                        best_step_s = step_s
                    tele.emit(T.epoch_event(
                        epoch, examples=plan.size, steps=steps,
                        wall_s=time.perf_counter() - t_epoch,
                        execute_s=execute_s, eval_s=eval_s, data_s=data_s,
                        compile_s=compile_s, flops_per_step=flops_per_step,
                        train_loss=train_loss, val_loss=val_loss,
                        mfu=T.estimate_mfu(flops_per_step, step_s)["mfu"]))
                    if health:
                        tele.emit(T.health_event(epoch, health_host, steps,
                                                 param_norm=param_norm))
                # Guard boundary: fetch the anomaly verdict, emit the anomaly
                # event, compute the cross-replica fingerprint (host-local by
                # design — a global reduction would hand every process the
                # same scalar), and build the manifest health stamp.
                stamp = grt.epoch_end(state, epoch, steps=int(losses.shape[0]))
                # Per-epoch full-state checkpoint (process-0 gated, atomic) so a killed run
                # can resume with --resume-from; the reference only ever saves final params.
                # Device-resident gathered state: the saver is process-0 gated and
                # device_gets internally — non-0 processes must not pay a host fetch.
                ck_state = gather(state)
                saver.save_train_state(ckpt_path, ck_state)
                if config.keep_checkpoints:
                    # Versioned store (manifest + checksums + keep-last-N GC): what
                    # the fleet supervisor's newest-HEALTHY resume scan reads.
                    checkpoint.save_versioned(
                        ckpt_store, ck_state, keep=config.keep_checkpoints,
                        tele=tele, health=stamp,
                        # The manifest's data cursor: the (seed, epoch)-pure
                        # permutation's resume anchor (DESIGN.md §26).
                        cursor={"version": 1, "kind": "epoch",
                                "seed": config.seed, "epoch": epoch + 1,
                                "batch": 0, "step": int(ck_state.step)})
                # Anomaly policy AFTER the (stamped) checkpoint is durable: the
                # supervisor rolls back to the newest CLEAN stamp and restarts
                # with --skip-steps (raises Poisoned; __main__ exits 65).
                grt.check_poisoned(state)
                # Cooperative preemption: honor a pending SIGTERM now, with this
                # epoch's checkpoint durable (raises Preempted; __main__ exits 75).
                rt.check_preempt(epoch=epoch, state=state, checkpoint=ckpt_path,
                                 tele=tele)
            if tele.enabled and best_step_s is not None:
                tele.emit(T.mfu_event(flops_per_step, best_step_s))

        if not config.fsdp:
            # The desync "race detector" (SURVEY.md §5). Under FSDP the replica-sync
            # invariant it guards does not apply: sharded leaves hold DIFFERENT
            # slices by design, and gathered copies are replicated-by-construction
            # (the check would be vacuous, not reassuring).
            assert_replicas_synced(state.params)

        plotting.save_loss_curves(
            history, os.path.join(config.images_dir, "train_test_curve_dist.png"))  # ≙ :161
        M.save_metrics_jsonl(history, os.path.join(config.results_dir, "metrics.jsonl"))
        # The export must be the weights the reported metrics came from: the EMA tree
        # when --ema-decay is set (eval consumes it above), the raw params otherwise.
        export_state = gather(state)    # on device; save_params is process-0 gated
        checkpoint.save_params(
            os.path.join(config.results_dir, "model_dist.msgpack"),
            export_state.ema if export_state.ema is not None
            else export_state.params)   # ≙ :163-164
    finally:
        # Drain the write-behind queue even on an exception/signal/preemption
        # mid-run — the queued per-epoch checkpoint is the resume artifact a killed
        # run needs, and flush() re-raises deferred background IO errors. The
        # preemption latch is uninstalled so in-process callers get their signal
        # semantics back.
        rt.uninstall()
        saver.flush()
    return state, history


if __name__ == "__main__":
    try:
        main(parse_config(DistributedConfig))
    except resilience.Preempted as e:
        M.log(f"preempted at step {e.step} (checkpoint {e.checkpoint or 'n/a'}); "
              f"exiting {resilience.EXIT_PREEMPTED} — resume with --resume-from")
        raise SystemExit(resilience.EXIT_PREEMPTED)
    except resilience.Poisoned as e:
        M.log(f"poisoned at step {e.step} (anomaly window "
              f"{e.window[0]}:{e.window[1]}); exiting "
              f"{resilience.EXIT_POISONED} — the supervisor rolls back to the "
              f"newest healthy checkpoint and skips the window")
        raise SystemExit(resilience.EXIT_POISONED)
