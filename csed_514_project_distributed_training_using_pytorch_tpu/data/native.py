"""ctypes bindings to the native (C++) data-loader runtime in ``data/_native``.

The reference's host-side input path is C++ inside libtorch: torchvision's MNIST cache
reader (reference ``src/train.py:26-31``) and the DataLoader worker pool
(``num_workers=4, pin_memory=True``, reference ``src/train_dist.py:43-45``). This module is
that native substrate rebuilt first-party for the TPU framework — IDX parsing, pixel
normalization, batch gather, and a threaded prefetching batch queue — compiled on demand from
``_native/loader.cc`` and reached over a C ABI (ctypes; pybind11 intentionally not required).

Every entry point degrades gracefully: if the toolchain or library is unavailable,
``available()`` is False and callers (``data.mnist``, ``data.loader``) use their pure-numpy
paths, which are bit-exact equivalents (asserted by tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator

import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.data._native import build

_lib: ctypes.CDLL | None = None
_lib_tried = False

_DISABLE_ENV = "CSED514_TPU_NO_NATIVE"


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get(_DISABLE_ENV):
        return None
    path = build.build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    c_ll, c_int, c_float = ctypes.c_longlong, ctypes.c_int, ctypes.c_float
    p_u8 = ctypes.POINTER(ctypes.c_ubyte)
    p_f32 = ctypes.POINTER(c_float)
    p_i32 = ctypes.POINTER(c_int)
    p_ll = ctypes.POINTER(c_ll)

    lib.nl_idx_info.argtypes = [ctypes.c_char_p, ctypes.POINTER(c_int), p_ll]
    lib.nl_idx_info.restype = c_int
    lib.nl_idx_read.argtypes = [ctypes.c_char_p, p_u8, c_ll]
    lib.nl_idx_read.restype = c_int
    lib.nl_normalize.argtypes = [p_u8, p_f32, c_ll, c_float, c_float, c_int]
    lib.nl_normalize.restype = c_int
    lib.nl_gather_f32.argtypes = [p_f32, c_ll, c_ll, p_i32, c_ll, p_f32, c_int]
    lib.nl_gather_f32.restype = c_int
    lib.nl_gather_i32.argtypes = [p_i32, c_ll, p_i32, c_ll, p_i32]
    lib.nl_gather_i32.restype = c_int
    lib.nl_prefetcher_create.argtypes = [p_f32, p_i32, c_ll, c_ll, p_i32, c_ll, c_ll,
                                         c_int, c_int]
    lib.nl_prefetcher_create.restype = ctypes.c_void_p
    lib.nl_prefetcher_next.argtypes = [ctypes.c_void_p, p_f32, p_i32]
    lib.nl_prefetcher_next.restype = c_ll
    lib.nl_prefetcher_destroy.argtypes = [ctypes.c_void_p]
    lib.nl_prefetcher_destroy.restype = None
    lib.nl_abi_version.argtypes = []
    lib.nl_abi_version.restype = c_int

    if lib.nl_abi_version() != 1:
        return None
    _lib = lib
    return _lib


def available() -> bool:
    """True when the native library is built and loadable."""
    return _load() is not None


def _as_ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def load_idx(path: str) -> np.ndarray:
    """Parse one IDX file (plain or .gz) into a uint8 array — native analog of
    ``data.mnist._read_idx``."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    ndim = ctypes.c_int(0)
    shape = (ctypes.c_longlong * 4)()
    rc = lib.nl_idx_info(path.encode(), ctypes.byref(ndim), shape)
    if rc != 0:
        raise ValueError(f"nl_idx_info({path!r}) failed with {rc}")
    dims = tuple(shape[i] for i in range(ndim.value))
    out = np.empty(int(np.prod(dims)), dtype=np.uint8)
    rc = lib.nl_idx_read(path.encode(), _as_ptr(out, ctypes.c_ubyte), out.size)
    if rc != 0:
        raise ValueError(f"nl_idx_read({path!r}) failed with {rc}")
    return out.reshape(dims)


def normalize(images_u8: np.ndarray, mean: float, std: float,
              num_threads: int = 4) -> np.ndarray:
    """uint8 [N,H,W] → normalized float32 [N,H,W,1] — native analog of
    ``data.mnist._normalize``."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    src = np.ascontiguousarray(images_u8, dtype=np.uint8)
    dst = np.empty(src.shape, dtype=np.float32)
    rc = lib.nl_normalize(_as_ptr(src, ctypes.c_ubyte), _as_ptr(dst, ctypes.c_float),
                          src.size, mean, std, num_threads)
    if rc != 0:
        raise ValueError(f"nl_normalize failed with {rc}")
    return dst[..., None]


def gather(images: np.ndarray, labels: np.ndarray, idx: np.ndarray,
           num_threads: int = 4) -> tuple[np.ndarray, np.ndarray]:
    """(images[idx], labels[idx]) via the threaded native gather — one DataLoader-worker
    batch assembly."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    images = np.ascontiguousarray(images, dtype=np.float32)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    sample_elems = int(np.prod(images.shape[1:]))
    out_i = np.empty((len(idx),) + images.shape[1:], dtype=np.float32)
    out_l = np.empty(len(idx), dtype=np.int32)
    rc = lib.nl_gather_f32(_as_ptr(images, ctypes.c_float), images.shape[0],
                           sample_elems, _as_ptr(idx, ctypes.c_int), len(idx),
                           _as_ptr(out_i, ctypes.c_float), num_threads)
    if rc == 0:
        rc = lib.nl_gather_i32(_as_ptr(labels, ctypes.c_int), labels.shape[0],
                               _as_ptr(idx, ctypes.c_int), len(idx),
                               _as_ptr(out_l, ctypes.c_int))
    if rc != 0:
        raise IndexError("gather index out of range")
    return out_i, out_l


class Prefetcher:
    """Threaded batch queue over a ``[steps, batch]`` index plan — the ``num_workers``
    prefetch pool (reference ``src/train_dist.py:43-45``) as a first-party C++ component.

    Iterates ``(images[batch], labels[batch])`` in plan order while worker threads gather
    ahead into a bounded ring. Use as a context manager or iterate to exhaustion.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray, plan: np.ndarray, *,
                 num_workers: int = 4, capacity: int = 8):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader unavailable")
        self._lib = lib
        # Keep references so the buffers outlive the C++ threads reading them.
        self._images = np.ascontiguousarray(images, dtype=np.float32)
        self._labels = np.ascontiguousarray(labels, dtype=np.int32)
        plan = np.ascontiguousarray(plan, dtype=np.int32)
        if plan.ndim != 2:
            raise ValueError(f"plan must be [steps, batch], got shape {plan.shape}")
        self.steps, self.batch = plan.shape
        self._sample_shape = self._images.shape[1:]
        sample_elems = int(np.prod(self._sample_shape))
        self._handle = lib.nl_prefetcher_create(
            _as_ptr(self._images, ctypes.c_float), _as_ptr(self._labels, ctypes.c_int),
            self._images.shape[0], sample_elems, _as_ptr(plan, ctypes.c_int),
            self.steps, self.batch, num_workers, capacity)
        if not self._handle:
            raise RuntimeError("nl_prefetcher_create failed")

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        while True:
            if self._handle is None:
                raise ValueError("Prefetcher is closed")
            out_i = np.empty((self.batch,) + self._sample_shape, dtype=np.float32)
            out_l = np.empty(self.batch, dtype=np.int32)
            step = self._lib.nl_prefetcher_next(
                self._handle, _as_ptr(out_i, ctypes.c_float),
                _as_ptr(out_l, ctypes.c_int))
            if step == -1:
                return
            if step == -2:
                raise IndexError("prefetcher: plan index out of range")
            yield out_i, out_l

    def close(self) -> None:
        if self._handle:
            self._lib.nl_prefetcher_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
