// Native host-side data-loader runtime.
//
// TPU-native counterpart of the C++ the reference leans on for input handling: torch's
// DataLoader worker pool (num_workers=4, pin_memory=True, reference src/train_dist.py:43-45)
// and torchvision's on-disk MNIST cache reader (reference src/train.py:26-31). That machinery
// lives in libtorch C++; here the same roles — parse the raw IDX files, normalize pixels,
// assemble shuffled batches ahead of the training loop with a threaded prefetcher — are a
// small first-party C++17 library reached from Python over a C ABI (ctypes, no pybind11).
//
// Everything is optional: csed_514_project_distributed_training_using_pytorch_tpu.data
// falls back to the pure-numpy implementations when this library is not built; tests assert
// bit-exact parity between the two paths.
//
// Build: see build.py next to this file (g++ -O3 -shared -fPIC -std=c++17 -pthread -lz).

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// Run fn(chunk_begin, chunk_end) over [0, n) on up to max_threads threads.
void parallel_for(long long n, int max_threads,
                  const std::function<void(long long, long long)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int nt = max_threads > 0 ? max_threads : 1;
  if (hw > 0 && static_cast<unsigned>(nt) > hw) nt = static_cast<int>(hw);
  if (nt <= 1 || n < 2) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  long long chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    long long b = t * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    threads.emplace_back(fn, b, e);
  }
  for (auto& th : threads) th.join();
}

uint32_t read_be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) | (uint32_t(p[2]) << 8) |
         uint32_t(p[3]);
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------------------
// IDX file reading (zlib's gzopen transparently reads both .gz and plain files).
// Layout (classic LeCun IDX): u32 magic (0x00 0x08=ubyte ndim), ndim × u32 big-endian dims,
// then the payload bytes. Mirrors the Python parser in data/mnist.py:_read_idx.
// ---------------------------------------------------------------------------------------

// Parse the header: fills ndim and shape[0..ndim). Returns 0 on success, negative on error.
int nl_idx_info(const char* path, int* ndim, long long* shape) {
  gzFile f = gzopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (gzread(f, hdr, 4) != 4) { gzclose(f); return -2; }
  if (hdr[0] != 0 || hdr[1] != 0 || hdr[2] != 0x08) { gzclose(f); return -3; }
  int nd = hdr[3];
  if (nd < 1 || nd > 4) { gzclose(f); return -3; }
  for (int i = 0; i < nd; ++i) {
    unsigned char dim[4];
    if (gzread(f, dim, 4) != 4) { gzclose(f); return -2; }
    shape[i] = read_be32(dim);
  }
  *ndim = nd;
  gzclose(f);
  return 0;
}

// Read the payload (n bytes after the header) into out. Returns 0 on success.
int nl_idx_read(const char* path, unsigned char* out, long long n) {
  gzFile f = gzopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (gzread(f, hdr, 4) != 4) { gzclose(f); return -2; }
  int nd = hdr[3];
  if (gzseek(f, 4 + 4 * nd, SEEK_SET) < 0) { gzclose(f); return -2; }
  long long got = 0;
  while (got < n) {
    int chunk = static_cast<int>(std::min<long long>(n - got, 1 << 24));
    int r = gzread(f, out + got, chunk);
    if (r <= 0) { gzclose(f); return -2; }
    got += r;
  }
  gzclose(f);
  return 0;
}

// ---------------------------------------------------------------------------------------
// Normalization: uint8 pixels -> (x/255 - mean)/std float32 (reference src/train.py:28-30),
// threaded over samples. Output layout equals input layout (the [..., 1] channel axis added
// on the Python side is a free reshape).
// ---------------------------------------------------------------------------------------

int nl_normalize(const unsigned char* src, float* dst, long long n, float mean,
                 float stddev, int num_threads) {
  if (stddev == 0.0f) return -1;
  // Same operation order as the numpy path (x/255, -mean, /std) for bit-exact parity.
  parallel_for(n, num_threads, [&](long long b, long long e) {
    for (long long i = b; i < e; ++i)
      dst[i] = (float(src[i]) / 255.0f - mean) / stddev;
  });
  return 0;
}

// ---------------------------------------------------------------------------------------
// Batch gather: out[i] = images[idx[i]] — the DataLoader worker's per-batch job once
// transforms are pre-applied. Threaded over batch rows.
// ---------------------------------------------------------------------------------------

int nl_gather_f32(const float* images, long long n_images, long long sample_elems,
                  const int* idx, long long batch, float* out, int num_threads) {
  std::atomic<int> bad{0};
  parallel_for(batch, num_threads, [&](long long b, long long e) {
    for (long long i = b; i < e; ++i) {
      long long j = idx[i];
      if (j < 0 || j >= n_images) { bad.store(1); continue; }
      std::memcpy(out + i * sample_elems, images + j * sample_elems,
                  sizeof(float) * sample_elems);
    }
  });
  return bad.load() ? -1 : 0;
}

int nl_gather_i32(const int* labels, long long n, const int* idx, long long batch,
                  int* out) {
  for (long long i = 0; i < batch; ++i) {
    long long j = idx[i];
    if (j < 0 || j >= n) return -1;
    out[i] = labels[j];
  }
  return 0;
}

// ---------------------------------------------------------------------------------------
// Threaded batch prefetcher — the worker-pool analog (num_workers, prefetching queue).
// Workers claim steps of a [steps, batch] index plan, gather image/label batches into a
// bounded ring of slots; the consumer drains slots in step order.
// ---------------------------------------------------------------------------------------

namespace {

enum SlotState { kFree = 0, kFilling = 1, kReady = 2 };

struct Prefetcher {
  const float* images;
  const int* labels;
  long long n_examples, sample_elems, steps, batch;
  std::vector<int> plan;  // owned copy: [steps * batch]

  int capacity;
  std::vector<std::vector<float>> img_slots;
  std::vector<std::vector<int>> lab_slots;
  std::vector<int> state;           // SlotState per slot
  std::vector<long long> slot_step; // step id occupying the slot
  long long next_consume = 0;
  std::atomic<long long> next_claim{0};
  std::atomic<int> error{0};
  std::atomic<bool> stopping{false};
  std::mutex m;
  std::condition_variable cv_free, cv_ready;
  std::vector<std::thread> workers;

  void worker_loop() {
    for (;;) {
      long long s = next_claim.fetch_add(1);
      if (s >= steps || stopping) return;
      int slot = static_cast<int>(s % capacity);
      {
        std::unique_lock<std::mutex> lk(m);
        cv_free.wait(lk, [&] {
          return stopping || (state[slot] == kFree && s - next_consume <
                              static_cast<long long>(capacity));
        });
        if (stopping) return;
        state[slot] = kFilling;
        slot_step[slot] = s;
      }
      const int* idx = plan.data() + s * batch;
      float* img_out = img_slots[slot].data();
      int* lab_out = lab_slots[slot].data();
      for (long long i = 0; i < batch; ++i) {
        long long j = idx[i];
        if (j < 0 || j >= n_examples) { error.store(1); j = 0; }
        std::memcpy(img_out + i * sample_elems, images + j * sample_elems,
                    sizeof(float) * sample_elems);
        lab_out[i] = labels[j];
      }
      {
        std::lock_guard<std::mutex> lk(m);
        state[slot] = kReady;
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

void* nl_prefetcher_create(const float* images, const int* labels, long long n_examples,
                           long long sample_elems, const int* plan, long long steps,
                           long long batch, int num_workers, int capacity) {
  if (steps <= 0 || batch <= 0 || capacity <= 0 || num_workers <= 0) return nullptr;
  auto* p = new Prefetcher();
  p->images = images;
  p->labels = labels;
  p->n_examples = n_examples;
  p->sample_elems = sample_elems;
  p->steps = steps;
  p->batch = batch;
  p->plan.assign(plan, plan + steps * batch);
  p->capacity = capacity;
  p->img_slots.assign(capacity, std::vector<float>(batch * sample_elems));
  p->lab_slots.assign(capacity, std::vector<int>(batch));
  p->state.assign(capacity, kFree);
  p->slot_step.assign(capacity, -1);
  for (int w = 0; w < num_workers; ++w)
    p->workers.emplace_back(&Prefetcher::worker_loop, p);
  return p;
}

// Copy the next batch (in step order) into out buffers. Returns the step index, -1 when the
// plan is exhausted, -2 on an out-of-range index in the plan.
long long nl_prefetcher_next(void* handle, float* out_images, int* out_labels) {
  auto* p = static_cast<Prefetcher*>(handle);
  if (p->next_consume >= p->steps) return -1;
  long long s = p->next_consume;
  int slot = static_cast<int>(s % p->capacity);
  {
    std::unique_lock<std::mutex> lk(p->m);
    p->cv_ready.wait(lk, [&] { return p->state[slot] == kReady && p->slot_step[slot] == s; });
  }
  std::memcpy(out_images, p->img_slots[slot].data(),
              sizeof(float) * p->batch * p->sample_elems);
  std::memcpy(out_labels, p->lab_slots[slot].data(), sizeof(int) * p->batch);
  {
    std::lock_guard<std::mutex> lk(p->m);
    p->state[slot] = kFree;
    p->slot_step[slot] = -1;
    p->next_consume = s + 1;
  }
  p->cv_free.notify_all();
  return p->error.load() ? -2 : s;
}

void nl_prefetcher_destroy(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->m);
    p->stopping = true;
  }
  p->cv_free.notify_all();
  p->next_claim.store(p->steps);  // stop claimers that haven't checked stopping yet
  for (auto& w : p->workers) w.join();
  delete p;
}

int nl_abi_version() { return 1; }

}  // extern "C"
