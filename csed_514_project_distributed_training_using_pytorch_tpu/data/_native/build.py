"""Build the native loader shared library (g++, no pybind11 — plain C ABI for ctypes).

Invoked lazily on first import of ``data.native`` and cached by source mtime; also runnable
directly: ``python -m csed_514_project_distributed_training_using_pytorch_tpu.data._native.build``.
"""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_DIR, "loader.cc")
LIBRARY = os.path.join(_DIR, "libnativeloader.so")


def build(force: bool = False, quiet: bool = True) -> str | None:
    """Compile loader.cc → libnativeloader.so if stale/missing. Returns the library path, or
    None when the toolchain is unavailable or compilation fails (callers fall back to numpy).
    """
    if not force and os.path.exists(LIBRARY):
        try:
            if os.path.getmtime(LIBRARY) >= os.path.getmtime(SOURCE):
                return LIBRARY
        except OSError:
            return LIBRARY  # source missing (e.g. binary-only install): use the built .so
    # Compile to a per-process temp path, then atomically os.replace into place: every
    # process runs this same module (the framework's launch contract), so concurrent
    # builders must never interleave writes into the .so another process may be dlopening.
    tmp = f"{LIBRARY}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           SOURCE, "-o", tmp, "-lz"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            if not quiet:
                raise RuntimeError(f"native loader build failed:\n{proc.stderr}")
            return None
        os.replace(tmp, LIBRARY)
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return LIBRARY


if __name__ == "__main__":
    path = build(force=True, quiet=False)
    print(f"built {path}")
