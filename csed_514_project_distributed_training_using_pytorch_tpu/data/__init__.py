"""Data ingest + host input pipeline.

TPU-native replacement for the reference's ``torchvision.datasets.MNIST`` + ``DataLoader``
stack (reference ``src/train.py:25-41``, ``src/train_dist.py:15-47``; worker pool
``num_workers=4``/``pin_memory`` at ``src/train_dist.py:43-45``). Strategy per SURVEY.md §3.5:
load the full dataset once into host numpy arrays, normalize once, and feed the device with
epoch-seeded permutations — no per-sample transform pipeline, no worker processes. A native C++
batch-assembly path (``data/_native``) covers the DataLoader-worker-pool role at speed.
"""

from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
    MNIST_MEAN,
    MNIST_STD,
    Dataset,
    load_mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data.loader import (
    BatchLoader,
    iter_plan_batches,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data.download import (
    download_mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data.stream import (
    StreamLoader,
    eval_tokens,
)

__all__ = ["MNIST_MEAN", "MNIST_STD", "Dataset", "load_mnist", "BatchLoader",
           "download_mnist", "iter_plan_batches", "StreamLoader", "eval_tokens"]
