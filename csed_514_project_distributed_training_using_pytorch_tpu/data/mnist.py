"""MNIST ingest: first-party IDX parser + deterministic synthetic fallback.

The reference obtains MNIST through ``torchvision.datasets.MNIST(download=True)``
(reference ``src/train.py:26-31``, ``src/train_dist.py:22-30``) and normalizes with
``Normalize((0.1307,), (0.3081,))`` (``src/train.py:28-30``). This module:

- parses the raw IDX files (``train-images-idx3-ubyte[.gz]`` etc.) directly — no torchvision —
  from ``<data_dir>`` or ``<data_dir>/MNIST/raw`` (torchvision's cache layout), so a
  torchvision-downloaded cache is reusable as-is;
- applies the same normalization constants once, ahead of time, to the whole array;
- if no IDX files exist and the environment has no network (this build environment has zero
  egress), synthesizes a deterministic MNIST-shaped dataset (60k/10k, 28×28 grayscale digits
  rendered from a built-in glyph font with random scale/shift/intensity/noise). The synthetic
  set is learnable to high accuracy by the reference CNN, so convergence tests, loss curves,
  and wall-clock benchmarks (identical FLOPs — same shapes/dtypes) all remain meaningful.
  ``Dataset.source`` records which path produced the data.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass

import numpy as np

MNIST_MEAN = 0.1307  # reference src/train.py:29
MNIST_STD = 0.3081   # reference src/train.py:29

_IDX_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}

# 5x7 bitmap glyphs for digits 0-9 (rows of 5 bits, MSB = leftmost pixel).
_GLYPHS = {
    0: (0b01110, 0b10001, 0b10011, 0b10101, 0b11001, 0b10001, 0b01110),
    1: (0b00100, 0b01100, 0b00100, 0b00100, 0b00100, 0b00100, 0b01110),
    2: (0b01110, 0b10001, 0b00001, 0b00010, 0b00100, 0b01000, 0b11111),
    3: (0b11111, 0b00010, 0b00100, 0b00010, 0b00001, 0b10001, 0b01110),
    4: (0b00010, 0b00110, 0b01010, 0b10010, 0b11111, 0b00010, 0b00010),
    5: (0b11111, 0b10000, 0b11110, 0b00001, 0b00001, 0b10001, 0b01110),
    6: (0b00110, 0b01000, 0b10000, 0b11110, 0b10001, 0b10001, 0b01110),
    7: (0b11111, 0b00001, 0b00010, 0b00100, 0b01000, 0b01000, 0b01000),
    8: (0b01110, 0b10001, 0b10001, 0b01110, 0b10001, 0b10001, 0b01110),
    9: (0b01110, 0b10001, 0b10001, 0b01111, 0b00001, 0b00010, 0b01100),
}


@dataclass(frozen=True)
class Dataset:
    """A fully-materialized split: normalized NHWC images + integer labels."""

    images: np.ndarray  # [N, 28, 28, 1] float32, normalized
    labels: np.ndarray  # [N] int32
    source: str         # "idx" (real MNIST files) or "synthetic"

    def __len__(self) -> int:
        return self.images.shape[0]


def truncate(ds: Dataset, n: int) -> Dataset:
    """First-``n``-examples view of a split (``n <= 0`` means the whole split). Dev/CI
    shortening knob — the reference always trains the full split."""
    if n <= 0 or n >= len(ds):
        return ds
    return Dataset(ds.images[:n], ds.labels[:n], ds.source)


def _read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally gzipped). Format: the classic LeCun IDX layout."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dtype_code = (magic >> 8) & 0xFF
        if dtype_code != 0x08:  # unsigned byte — the only type MNIST uses
            raise ValueError(f"{path}: unsupported IDX dtype 0x{dtype_code:02x}")
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    expected = int(np.prod(shape))
    if data.size != expected:
        raise ValueError(f"{path}: IDX payload size mismatch — header {shape} needs "
                         f"{expected} bytes, got {data.size} (truncated download or "
                         f"corrupt file)")
    return data.reshape(shape)


def _find_idx_file(data_dir: str, stem: str) -> str | None:
    for sub in ("", "MNIST/raw"):
        for suffix in ("", ".gz"):
            path = os.path.join(data_dir, sub, stem + suffix)
            if os.path.exists(path):
                return path
    return None


def _normalize(images_u8: np.ndarray) -> np.ndarray:
    """uint8 [N,H,W] -> normalized float32 [N,H,W,1] (reference src/train.py:28-30)."""
    x = images_u8.astype(np.float32) / 255.0
    x = (x - MNIST_MEAN) / MNIST_STD
    return x[..., None]


def _synthesize_split(n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Render n MNIST-shaped digit images deterministically (vectorized numpy).

    Each sample: a digit glyph upsampled ×2 or ×3 (nearest), placed on a 28×28 canvas at a
    random offset, scaled by a random intensity, plus Gaussian pixel noise. Returns
    (uint8 images [n,28,28], int labels [n]).
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n, dtype=np.int64)

    # Glyph bank: [10 digits, 2 scales, 36, 36] uint8 canvases with the glyph centred.
    pad = 36
    bank = np.zeros((10, 2, pad, pad), dtype=np.uint8)
    for d, rows in _GLYPHS.items():
        glyph = np.array([[(r >> (4 - c)) & 1 for c in range(5)] for r in rows],
                         dtype=np.uint8)
        for si, s in enumerate((2, 3)):
            up = np.kron(glyph, np.ones((s, s), dtype=np.uint8)) * 255
            h, w = up.shape
            y0, x0 = (pad - h) // 2, (pad - w) // 2
            bank[d, si, y0:y0 + h, x0:x0 + w] = up

    scales = rng.integers(0, 2, size=n)
    base = bank[labels, scales]  # [n, 36, 36]

    # Random crop of the 28×28 window == random shift of the digit by ±4 px.
    off_y = rng.integers(0, 9, size=n)
    off_x = rng.integers(0, 9, size=n)
    iy = off_y[:, None] + np.arange(28)[None, :]          # [n, 28]
    ix = off_x[:, None] + np.arange(28)[None, :]          # [n, 28]
    imgs = base[np.arange(n)[:, None, None], iy[:, :, None], ix[:, None, :]]

    imgs = imgs.astype(np.float32) * rng.uniform(0.6, 1.0, size=(n, 1, 1)).astype(np.float32)
    imgs += rng.normal(0.0, 12.0, size=imgs.shape).astype(np.float32)
    return np.clip(imgs, 0, 255).astype(np.uint8), labels.astype(np.int64)


def load_mnist(data_dir: str = "files", *, synthetic_seed: int = 514,
               allow_synthetic: bool = True) -> tuple[Dataset, Dataset]:
    """Load (train, test) splits: real IDX files if present, else the synthetic fallback.

    Mirrors the data the reference trains on: 60,000 train / 10,000 test 28×28 grayscale
    images, normalized with (0.1307, 0.3081).
    """
    paths = {k: _find_idx_file(data_dir, stem) for k, stem in _IDX_FILES.items()}
    if all(paths.values()):
        # Prefer the native (C++) IDX reader — the first-party analog of torchvision's
        # C++-backed cache read (see data/native.py); the numpy parser is the bit-exact
        # fallback when the library isn't built.
        from csed_514_project_distributed_training_using_pytorch_tpu.data import native
        read = native.load_idx if native.available() else _read_idx
        train_x = read(paths["train_images"])
        train_y = read(paths["train_labels"]).astype(np.int64)
        test_x = read(paths["test_images"])
        test_y = read(paths["test_labels"]).astype(np.int64)
        source = "idx"
    elif allow_synthetic:
        train_x, train_y = _synthesize_split(60_000, synthetic_seed)
        test_x, test_y = _synthesize_split(10_000, synthetic_seed + 1)
        source = "synthetic"
    else:
        raise FileNotFoundError(
            f"no MNIST IDX files under {data_dir!r} and synthetic fallback disabled")

    from csed_514_project_distributed_training_using_pytorch_tpu.data import native
    if native.available():
        norm = lambda x: native.normalize(x, MNIST_MEAN, MNIST_STD)
    else:
        norm = _normalize
    train = Dataset(norm(train_x), train_y.astype(np.int32), source)
    test = Dataset(norm(test_x), test_y.astype(np.int32), source)
    return train, test
