"""Host-side batch loader.

Replaces the reference's ``torch.utils.data.DataLoader`` (reference ``src/train.py:25-41``,
``src/train_dist.py:40-45``). Because the whole dataset is a resident numpy array (see
``data/mnist.py``), "loading" a batch is a single fancy-index gather — there is no per-sample
transform to hide, so no worker pool (``num_workers=4``, reference ``src/train_dist.py:43``) is
needed; the optional native C++ gather (``data/_native``) covers that role where the Python
gather ever matters. Shuffling follows the reference's two modes:

- single-process: ``shuffle=True`` per epoch (reference ``src/train.py:32``) — here an
  epoch-seeded permutation;
- distributed: sharding is delegated to ``parallel.ShardedSampler`` (the
  ``DistributedSampler`` contract) and the loader itself does not shuffle, mirroring the
  reference's ``shuffle=False  # Must be False!`` (``src/train_dist.py:41-42``).

``drop_last`` defaults to False like torch's: the final short batch is emitted (60,000/64 →
937×64 + 1×32, two jit specializations — the only two shapes ever compiled).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Iterator

import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import Dataset
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)


def iter_plan_batches(dataset: Dataset, plan: np.ndarray, *,
                      num_workers: int = 4) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Yield one ``(images, labels)`` host batch per row of a ``[steps, batch]`` index
    plan, through the native threaded prefetcher when built (the ``num_workers=4``
    DataLoader worker-pool analog, reference ``src/train_dist.py:43-45`` — workers gather
    ahead into a bounded ring while the consumer's previous batch is in flight), else a
    plain numpy gather. Used by both the single-process host pipeline and the
    distributed host-local feed."""
    from csed_514_project_distributed_training_using_pytorch_tpu.data import native
    if plan.shape[0] == 0:
        return
    if not native.available():
        for row in plan:
            yield dataset.images[row], dataset.labels[row]
        return
    with native.Prefetcher(dataset.images, dataset.labels, plan,
                           num_workers=num_workers) as pf:
        yield from pf


def _device_prefetch_iter(base: Iterator, depth: int) -> Iterator:
    """Double-buffered device feed: a daemon thread stages up to ``depth`` batches
    ahead — host gather plus ``jax.device_put`` — while the consumer's current
    batch is in flight, overlapping H2D transfer with compute (``depth=2`` is
    classic double buffering). Order and values are exactly the base iterator's
    (pinned in ``tests/test_data.py``); worker exceptions re-raise at the
    consumer's next pull; abandoning the iterator early unblocks and stops the
    worker."""
    import jax

    q: queue_mod.Queue = queue_mod.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False

    def worker():
        try:
            for batch in base:
                if not put(("item", tuple(jax.device_put(b) for b in batch))):
                    return
            put(("done", None))
        except BaseException as e:               # re-raised by the consumer
            put(("error", e))

    thread = threading.Thread(target=worker, daemon=True, name="loader-prefetch")
    thread.start()
    try:
        while True:
            kind, payload = q.get()
            if kind == "done":
                return
            if kind == "error":
                raise payload
            yield payload
    finally:
        stop.set()


class BatchLoader:
    """Iterates (images, labels) numpy batches in a sampler-defined order.

    ``set_epoch`` mirrors ``train_loader.sampler.set_epoch(i)`` (reference
    ``src/train_dist.py:72``); for the single-process shuffle case the same mechanism provides
    the per-epoch reshuffle.

    ``prefetch=N`` (0 = off, the default) inserts the double-buffered device
    pipeline: batches arrive as device-resident ``jax.Array``s, gathered and
    ``device_put`` N deep on a background thread while the consumer's batch is in
    flight. Batch order and values are unchanged — only residency and overlap.

    Stall accounting: every second the CONSUMER spends blocked pulling the next
    batch — the prefetch queue empty, the native prefetcher behind, or the plain
    gather itself — accumulates in ``wait_s`` (read the per-window delta with
    ``pop_wait_s()``). Before this the loader's stalls were invisible: a
    data-starved run reported ``data_s ~ 0`` and the goodput ``data_wait``
    segment read zero while the stall hid inside execute/idle (DESIGN.md §26).
    """

    def __init__(self, dataset: Dataset, batch_size: int, *,
                 sampler: ShardedSampler | None = None, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False, prefetch: int = 0):
        if sampler is not None and shuffle:
            raise ValueError("shuffle must be False when a sampler is given "
                             "(reference src/train_dist.py:41-42)")
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.prefetch = int(prefetch)
        self.sampler = sampler or ShardedSampler(
            len(dataset), num_replicas=1, rank=0, shuffle=shuffle, seed=seed)
        self._epoch = 0
        #: Consumer-blocked seconds (queue waits + gathers); see class docstring.
        self.wait_s = 0.0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def pop_wait_s(self) -> float:
        """Return and reset the accumulated consumer-blocked seconds — the
        per-epoch ``data_s`` charge the trainers emit (goodput's ``data_wait``
        input, obs/goodput.py)."""
        w, self.wait_s = self.wait_s, 0.0
        return w

    def _timed(self, base: Iterator) -> Iterator:
        """Wrap an iterator so time the consumer spends blocked in ``next()``
        accumulates in ``wait_s``. Pull-side by construction: overlapped
        producer work (prefetch threads ahead of the consumer) charges
        nothing — only actual stalls count."""
        while True:
            t0 = time.perf_counter()
            try:
                item = next(base)
            except StopIteration:
                self.wait_s += time.perf_counter() - t0
                return
            self.wait_s += time.perf_counter() - t0
            yield item

    def __len__(self) -> int:
        n = self.sampler.num_samples
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        if self.prefetch:
            return self._timed(
                _device_prefetch_iter(self._host_iter(), self.prefetch))
        return self._timed(self._host_iter())

    def _host_iter(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        from csed_514_project_distributed_training_using_pytorch_tpu.data import native
        if native.available():
            # Threads only pay off once a batch is memcpy-heavy; below that the native
            # call runs single-threaded inline (no per-batch thread spawn/join).
            sample_bytes = int(np.prod(self.dataset.images.shape[1:])) * 4
            threads = 4 if self.batch_size * sample_bytes >= (4 << 20) else 1
            gather = lambda imgs, labs, idx: native.gather(imgs, labs, idx,
                                                           num_threads=threads)
        else:
            gather = lambda imgs, labs, idx: (imgs[idx], labs[idx])
        indices = self.sampler.epoch_indices(self._epoch)
        n = len(indices)
        end = n - n % self.batch_size if self.drop_last else n
        for start in range(0, end, self.batch_size):
            idx = indices[start:start + self.batch_size]
            yield gather(self.dataset.images, self.dataset.labels, idx)

    def prefetch_iter(self, epoch: int | None = None,
                      num_workers: int = 4) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Iterate full batches through the native threaded prefetcher (the
        ``num_workers=4`` DataLoader worker pool analog, reference
        ``src/train_dist.py:43-45``); falls back to the plain ``__iter__`` gather when the
        native library isn't built. Full batches only (the plan is rectangular)."""
        # allow_empty so a split smaller than one batch yields zero full batches here and
        # leaves the ragged tail to the caller — identical contract to the scan fast path
        # (advisor finding r1: the old allow_empty=False raised where the scan path
        # trained fine).
        plan = self.epoch_index_matrix(epoch, allow_empty=True)
        yield from self._timed(
            iter_plan_batches(self.dataset, plan, num_workers=num_workers))

    def epoch_index_matrix(self, epoch: int | None = None, steps_multiple: int = 1,
                           allow_empty: bool = False) -> np.ndarray:
        """This epoch's order as a ``[num_steps, batch_size]`` index matrix for the
        device-resident fast path (``lax.scan`` over gathered batches): full batches only,
        optionally truncated to a multiple of ``steps_multiple`` (e.g. ``log_interval``).
        ``epoch=None`` uses the ``set_epoch`` value. With zero full batch groups, raises —
        or returns a ``[0, batch_size]`` matrix when ``allow_empty`` (callers that train the
        ragged tail separately, e.g. the single-process trainer's drop_last=False path)."""
        indices = self.sampler.epoch_indices(self._epoch if epoch is None else epoch)
        steps = len(indices) // self.batch_size
        steps -= steps % steps_multiple
        if steps == 0 and not allow_empty:
            raise ValueError(
                f"no full batch groups: {len(indices)} samples, batch {self.batch_size}, "
                f"steps_multiple {steps_multiple} — lower batch_size or steps_multiple")
        return indices[:steps * self.batch_size].reshape(steps, self.batch_size)
