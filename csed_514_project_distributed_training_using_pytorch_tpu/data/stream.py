"""Sharded streaming token corpus: on-disk shards + a deterministic cursor loader.

The reference (and every trainer here until now) feeds from a resident in-memory
array — fine for MNIST, wrong shape for a corpus that outlives host RAM or a run
that outlives its process. This module is the data half of continuous deployment
(DESIGN.md §26): a corpus directory of fixed-length token-sequence shards
(``tools/build_corpus.py`` writes them) and a :class:`StreamLoader` whose entire
epoch order is a PURE function of ``(seed, epoch)`` — the same contract
``parallel/sampler.py`` pins for the in-memory trainers, extended with a durable
**cursor** ``(shard, intra-shard offset, epoch-plan CRC)`` that
``utils/checkpoint.py::save_versioned`` keys into the checkpoint manifest.
Preemption-resume re-derives the plan from ``(seed, epoch)``, seeks to the
cursor WITHOUT touching the skipped shards, verifies the derived position
against the stored one (corpus drift under a checkpoint is an error, not a
silent reshuffle), and replays the remaining batch stream bitwise.

Stall accounting: every second the consumer spends blocked on this loader —
shard reads, integrity hashing, the optional ``throttle_s`` brake — accumulates
in ``wait_s`` and is charged by the trainers to the epoch event's ``data_s``,
which ``obs/goodput.py`` rolls into the ``data_wait_s`` segment. Before this,
data-starved runs read ``data_wait ~ 0`` and the stall hid inside ``idle``.

Corpus layout (``corpus.json`` + numpy shard files, stdlib + numpy only)::

    corpus.json   {"version": 1, "tokenizer": "byte", "vocab": V, "seq_len": S,
                   "shards": [{"file": "shard_0000.npy", "sequences": N,
                               "sha256": "..."}, ...],
                   "eval": {"file": "eval.npy", "sequences": M, "sha256": ...}}
    shard_*.npy   uint16 [N, S] token-id matrices (BOS is NOT stored; models
                  prepend it — vocab ids are 0..V-1)

This module is jax-free: the loader yields host numpy batches; residency is the
trainer's business.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import time
import zlib

import numpy as np

META_NAME = "corpus.json"

#: Cursor schema version — bump on any change to the fields or their meaning.
CURSOR_VERSION = 1


class CorpusError(ValueError):
    """A corpus directory that cannot be trusted: missing/torn meta, a shard
    whose bytes do not match the recorded sha256, or a resume cursor that the
    re-derived epoch plan contradicts (the corpus changed under a checkpoint)."""


def load_meta(corpus_dir: str) -> dict:
    """Read + sanity-check ``corpus.json``. Raises :class:`CorpusError` with the
    offending path (never a raw KeyError) — this is the first call of every
    consumer and must name what is wrong."""
    path = os.path.join(corpus_dir, META_NAME)
    try:
        with open(path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise CorpusError(f"unreadable corpus meta {path}: {e}") from None
    for key in ("version", "vocab", "seq_len", "shards"):
        if key not in meta:
            raise CorpusError(f"corpus meta {path} missing {key!r}")
    if not meta["shards"]:
        raise CorpusError(f"corpus meta {path} lists zero shards")
    return meta


def _load_shard(corpus_dir: str, entry: dict, *, verify: bool = True) -> np.ndarray:
    path = os.path.join(corpus_dir, entry["file"])
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise CorpusError(f"unreadable corpus shard {path}: {e}") from None
    if verify and entry.get("sha256"):
        digest = hashlib.sha256(raw).hexdigest()
        if digest != entry["sha256"]:
            raise CorpusError(
                f"corpus shard {path} sha256 mismatch (manifest "
                f"{entry['sha256'][:12]}..., file {digest[:12]}...) — the corpus "
                f"changed under its meta; rebuild with tools/build_corpus.py")
    arr = np.load(io.BytesIO(raw), allow_pickle=False)
    if arr.ndim != 2:
        raise CorpusError(f"corpus shard {path} is {arr.ndim}-d, expected [N, S]")
    return arr


def eval_tokens(corpus_dir: str, *, verify: bool = True) -> np.ndarray | None:
    """The held-out eval split as one ``[M, S]`` int32 array, or None when the
    corpus was built without one (``--eval-frac 0``)."""
    meta = load_meta(corpus_dir)
    entry = meta.get("eval")
    if not entry:
        return None
    return _load_shard(corpus_dir, entry, verify=verify).astype(np.int32)


class StreamLoader:
    """Deterministic shard-shuffling batch stream over a token corpus.

    The epoch plan — shard visit order plus one intra-shard permutation per
    shard — is drawn from ``default_rng(SeedSequence([seed, epoch]))`` exactly
    once per epoch, eagerly (index-level only, cheap: the plan never loads
    token bytes). The epoch's sequence stream is the concatenation of the
    permuted shards in visit order; batches are consecutive ``batch_size``
    slices of that stream; the ragged tail is dropped so every epoch has the
    same ``batches_per_epoch`` (the compiled epoch program's step count must
    not wobble across epochs).

    Shard DATA loads lazily, one shard resident at a time, sha256-verified on
    first touch per epoch. ``throttle_s`` sleeps that long per batch — the
    data-starvation brake the goodput regression tests (and the bench's
    throttled leg) use to prove ``data_wait`` is actually measured.
    """

    def __init__(self, corpus_dir: str, batch_size: int, *, seed: int = 0,
                 throttle_s: float = 0.0, verify: bool = True):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.corpus_dir = corpus_dir
        self.meta = load_meta(corpus_dir)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.throttle_s = float(throttle_s)
        self.verify = verify
        self.vocab = int(self.meta["vocab"])
        self.seq_len = int(self.meta["seq_len"])
        self._shards = list(self.meta["shards"])
        self._sizes = [int(e["sequences"]) for e in self._shards]
        self.num_sequences = sum(self._sizes)
        if self.num_sequences < self.batch_size:
            raise CorpusError(
                f"corpus {corpus_dir} has {self.num_sequences} sequences — "
                f"fewer than one batch of {self.batch_size}")
        #: Seconds the consumer spent blocked on this loader (reads, hashing,
        #: throttle). Monotonic; read the per-window delta via pop_wait_s().
        self.wait_s = 0.0
        # One-slot RAW shard cache: the visit order touches each shard once
        # per epoch, so a single slot is a perfect within-epoch cache and a
        # best-effort cross-epoch one.
        self._cached: tuple[int, np.ndarray] | None = None

    # -- epoch plan (pure in (seed, epoch)) ----------------------------------

    @property
    def batches_per_epoch(self) -> int:
        return self.num_sequences // self.batch_size

    def epoch_plan(self, epoch: int) -> dict:
        """The epoch's full order: ``{"order": shard visit order,
        "perms": {shard: permutation}, "crc": plan digest}``. Index-level only
        — no token bytes. The CRC digests the order and every permutation, so
        two corpora that merely LOOK alike (same shard count/sizes) still
        collide only if the actual plan is identical."""
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(epoch)]))
        order = rng.permutation(len(self._shards)).astype(np.int64)
        perms = {int(s): rng.permutation(self._sizes[int(s)]).astype(np.int64)
                 for s in order}
        crc = zlib.crc32(order.tobytes())
        for s in order:
            crc = zlib.crc32(perms[int(s)].tobytes(), crc)
        return {"order": order, "perms": perms, "crc": int(crc)}

    def cursor(self, epoch: int, batch: int, *, plan: dict | None = None) -> dict:
        """The durable resume position BEFORE batch ``batch`` of ``epoch``:
        which shard the stream is inside, how many of its sequences this epoch
        already consumed, and the epoch-plan CRC that pins the shuffle RNG
        state (the plan is pure in ``(seed, epoch)``, so the CRC *is* the RNG
        state, one derivation step early). This dict is what
        ``save_versioned(cursor=...)`` keys into the checkpoint manifest."""
        plan = plan or self.epoch_plan(epoch)
        pos = int(batch) * self.batch_size
        if not 0 <= pos <= self.num_sequences:
            raise ValueError(f"batch {batch} outside epoch "
                             f"(batches_per_epoch {self.batches_per_epoch})")
        shard, offset = int(plan["order"][0]), 0
        remaining = pos
        for s in plan["order"]:
            size = self._sizes[int(s)]
            if remaining < size:
                shard, offset = int(s), remaining
                break
            remaining -= size
        else:                               # pos == num_sequences: epoch end
            shard, offset = int(plan["order"][-1]), self._sizes[
                int(plan["order"][-1])]
        return {"version": CURSOR_VERSION, "kind": "stream",
                "seed": self.seed, "epoch": int(epoch), "batch": int(batch),
                "shard": shard, "offset": int(offset),
                "plan_crc": int(plan["crc"])}

    def verify_cursor(self, cursor: dict) -> tuple[int, int]:
        """Validate a manifest cursor against THIS corpus and return
        ``(epoch, batch)`` to resume from. The re-derived plan must agree with
        the stored shard/offset/CRC — a mismatch means the corpus (or seed)
        changed under the checkpoint, and silently resuming would feed a
        different stream than the one the checkpoint's step count paid for."""
        if cursor.get("kind") != "stream":
            raise CorpusError(f"not a stream cursor: {cursor!r}")
        if cursor.get("version") != CURSOR_VERSION:
            raise CorpusError(f"unknown cursor version {cursor.get('version')!r} "
                              f"(this build speaks {CURSOR_VERSION})")
        if int(cursor.get("seed", -1)) != self.seed:
            raise CorpusError(
                f"cursor seed {cursor.get('seed')} != loader seed {self.seed} — "
                f"resuming would reshuffle the stream")
        epoch, batch = int(cursor["epoch"]), int(cursor["batch"])
        derived = self.cursor(epoch, batch)
        for key in ("shard", "offset", "plan_crc"):
            if derived[key] != cursor.get(key):
                raise CorpusError(
                    f"cursor {key} mismatch (manifest {cursor.get(key)!r}, "
                    f"derived {derived[key]!r}) — the corpus changed under the "
                    f"checkpoint; rebuild or restart from scratch")
        return epoch, batch

    # -- batch stream --------------------------------------------------------

    def _shard_data(self, shard: int) -> np.ndarray:
        """One shard's RAW token matrix, sha256-verified on load, one-slot
        cached. Callers time the call — blocked time charges to ``wait_s``
        at the iter_batches site, once."""
        if self._cached and self._cached[0] == shard:
            return self._cached[1]
        data = _load_shard(self.corpus_dir, self._shards[shard],
                           verify=self.verify)
        self._cached = (shard, data)
        return data

    def iter_batches(self, epoch: int, *, start_batch: int = 0):
        """Yield ``[batch_size, seq_len]`` int32 batches of epoch ``epoch``,
        starting at ``start_batch`` (the cursor's resume entry point — skipped
        batches cost index arithmetic only, never shard reads). Time the
        consumer spends blocked in here (shard IO, hashing, throttle)
        accumulates in ``wait_s``."""
        plan = self.epoch_plan(epoch)
        b = self.batch_size
        # The permuted global stream as (shard, local index) pairs is implied;
        # walk it shard-by-shard, slicing batches across shard boundaries.
        start_pos = int(start_batch) * b
        end_pos = self.batches_per_epoch * b
        if start_pos >= end_pos:
            return
        pos = 0
        pending: list[np.ndarray] = []
        pending_n = 0
        for s in plan["order"]:
            s = int(s)
            size = self._sizes[s]
            if pos + size <= start_pos:     # wholly before the cursor: skip
                pos += size                  # without touching the bytes
                continue
            lo = max(0, start_pos - pos)
            hi = min(size, end_pos - pos)
            if lo < hi:
                t0 = time.perf_counter()
                data = self._shard_data(s)
                # Gather only the cursor-onward slice of the permutation — the
                # resume cost of a skipped prefix is index arithmetic, not IO.
                chunk = data[plan["perms"][s][lo:hi]]
                self.wait_s += time.perf_counter() - t0
                pending.append(chunk)
                pending_n += len(chunk)
                while pending_n >= b:
                    t1 = time.perf_counter()
                    flat = (pending[0] if len(pending) == 1
                            else np.concatenate(pending, axis=0))
                    batch, rest = flat[:b], flat[b:]
                    pending = [rest] if len(rest) else []
                    pending_n = len(rest)
                    if self.throttle_s:
                        time.sleep(self.throttle_s)
                    self.wait_s += time.perf_counter() - t1
                    yield np.ascontiguousarray(batch, dtype=np.int32)
            pos += size
            if pos >= end_pos:
                break

    def epoch_tokens(self, epoch: int, *, start_batch: int = 0) -> np.ndarray:
        """Materialize the epoch's (remaining) batch stream as one
        ``[n_batches * batch_size, seq_len]`` int32 array, in stream order —
        the device-resident feed for the scanned epoch program. The loader
        wall (reads, hashing, throttle) lands in ``wait_s`` as usual."""
        batches = list(self.iter_batches(epoch, start_batch=start_batch))
        if not batches:
            return np.zeros((0, self.seq_len), np.int32)
        return np.concatenate(batches, axis=0)

    def stream_digest(self, epoch: int, *, start_batch: int = 0) -> int:
        """CRC32 of the epoch's (remaining) token bytes in stream order — the
        cheap bitwise pin the deterministic-resume tests and the bench compare
        across a kill/resume boundary."""
        crc = 0
        for batch in self.iter_batches(epoch, start_batch=start_batch):
            crc = zlib.crc32(batch.tobytes(), crc)
        return int(crc)

    def pop_wait_s(self) -> float:
        """Return and reset the accumulated consumer-blocked seconds — the
        per-epoch ``data_s`` charge the trainers emit."""
        w, self.wait_s = self.wait_s, 0.0
        return w
