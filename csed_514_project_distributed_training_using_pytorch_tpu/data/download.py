"""First-party MNIST downloader — the analog of ``torchvision.datasets.MNIST(download=True)``
(reference ``src/train.py:26-31``: first run fetches the four IDX archives into the data
root before training).

Stdlib-only (``urllib``): mirror list tried in order, MD5 verification against
torchvision's pinned digests, atomic install (fetch to a temp file in the target dir,
verify, then ``os.replace``) so a crashed or failed download never leaves a truncated
archive where ``load_mnist`` would find it. Files already present and passing their
checksum are not re-fetched.

This build environment has zero egress, so the default mirrors are unreachable here —
the function is exercised in CI against a local HTTP server serving the golden IDX
fixture (``tests/test_download.py``), and works unchanged against the real mirrors on a
connected machine.
"""

from __future__ import annotations

import hashlib
import http.client
import os
import tempfile
import urllib.error
import urllib.request

# Same archive set and layout torchvision installs under <root>/MNIST/raw; we install
# directly into <data_dir>, which load_mnist also searches (data/mnist.py).
FILES = (
    "train-images-idx3-ubyte.gz",
    "train-labels-idx1-ubyte.gz",
    "t10k-images-idx3-ubyte.gz",
    "t10k-labels-idx1-ubyte.gz",
)

# Mirrors in preference order (the classic yann.lecun.com host throttles/403s).
DEFAULT_MIRRORS = (
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
)

# torchvision's pinned MD5 digests for the four archives.
MD5S = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download_mnist(data_dir: str = "files", *,
                   mirrors: tuple[str, ...] = DEFAULT_MIRRORS,
                   checksums: dict[str, str] | None = None,
                   timeout: float = 30.0) -> list[str]:
    """Ensure the four MNIST IDX archives exist (and verify) under ``data_dir``.

    ``checksums`` maps filename -> expected MD5; defaults to torchvision's pinned
    digests (pass ``{}`` to skip verification, e.g. for non-canonical fixtures).
    Returns the four local paths. A checksum mismatch counts as that mirror failing
    (the corrupt download is removed and the next mirror tried); when every mirror
    fails for a file, raises ``RuntimeError`` chained from the last underlying error —
    which is the ``ValueError`` mismatch if corruption was the cause.
    """
    if checksums is None:
        checksums = MD5S
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for name in FILES:
        dest = os.path.join(data_dir, name)
        expected = checksums.get(name)
        if os.path.exists(dest) and (expected is None or _md5(dest) == expected):
            paths.append(dest)
            continue

        last_err: Exception | None = None
        for base in mirrors:
            url = base + name
            fd, tmp = tempfile.mkstemp(dir=data_dir, prefix=name + ".part-")
            try:
                with urllib.request.urlopen(url, timeout=timeout) as resp, \
                        os.fdopen(fd, "wb") as out:
                    fd = None
                    while chunk := resp.read(1 << 20):
                        out.write(chunk)
                if expected is not None and (got := _md5(tmp)) != expected:
                    raise ValueError(f"{url}: MD5 mismatch — got {got}, "
                                     f"expected {expected}")
                # mkstemp creates 0600; install with normal umask-based permissions so a
                # shared data_dir cache stays readable by other users (as torchvision's).
                umask = os.umask(0)
                os.umask(umask)
                os.chmod(tmp, 0o666 & ~umask)
                os.replace(tmp, dest)     # atomic: never a truncated file at dest
                tmp = None
                break
            except (urllib.error.URLError, http.client.HTTPException,
                    OSError, ValueError) as e:
                last_err = e
            finally:
                if fd is not None:
                    os.close(fd)
                if tmp is not None and os.path.exists(tmp):
                    os.remove(tmp)
        else:
            raise RuntimeError(
                f"could not download {name} from any of {list(mirrors)}"
            ) from last_err
        paths.append(dest)
    return paths
