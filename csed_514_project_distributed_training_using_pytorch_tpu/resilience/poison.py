"""Rollback-and-skip vocabulary: the typed exit, the skip-window grammar, the marker.

The process-failure story (crash/preempt/hang — supervisor.py) restarts a fleet
from the newest checkpoint and replays forward. A failure of the *math itself*
— a non-finite loss, a gradient spike, a silently corrupted gradient — needs a
different recovery shape: the offending *step window* must not be replayed at
all, because replaying it deterministically reproduces the poison (the data
order is a pure function of seed+step, which is exactly what makes the skip
set well-defined). This module owns the pieces of that contract that both
sides — the jax-side trainers and the jax-free supervisor — must agree on:

- ``EXIT_POISONED`` (65, BSD's ``EX_DATAERR``: "input data was incorrect") —
  the trainer's typed exit when anomalies exceed its ``--anomaly-exit``
  policy. Distinct from crash codes and from ``EXIT_PREEMPTED`` (75) so the
  supervisor classifies without parsing logs.
- :class:`Poisoned` — the in-process form (the trainers' epoch loops raise
  it; ``__main__`` converts to ``SystemExit(EXIT_POISONED)``), carrying the
  step window the run wants skipped on replay.
- the ``--skip-steps`` grammar ``"a:b[,c:d...]"`` (half-open step windows)
  with :func:`parse_skip_steps` / :func:`format_skip_steps` as the one
  parser/printer pair, and :func:`merge_windows` — the supervisor's
  escalation arithmetic: a window overlapping an already-skipped one means
  the skip was too narrow, so the union is *widened* by the new window's
  length; a disjoint window is appended (and the caller escalates to
  fingerprint-verify mode — scattered poison looks like silent corruption,
  not a single bad batch).
- the poison *marker* (``poison.json`` in the versioned checkpoint store):
  how the dying trainer hands its window to the supervisor. Written by the
  logging process at the poisoned epoch boundary, consumed (read + removed)
  by the supervisor when it classifies the exit.

Deliberately jax-free, like the rest of the resilience process surface: the
supervisor imports this, and the supervisor must never touch the accelerator.
"""

from __future__ import annotations

import json
import os
import time

#: Exit status of a trainer that stopped because training-step anomalies
#: exceeded its ``--anomaly-exit`` policy (EX_DATAERR). The checkpoint store
#: then holds a health-stamped history and a ``poison.json`` marker naming the
#: step window to skip on replay.
EXIT_POISONED = 65

MARKER_NAME = "poison.json"


class Poisoned(RuntimeError):
    """Raised by a trainer at the epoch boundary where its anomaly count
    crossed ``--anomaly-exit``. Carries the global step the run stopped at and
    the half-open ``[lo, hi)`` step window its detector blames, so the
    supervisor can roll back to the newest *healthy* checkpoint and restart
    with ``--skip-steps lo:hi``."""

    def __init__(self, step: int, window: tuple[int, int]):
        self.step = int(step)
        self.window = (int(window[0]), int(window[1]))
        super().__init__(f"training poisoned at step {step} "
                         f"(anomaly window {self.window[0]}:{self.window[1]})")


def parse_skip_steps(spec: str) -> tuple[tuple[int, int], ...]:
    """``"a:b[,c:d...]"`` -> sorted tuple of half-open ``(lo, hi)`` windows.
    Empty spec -> ``()``. Malformed windows raise at parse time — a typo'd
    skip set must fail the restart loudly, not silently replay the poison."""
    if not spec:
        return ()
    windows = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        lo, sep, hi = part.partition(":")
        if not sep:
            raise ValueError(f"skip window {part!r} is not of the form a:b")
        lo_i, hi_i = int(lo), int(hi)
        if lo_i < 0 or hi_i <= lo_i:
            raise ValueError(f"skip window {part!r} must satisfy 0 <= a < b")
        windows.append((lo_i, hi_i))
    return tuple(sorted(windows))


def format_skip_steps(windows) -> str:
    """Inverse of :func:`parse_skip_steps` (round-trip pinned in tests)."""
    return ",".join(f"{lo}:{hi}" for lo, hi in sorted(windows))


def merge_windows(existing, new: tuple[int, int]):
    """Fold a fresh poison window into the accumulated skip set.

    Returns ``(windows, widened)``. Overlap with (or adjacency to) an existing
    window means the previous skip did not cover the poison — the merged
    window is the union *extended by the new window's length* (auto-widening:
    repeated poison at the same site grows the skip geometrically instead of
    looping forever one step at a time). A disjoint window is appended
    unchanged; the caller treats that as *scattered* poison and escalates to
    fingerprint verification."""
    lo, hi = int(new[0]), int(new[1])
    merged = []
    widened = False
    for (elo, ehi) in existing:
        if ehi >= lo and elo <= hi:        # overlap or adjacency
            lo, hi = min(elo, lo), max(ehi, hi)
            widened = True
        else:
            merged.append((elo, ehi))
    if widened:
        hi += max(int(new[1]) - int(new[0]), 1)
    merged.append((lo, hi))
    return tuple(sorted(merged)), widened


def write_marker(store_dir: str, *, window: tuple[int, int], step: int,
                 anomalies: int) -> str:
    """Write the poison marker next to the versioned checkpoints (atomic —
    the heartbeat module's shared jax-free tmp+rename writer). The caller
    gates to the logging process — this module stays jax-free and cannot
    ask."""
    from csed_514_project_distributed_training_using_pytorch_tpu.resilience.heartbeat import (
        _atomic_write_text,
    )

    path = os.path.join(store_dir, MARKER_NAME)
    _atomic_write_text(path, json.dumps({
        "window": [int(window[0]), int(window[1])],
        "step": int(step),
        "anomalies": int(anomalies),
        "unix_time": time.time(),
    }))
    return path


def read_marker(store_dir: str, *, consume: bool = True) -> dict | None:
    """The supervisor's side: read (and by default remove — a marker vouches
    only for the exit it was written by) the poison marker. None when absent
    or unreadable (a half-written marker falls back to no-window rollback)."""
    path = os.path.join(store_dir, MARKER_NAME)
    try:
        with open(path) as f:
            marker = json.load(f)
    except (OSError, ValueError):
        return None
    if consume:
        try:
            os.remove(path)
        except OSError:
            pass
    if (not isinstance(marker.get("window"), list)
            or len(marker["window"]) != 2):
        return None
    marker["window"] = (int(marker["window"][0]), int(marker["window"][1]))
    return marker
