"""Fault tolerance: supervised fleets, heartbeats, preemption, fault injection.

The layer that turns launch-and-pray into supervised checkpoint-restart training
(SURVEY.md §5's missing half): ``supervisor`` watches a fleet and restarts it from the
newest *valid* checkpoint; ``heartbeat`` is the liveness signal that tells slow from
hung; ``preemption`` converts SIGTERM into a cooperative stop with a durable checkpoint
and a distinct resumable exit status; ``faults`` injects every one of those failure
modes deterministically so the whole story is testable on localhost.

``RunHooks`` is the trainers' four-line wiring surface: flag-gated, host-side only
(the compiled epoch program is untouched — same discipline as ``--health-stats``), and
zero-cost when every flag is off (the hooks then never even read ``state.step``, so no
device sync is added)."""

from __future__ import annotations

from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (  # noqa: F401
    faults,
    heartbeat,
    poison,
    preemption,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience.poison import (  # noqa: F401
    EXIT_POISONED,
    Poisoned,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience.preemption import (  # noqa: F401
    EXIT_PREEMPTED,
    Preempted,
)


class RunHooks:
    """Per-trainer resilience wiring: heartbeat ticks, fault ticks, preemption checks.

    Everything is host-side epoch-boundary code. With ``heartbeat_dir`` empty,
    ``handle_preemption`` off, and no ``RESILIENCE_FAULTS`` armed, every method is a
    couple of attribute checks — in particular ``state.step`` is never fetched, so
    the flag-off trainer performs the identical host and device work as before."""

    def __init__(self, *, heartbeat_dir: str = "", handle_preemption: bool = False,
                 process_index: int = 0):
        self.heartbeat = (heartbeat.HeartbeatWriter(heartbeat_dir,
                                                    process_index=process_index)
                          if heartbeat_dir else None)
        self.preemption = preemption.install() if handle_preemption else None

    @property
    def active(self) -> bool:
        return self.heartbeat is not None or faults.active()

    def uninstall(self) -> None:
        """Restore the signal handlers (trainers call this from their teardown
        ``finally``) — an in-process caller's SIGTERM/SIGINT semantics must not
        outlive the run that installed the latch."""
        if self.preemption is not None:
            self.preemption.uninstall()

    def epoch_tick(self, state, epoch: int,
                   fingerprint: float | None = None) -> None:
        """Call at the top of each epoch: beat the heartbeat, apply armed faults.
        No-op (without touching ``state``) unless a heartbeat or fault is armed.
        ``fingerprint`` (the ``--guard`` trainers' cross-replica param
        fingerprint, computed at the PREVIOUS epoch's boundary) rides the beat
        so the supervisor's fingerprint-verify mode can compare replicas at
        the same step."""
        if not self.active:
            return
        step = int(state.step)                  # host sync — epoch-boundary only
        faults.on_tick(step=step, epoch=epoch)
        if self.heartbeat is not None and not faults.heartbeat_frozen(step=step,
                                                                      epoch=epoch):
            self.heartbeat.beat(step=step, epoch=epoch, fingerprint=fingerprint)

    def check_preempt(self, *, epoch: int, state, checkpoint: str = "",
                      tele=None, save=None) -> None:
        """Honor a pending preemption request at an epoch boundary: run ``save`` (for
        trainers whose per-epoch checkpoint is not already durable at this point),
        emit the telemetry ``preempt`` event, leave a final ``status=preempted``
        heartbeat, and raise :class:`Preempted`. No-op when nothing was requested."""
        if self.preemption is None or not self.preemption.requested:
            return
        step = int(state.step)
        if save is not None:
            save()
        if tele is not None and tele.enabled:
            from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
                telemetry as T,
            )
            tele.emit(T.preempt_event(epoch=epoch, step=step, checkpoint=checkpoint))
        if self.heartbeat is not None:
            self.heartbeat.beat(step=step, epoch=epoch,
                                status=heartbeat.STATUS_PREEMPTED)
        raise Preempted(step, checkpoint)
