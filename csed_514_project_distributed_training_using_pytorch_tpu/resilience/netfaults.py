"""Deterministic network-fault injection: a seeded in-process TCP chaos proxy.

``resilience/faults.py`` made every PROCESS failure the fleet claims to
survive injectable on demand (kill/preempt/freeze/stall/torn). This module is
the same doctrine for the WIRE: the router↔replica TCP stream is the one
transport the serve path owns, and "a corrupt byte", "a truncated completion
line", "a link that adds 800ms", "a connection that just closes" are the gray
failures DESIGN.md §23 exists for. Each is injectable, deterministically,
between any router and replica — by routing the connection through a
:class:`ChaosProxy` whose per-connection schedule comes from a spec string.

Spec grammar (``;``-separated, ``kind:key=value[,key=value...]`` — the
``RESILIENCE_FAULTS`` shape; the env var here is ``NETWORK_FAULTS``)::

    NETWORK_FAULTS="delay:replica=1,dir=s2c,ms=800,count=20;corrupt:replica=0,after=5"

Kinds (all applied to forwarded stream units — on this protocol's loopback
sockets with TCP_NODELAY and message-at-a-time writers, one recv'd unit is in
practice one protocol message, which is what makes counter-based schedules
reproducible):

``delay``
    sleep ``ms`` milliseconds before forwarding each matching unit from index
    ``after`` for ``count`` units (``count=0`` = every unit from ``after`` on)
    — the 10x straggler: the replica computes at full speed, the LINK is slow.
``stall``
    one-time ``secs`` sleep before forwarding unit ``after`` — a wedged
    middlebox; long enough, it trips the receiver's recv deadline.
``drop``
    close both directions when unit ``after`` arrives — the silent connection
    reset that must surface as a typed reconnect + ledger drain, never a hang.
``corrupt``
    flip one byte (seeded position) in units ``[after, after+count)`` — the
    flipped-bit-in-flight that framing's CRC (or the newline parser's typed
    reject) must contain.
``truncate``
    forward only the first half of unit ``after``, then close — the torn
    line/frame a peer's death mid-write leaves on the stream.

Trigger keys: ``replica`` (the proxy's id — the router runs one proxy per
replica, id = replica index; unset = every proxy), ``conn`` (connection
ordinal within the proxy, 0-based across reconnects; unset = every
connection), ``dir`` (``c2s`` router→replica, ``s2c`` replica→router,
default both), ``after`` (units forwarded in the matching direction before
firing, default 0), ``count`` (delay/corrupt repetition, default 1; ``0`` on
``delay`` = forever), ``ms`` (delay), ``secs`` (stall, default 5).

Determinism rules (the chaos-harness contract, pinned in tests): schedules
are COUNTER-based per (connection, direction) — no wall clocks, no
probabilities; the only randomness is the corrupt-byte position, drawn from
``random.Random(seed ^ proxy_id ^ conn)`` so a rerun with the same seed
damages the same offsets. Everything is plain stdlib and backend-free
(graftlint-enforced): the proxy lives in the router's process, which must
never touch a device.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import random
import socket
import threading
import time

ENV_VAR = "NETWORK_FAULTS"

KINDS = ("delay", "stall", "drop", "corrupt", "truncate")
DIRS = ("c2s", "s2c", "both")
DEFAULT_STALL_SECS = 5.0


@dataclasses.dataclass(frozen=True)
class NetFault:
    kind: str
    replica: int | None = None   # proxy id to match (router: replica index)
    conn: int | None = None      # connection ordinal within the proxy
    dir: str = "both"            # which direction the schedule watches
    after: int = 0               # units forwarded before the fault fires
    count: int = 1               # delay/corrupt: units affected (0 = forever)
    ms: float = 0.0              # delay per unit, milliseconds
    secs: float = DEFAULT_STALL_SECS  # stall sleep


@functools.lru_cache(maxsize=8)
def parse(spec: str) -> tuple[NetFault, ...]:
    """Parse a spec string (see module docstring). Unknown kinds/keys raise —
    a typo'd chaos spec must fail the harness loudly, not silently run an
    unfaulted fleet and report it as the chaos leg."""
    faults = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition(":")
        if kind not in KINDS:
            raise ValueError(f"unknown netfault kind {kind!r} "
                             f"(known: {', '.join(KINDS)})")
        kwargs: dict = {"kind": kind}
        for kv in filter(None, rest.split(",")):
            key, _, value = kv.partition("=")
            if key in ("replica", "conn", "after", "count"):
                kwargs[key] = int(value)
            elif key in ("ms", "secs"):
                kwargs[key] = float(value)
            elif key == "dir":
                if value not in DIRS:
                    raise ValueError(f"netfault dir must be one of {DIRS}, "
                                     f"got {value!r}")
                kwargs[key] = value
            else:
                raise ValueError(f"unknown netfault key {key!r} in {part!r}")
        faults.append(NetFault(**kwargs))
    return tuple(faults)


def from_env() -> tuple[NetFault, ...]:
    return parse(os.environ.get(ENV_VAR, ""))


class _ConnSchedule:
    """One direction of one proxied connection: applies the matching faults to
    a stream of units, counting as it goes."""

    def __init__(self, faults, proxy_id: int, conn: int, direction: str,
                 seed: int, on_fault):
        self.faults = [f for f in faults
                       if (f.replica is None or f.replica == proxy_id)
                       and (f.conn is None or f.conn == conn)
                       and f.dir in (direction, "both")]
        self.proxy_id = proxy_id
        self.conn = conn
        self.direction = direction
        self.on_fault = on_fault
        self._rng = random.Random(seed ^ (proxy_id << 8) ^ conn)
        self._n = 0

    def _fired(self, f: NetFault, unit: int, **extra) -> None:
        if self.on_fault is not None:
            self.on_fault({"kind": f.kind, "replica": self.proxy_id,
                           "conn": self.conn, "dir": self.direction,
                           "unit": unit, **extra})

    def apply(self, unit: bytes) -> tuple[bytes | None, bool]:
        """Transform one unit. Returns ``(data, close)``: ``data`` to forward
        (None = nothing) and whether to tear the connection down after."""
        n = self._n
        self._n += 1
        close = False
        for f in self.faults:
            if f.kind == "delay":
                if n >= f.after and (f.count == 0 or n < f.after + f.count):
                    self._fired(f, n, ms=f.ms)
                    time.sleep(f.ms / 1000.0)
            elif f.kind == "stall":
                if n == f.after:
                    self._fired(f, n, secs=f.secs)
                    time.sleep(f.secs)
            elif f.kind == "drop":
                if n == f.after:
                    self._fired(f, n)
                    return None, True
            elif f.kind == "corrupt":
                if n >= f.after and n < f.after + max(f.count, 1) and unit:
                    pos = self._rng.randrange(len(unit))
                    self._fired(f, n, pos=pos)
                    unit = unit[:pos] + bytes([unit[pos] ^ 0xFF]) \
                        + unit[pos + 1:]
            elif f.kind == "truncate":
                if n == f.after:
                    self._fired(f, n, kept=len(unit) // 2)
                    return unit[:len(unit) // 2], True
        return unit, close


class ChaosProxy:
    """A TCP forwarder between one client (the router) and one target (a
    replica) that applies a seeded fault schedule to the stream. In-process:
    ``start()`` binds a loopback port and returns it; every accepted
    connection gets two pump threads (one per direction) and its own
    counter-based schedules. ``stop()`` tears everything down."""

    def __init__(self, target_port: int, spec: str = "", *, proxy_id: int = 0,
                 seed: int = 0, on_fault=None):
        self.target_port = int(target_port)
        self.faults = parse(spec) if spec else from_env()
        self.proxy_id = int(proxy_id)
        self.seed = int(seed)
        self.on_fault = on_fault
        self.port = 0
        self.conns = 0
        self._lsock: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> int:
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(8)
        self._lsock.settimeout(0.2)
        self.port = self._lsock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"chaos-accept-{self.proxy_id}")
        t.start()
        self._threads.append(t)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                upstream = socket.create_connection(
                    ("127.0.0.1", self.target_port), timeout=5.0)
            except OSError:
                client.close()
                continue
            # The ordinal counts ESTABLISHED pairs only: while the target is
            # still binding its port, the client's connect-retry loop churns
            # accepted-then-failed sockets, and burning ordinals on those
            # would make `conn=` schedules land on a nondeterministic
            # connection.
            conn_id = self.conns
            self.conns += 1
            for s in (client, upstream):
                try:
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            for direction, src, dst in (("c2s", client, upstream),
                                        ("s2c", upstream, client)):
                sched = _ConnSchedule(self.faults, self.proxy_id, conn_id,
                                      direction, self.seed, self.on_fault)
                t = threading.Thread(
                    target=self._pump, args=(src, dst, sched, client, upstream),
                    daemon=True,
                    name=f"chaos-{self.proxy_id}-{conn_id}-{direction}")
                t.start()
                self._threads.append(t)

    def _pump(self, src, dst, sched: _ConnSchedule, client, upstream) -> None:
        src.settimeout(0.2)
        try:
            while not self._stop.is_set():
                try:
                    unit = src.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not unit:
                    break
                data, close = sched.apply(unit)
                if data:
                    try:
                        dst.sendall(data)
                    except OSError:
                        break
                if close:
                    break
        finally:
            # One side down tears both down: half-open proxied connections
            # would leave the peers disagreeing about liveness — the exact
            # ambiguity the fleet's typed faults exist to remove.
            for s in (client, upstream):
                try:
                    s.close()
                except OSError:
                    pass
