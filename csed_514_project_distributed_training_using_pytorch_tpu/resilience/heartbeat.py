"""Per-process liveness files — how the supervisor tells "slow" from "hung".

The reference's gloo fleet has no liveness signal at all: a hung peer and a busy peer
look identical until the collective timeout fires (SURVEY.md §5). Here every trainer
process with ``--heartbeat-dir`` writes one tiny JSON file per epoch tick —
``heartbeat_p{i}.json`` holding its step, epoch, pid, and a wall-clock timestamp —
atomically (tmp + rename, so a reader never sees a torn beat). The supervisor
(resilience/supervisor.py) polls the directory: a process whose last beat (or, before
its first beat, the fleet's start time) is older than the staleness timeout is *hung*,
and the whole fleet is torn down and restarted from the newest valid checkpoint. A slow
process keeps beating and is left alone — progress, not speed, is the liveness signal.

Deliberately jax-free: the reader runs inside the supervisor, which must never touch
(or even import machinery that could claim) the accelerator the fleet is using.
"""

from __future__ import annotations

import glob
import json
import os
import time

STATUS_RUNNING = "running"
STATUS_PREEMPTED = "preempted"


def heartbeat_path(dir_path: str, process_index: int) -> str:
    return os.path.join(dir_path, f"heartbeat_p{process_index}.json")


def _atomic_write_text(path: str, text: str) -> None:
    # Local copy of the checkpoint writer's tmp+rename discipline — importing
    # utils.checkpoint here would pull jax into the (jax-free) supervisor.
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class HeartbeatWriter:
    """One process's beat emitter. Construct once (per-process, NOT process-0 gated —
    every fleet member's liveness matters), call :meth:`beat` from the epoch loop."""

    def __init__(self, dir_path: str, *, process_index: int = 0):
        self.dir_path = dir_path
        self.process_index = int(process_index)
        self.path = heartbeat_path(dir_path, self.process_index)

    def beat(self, *, step: int, epoch: int, status: str = STATUS_RUNNING,
             fingerprint: float | None = None) -> None:
        doc = {
            "process_index": self.process_index,
            "pid": os.getpid(),
            "step": int(step),
            "epoch": int(epoch),
            "status": status,
            "time": time.time(),
        }
        if fingerprint is not None:
            # The cross-replica state fingerprint (--guard trainers): a cheap
            # host-local per-leaf float-sum of the params at this step. Every
            # process derives it from state that SPMD replication promises is
            # identical — the supervisor's fingerprint-verify mode compares
            # beats at the same step, and a mismatch is silent divergence
            # (SDC, desync): the fleet is torn down and rolled back strictly
            # past the mismatch step, so the diverged (already-durable)
            # checkpoint is never resumed as truth.
            doc["fingerprint"] = float(fingerprint)
        _atomic_write_text(self.path, json.dumps(doc))


def read_heartbeats(dir_path: str) -> dict[int, dict]:
    """All readable beats in ``dir_path``, keyed by process index. Torn/absent files
    are skipped (atomic writes make torn reads a non-event, but a dying writer can
    leave a stale ``.tmp`` behind — never counted)."""
    beats: dict[int, dict] = {}
    for path in glob.glob(os.path.join(dir_path, "heartbeat_p*.json")):
        try:
            with open(path) as f:
                b = json.load(f)
            beats[int(b["process_index"])] = b
        except (OSError, ValueError, KeyError):
            continue
    return beats


def stale_processes(dir_path: str, *, num_processes: int, timeout_s: float,
                    since: float, now: float | None = None) -> list[int]:
    """Process indices whose liveness signal is older than ``timeout_s``.

    ``since`` is the fleet's start wall-clock time (``time.time()`` domain — beats
    carry wall time, not the monotonic clock): a process that has not beaten *yet* is
    measured from fleet start, so slow startup gets the same grace as a slow epoch,
    and beats left by a previous attempt (cleared by the supervisor anyway) can never
    vouch for the current one."""
    now = time.time() if now is None else now
    beats = read_heartbeats(dir_path)
    stale = []
    for i in range(num_processes):
        t = beats[i]["time"] if i in beats and beats[i]["time"] >= since else since
        if now - t > timeout_s:
            stale.append(i)
    return stale


def fingerprint_mismatch(dir_path: str) -> dict | None:
    """Cross-replica state-divergence check over the latest beats: processes
    reporting a fingerprint AT THE SAME STEP must agree bitwise (the params
    they fingerprint are replicated by construction). Returns
    ``{"step": s, "fingerprints": {proc: fp, ...}}`` for the first step where
    two processes disagree, else None. Beats at different steps are never
    compared — an epoch-boundary skew between peers is normal pipelining, not
    divergence."""
    by_step: dict[int, dict[int, float]] = {}
    for i, b in read_heartbeats(dir_path).items():
        if b.get("fingerprint") is None or b.get("step") is None:
            continue
        by_step.setdefault(int(b["step"]), {})[i] = float(b["fingerprint"])
    for step in sorted(by_step):
        fps = by_step[step]
        if len(fps) >= 2 and len(set(fps.values())) > 1:
            return {"step": step, "fingerprints": fps}
    return None


def clear(dir_path: str, process_index: int | None = None) -> None:
    """Drop beat (and stray tmp) files — the supervisor calls this at attempt
    start so a restarted fleet is judged only on its own signals.
    ``process_index`` restricts the sweep to ONE process's files: the serving
    router restarts replicas individually, and wiping a healthy peer's beat
    would make it look newborn (or, worse, hung) to the next staleness check."""
    pattern = (f"heartbeat_p{process_index}.json*" if process_index is not None
               else "heartbeat_p*.json*")
    for path in glob.glob(os.path.join(dir_path, pattern)):
        try:
            os.remove(path)
        except OSError:
            pass
