"""Deterministic fault injection — the failure modes of SURVEY.md §5, on demand.

The reference's failure story is untestable by construction: a dead VM simply hangs the
gloo world forever, so "what happens when a worker dies" can only be answered by
unplugging a machine. Here every failure mode the resilience layer claims to survive is
injectable, deterministically, from the environment — which is exactly what an OS-level
fault needs to be, because the faulting process is a *different process* from the test
that arranged it (the launcher's children inherit the environment, so one env var
reaches the whole fleet).

``RESILIENCE_FAULTS`` holds ``;``-separated specs, each ``kind:key=value[,key=value...]``::

    RESILIENCE_FAULTS="kill:proc=1,step=8,flag=/tmp/f;torn:match=ckpt_00000008"

Kinds (all host-side — faults never touch the compiled program):

``kill``
    ``os._exit(exit)`` at the first resilience tick where the trigger holds — a hard
    crash mid-run (no atexit, no flushes: the honest SIGKILL/OOM analog).
``preempt``
    ``SIGTERM`` to the ticking process itself — a deterministic stand-in for the cloud
    scheduler's preemption notice (the cooperative-stop path, resilience/preemption.py).
``freeze``
    heartbeat emission stops while the process keeps running — the "hung, not slow"
    case the supervisor's staleness detector exists for.
``torn``
    checkpoint bytes are truncated to half on write (hooked into the checkpoint
    writer's ``_atomic_write``) — the torn-write artifact the manifest's checksum
    validation must refuse to resume from.
``stall``
    the ticking thread sleeps ``secs`` seconds (default 5) inside the tick — a
    wedged host step. On the serve path (``serving/replica.py`` points the
    engine's per-step hook here) this freezes a replica mid-decode without
    killing it; combined with ``freeze`` it is the full "hung, not dead" replica
    the router's heartbeat-staleness drain exists for.

Grad-poison kinds (the one sanctioned exception to "faults never touch the
compiled program": corrupting the *math* requires being in the math — the
injectors are folded into the train step at TRACE time, env-gated, so an
unarmed build adds zero ops):

``nan``
    every gradient leaf becomes NaN at exactly ``step=`` — the non-finite
    divergence the guarded update (``train/step.py`` ``--guard``) must refuse
    to apply.
``spike``
    every gradient leaf is multiplied by ``scale=`` (default 1e6) at exactly
    ``step=`` — the loss/grad-norm explosion the z-score detector catches.
``bitflip``
    ONE element of the gradient leaf whose path contains ``leaf=`` is set to
    ``scale=`` (default 1e15) at exactly ``step=`` — the silent-data-corruption
    analog: globally tiny, locally catastrophic.

Unlike the tick kinds (which fire at step/epoch ``>=`` the threshold, on the
host), poison kinds fire at step ``==`` exactly, inside the compiled program —
which is what makes a resumed attempt that replays the same step reproduce the
same poison, and therefore what makes ``--skip-steps`` a complete cure.

The serve path ticks too: a replica worker wires ``on_tick(step=engine.steps)``
into the engine's per-step hook, so ``step=N`` on the serving side means "after N
DECODE steps" — kill/preempt/stall a replica mid-decode, deterministically, with
requests in flight. ``proc`` matches the replica index there (the router spawns
each replica with ``JAX_PROCESS_ID`` = its replica id via
``train.launch.Fleet(process_id_base=...)``).

Trigger keys: ``proc`` (``JAX_PROCESS_ID`` to match; default: every process), ``step`` /
``epoch`` (tick-path kinds: fire when the tick's value is >= the threshold; unset =
immediately; rejected on ``torn``, whose write path has no tick to compare — poison
kinds instead REQUIRE ``step`` and fire at exact equality inside the program),
``match`` (path substring, ``torn`` only — required there), ``exit`` (``kill``'s exit
code, default 41), ``secs`` (``stall``'s sleep, default 5), ``scale`` (``spike``'s
multiplier, default 1e6; ``bitflip``'s planted value, default 1e15), ``leaf``
(``bitflip``'s grad-leaf path substring — required there),
``flag`` (a marker-file path: the fault fires at most ONCE per process — the marker is
created on firing with a per-process suffix, so a restarted run that replays the same
step does not re-fire; without ``flag`` the fault fires every time the trigger holds;
tick-path kinds only — poison kinds re-fire by design, so a replayed step reproduces
its poison and ``--skip-steps`` is a complete cure).

Everything here is env-gated: with ``RESILIENCE_FAULTS`` unset, ``active()`` is one dict
lookup and every hook is a no-op — production code paths pay nothing.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import signal
import sys
import time

ENV_VAR = "RESILIENCE_FAULTS"

#: Grad-poison kinds: compiled into the train step (exact-step equality), not
#: applied on the host tick path.
POISON_KINDS = ("nan", "spike", "bitflip")

KINDS = ("kill", "preempt", "freeze", "torn", "stall") + POISON_KINDS
DEFAULT_KILL_EXIT = 41
DEFAULT_STALL_SECS = 5.0
DEFAULT_SPIKE_SCALE = 1e6
DEFAULT_BITFLIP_VALUE = 1e15


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    proc: int | None = None     # None: any process
    step: int | None = None     # tick kinds: fire when step >= this;
    #                             poison kinds: fire when step == this
    epoch: int | None = None    # fire when tick epoch >= this
    flag: str = ""              # marker file: fire at most once per process
    exit: int = DEFAULT_KILL_EXIT
    match: str = ""             # path substring (torn)
    secs: float = DEFAULT_STALL_SECS   # stall sleep length
    scale: float = 0.0          # spike multiplier / bitflip planted value
    leaf: str = ""              # bitflip: grad-leaf path substring to corrupt


def active() -> bool:
    """True iff fault injection is armed (the zero-cost gate every hook checks)."""
    return bool(os.environ.get(ENV_VAR))


@functools.lru_cache(maxsize=8)
def _parse(spec: str) -> tuple[Fault, ...]:
    faults = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition(":")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {ENV_VAR} "
                             f"(known: {', '.join(KINDS)})")
        kwargs: dict = {"kind": kind}
        for kv in filter(None, rest.split(",")):
            key, _, value = kv.partition("=")
            if key in ("proc", "step", "epoch", "exit"):
                kwargs[key] = int(value)
            elif key in ("secs", "scale"):
                kwargs[key] = float(value)
            elif key in ("flag", "match", "leaf"):
                kwargs[key] = value
            else:
                raise ValueError(f"unknown fault key {key!r} in {ENV_VAR} spec {part!r}")
        if kind in POISON_KINDS and "scale" not in kwargs:
            kwargs["scale"] = (DEFAULT_BITFLIP_VALUE if kind == "bitflip"
                               else DEFAULT_SPIKE_SCALE)
        fault = Fault(**kwargs)
        if fault.kind in POISON_KINDS:
            # Poison fires INSIDE the compiled step at one exact step — the
            # trigger must be fully data-independent of the host tick path.
            if fault.step is None:
                raise ValueError(f"{fault.kind} faults fire at one exact step "
                                 f"inside the compiled program — add step= to "
                                 f"{part!r}")
            if fault.epoch is not None or fault.flag:
                raise ValueError(f"{fault.kind} faults trigger by exact step "
                                 f"equality in-program — epoch=/flag= do not "
                                 f"apply to {part!r}")
            if fault.kind == "bitflip" and not fault.leaf:
                raise ValueError(f"bitflip needs a leaf= grad-path substring: "
                                 f"{part!r}")
        if fault.kind == "torn":
            # Torn faults fire on the WRITE path, which has no tick step/epoch to
            # compare against — a step/epoch key would silently never trigger.
            if fault.step is not None or fault.epoch is not None:
                raise ValueError(f"torn faults trigger by path match, not by tick "
                                 f"— drop step/epoch from {part!r}")
            if not fault.match:
                raise ValueError(f"torn fault needs a match= path substring: {part!r}")
        faults.append(fault)
    return tuple(faults)


def get_faults() -> tuple[Fault, ...]:
    return _parse(os.environ.get(ENV_VAR, ""))


def _proc_index() -> int:
    """This process's fleet rank, from the launcher's env contract (train/launch.py);
    a single-process run is process 0."""
    return int(os.environ.get("JAX_PROCESS_ID", "0") or 0)


def _triggered(f: Fault, *, step: int | None, epoch: int | None) -> bool:
    if f.proc is not None and f.proc != _proc_index():
        return False
    if f.step is not None and (step is None or step < f.step):
        return False
    if f.epoch is not None and (epoch is None or epoch < f.epoch):
        return False
    return True


def _claim_once(f: Fault) -> bool:
    """True iff this firing is allowed. A ``flag`` marker file (suffixed per process,
    so fleet peers fire independently) is claimed exclusively — a restart that replays
    the trigger sees the marker and stays quiet."""
    if not f.flag:
        return True
    path = f"{f.flag}.p{_proc_index()}"
    try:
        with open(path, "x") as fh:
            fh.write(f"{f.kind} fired (pid {os.getpid()})\n")
        return True
    except FileExistsError:
        return False


def grad_poisons() -> tuple[Fault, ...]:
    """The armed grad-poison faults that match THIS process — the trace-time
    accessor ``train/step.py`` folds into the compiled step. Empty (and one
    dict lookup) when injection is unarmed, so the production step traces
    identical ops."""
    if not active():
        return ()
    return tuple(f for f in get_faults() if f.kind in POISON_KINDS
                 and (f.proc is None or f.proc == _proc_index()))


def on_tick(*, step: int | None = None, epoch: int | None = None) -> None:
    """The trainers' per-epoch resilience tick: apply any armed kill/preempt fault."""
    if not active():
        return
    for f in get_faults():
        if not _triggered(f, step=step, epoch=epoch):
            continue
        if f.kind == "kill" and _claim_once(f):
            print(f"[faults] kill: process {_proc_index()} exiting {f.exit} "
                  f"at step {step}", file=sys.stderr, flush=True)
            sys.stderr.flush()
            os._exit(f.exit)        # a hard crash: no atexit, no flushes
        elif f.kind == "preempt" and _claim_once(f):
            print(f"[faults] preempt: SIGTERM to process {_proc_index()} "
                  f"at step {step}", file=sys.stderr, flush=True)
            os.kill(os.getpid(), signal.SIGTERM)
        elif f.kind == "stall" and _claim_once(f):
            print(f"[faults] stall: process {_proc_index()} sleeping "
                  f"{f.secs:.1f}s at step {step}", file=sys.stderr, flush=True)
            time.sleep(f.secs)


def heartbeat_frozen(*, step: int | None = None, epoch: int | None = None) -> bool:
    """True while a ``freeze`` fault holds — the heartbeat writer then skips its beat
    (the process is alive but looks dead to the supervisor, by design)."""
    if not active():
        return False
    return any(f.kind == "freeze" and _triggered(f, step=step, epoch=epoch)
               for f in get_faults())


def mangle_write(path: str, data: bytes) -> bytes:
    """Apply any armed ``torn`` fault to a pending write: matching paths get their
    payload truncated to half (the torn-write artifact checksum validation must catch).
    Called by the checkpoint writer's ``_atomic_write`` only when ``active()``."""
    if not active():
        return data
    for f in get_faults():
        if (f.kind == "torn" and f.match and f.match in path
                and _triggered(f, step=None, epoch=None) and _claim_once(f)):
            print(f"[faults] torn: truncating write to {path} "
                  f"({len(data)} -> {len(data) // 2} bytes)",
                  file=sys.stderr, flush=True)
            return data[:len(data) // 2]
    return data
