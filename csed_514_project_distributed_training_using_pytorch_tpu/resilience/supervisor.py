"""Supervised fault-tolerant fleets: crash detection, checkpoint-restart, backoff.

The reference's failure model is all-or-nothing: one dead VM hangs the whole gloo world
until a human notices (SURVEY.md §5), and ``train/launch.py`` reproduced that contract
minus the hang. This module closes the loop — it is the retry harness that makes
time-to-train on preemptible fleets a property of *recovery*, not luck:

1. **spawn** the fleet (``train.launch.Fleet`` — same rendezvous env contract);
2. **watch** it: first nonzero child exit tears the fleet down immediately
   (fail-fast — peers blocked on a dead partner's collective are killed, not waited
   out), and heartbeat staleness (resilience/heartbeat.py) catches the hang that has
   no exit code at all;
3. **classify**: exit 0 → done; ``EXIT_PREEMPTED`` (75) → a cooperative stop with a
   durable checkpoint — *resumable*, returned to the caller without burning a retry
   (the outer scheduler re-runs when capacity returns); ``EXIT_POISONED`` (65) → the
   trainer's anomaly guard tripped its ``--anomaly-exit`` policy (the math, not the
   process, failed); a cross-replica fingerprint mismatch in the heartbeats
   (fingerprint-verify mode) → "desync"; anything else → crash;
4. **restart** a crashed/hung fleet from the newest *healthy* checkpoint
   (``utils.checkpoint.newest_healthy_checkpoint`` — the ONE resume-scan owner:
   health-stamped-clean preferred over merely-valid, checksums verified against the
   manifest so the torn write the crash itself may have produced is skipped, never
   loaded), appending ``--resume-from`` to the child command, with bounded retries
   and exponential backoff;
5. **rollback-and-skip** a poisoned fleet: read the trainer's poison marker
   (``resilience/poison.py``), fold its step window into the accumulated skip set,
   and restart with ``--skip-steps a:b[,c:d]`` — the data order is a pure function
   of seed+step, so the skip set is well-defined and replayable. Repeated poison
   overlapping an already-skipped window auto-WIDENS the window (the skip was too
   narrow); poison at scattered steps escalates to fingerprint-verify mode (it
   looks like silent corruption, not one bad batch), where heartbeat fingerprints
   are compared across replicas every staleness check.

Restart-from-checkpoint (not in-place recovery) is the whole design: the trainers'
sharded checkpoints already interchange across process counts and mesh layouts
(DESIGN.md §12), so a restarted fleet need not even be the same shape as the dead one.

The supervisor stays jax-free: it must never initialize (or race the children for) the
accelerator. Its telemetry is therefore a plain append-JSONL writer emitting the same
``{"event": "restart", ...}`` schema the trainers' telemetry uses — readable by the
shared reader and rendered by ``tools/telemetry_report.py``. (The one lazy import of
``utils.checkpoint`` for manifest scans loads jax the library, but never initializes a
backend — no device is claimed.)

CLI: ``tools/fleet_supervise.py``.
"""

from __future__ import annotations

import dataclasses
import signal
import time

from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    heartbeat as hb,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    poison as poison_mod,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience.poison import (
    EXIT_POISONED,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience.preemption import (
    EXIT_PREEMPTED, PreemptionHandler,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.launch import Fleet
from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
    JsonlWriter,
)

#: SuperviseResult.exit_code when the fleet was torn down by the supervisor itself
#: (hang / attempt timeout): 128+SIGTERM, the shell's convention for a terminated
#: process — the children had no exit code of their own to report.
EXIT_TORN_DOWN = 143


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Supervision knobs (the fleet-shape fields mirror ``train.launch``)."""

    num_processes: int = 2
    platform: str | None = None       # e.g. "cpu" for emulated fleets
    devices_per_process: int = 1
    port: int | None = None
    max_restarts: int = 3             # restarts, not attempts: N+1 runs max
    backoff_s: float = 1.0            # exponential: backoff_s * 2**restart, capped
    backoff_max_s: float = 30.0
    checkpoint_dir: str = ""          # versioned store (utils.checkpoint manifest) to
    #                                   resume from; "" = restart from scratch
    heartbeat_dir: str = ""           # fleet liveness files; auto-appended to the
    #                                   child command when set ("" = no hang watch)
    heartbeat_timeout_s: float = 0.0  # beat staleness that counts as hung; 0 off
    attempt_timeout_s: float = 0.0    # wall-clock bound per attempt; 0 = unbounded
    preempt_grace_s: float = 120.0    # drain window after a preemption before the
    #                                   teardown SIGKILL escalation: latched peers
    #                                   are finishing an epoch + final checkpoint,
    #                                   which dwarfs the crash-straggler grace
    telemetry: str = ""               # supervisor JSONL (restart events); "" off
    fingerprint_verify: bool = False  # compare cross-replica heartbeat param
    #                                   fingerprints (a mismatch at the same step
    #                                   is "desync" — silent state divergence);
    #                                   auto-armed when poison lands at scattered
    #                                   steps, settable up front for paranoia
    poll_s: float = 0.05


@dataclasses.dataclass
class SuperviseResult:
    status: str                       # "ok" | "preempted" | "failed"
    exit_code: int                    # 0 | EXIT_PREEMPTED | child rc | EXIT_TORN_DOWN
    attempts: int
    restarts: int
    resume_history: list              # checkpoint path (or None) each attempt resumed from
    skip_windows: tuple = ()          # accumulated rollback-and-skip step windows
    rollbacks: int = 0                # restarts caused by poison/desync (not crashes)


# The supervisor's telemetry writer is the shared jax-free JSONL appender —
# NOT utils.telemetry.TelemetryWriter, whose process-0 gate calls
# jax.process_index() and would initialize a jax backend inside the supervisor.
# Same line schema; the shared reader and report CLI consume both. (The serving
# router reuses the same writer for the same reason — utils/jsonl.py.)
_JsonlWriter = JsonlWriter


def _newest_healthy(checkpoint_dir: str,
                    before_step: int | None = None) -> str | None:
    """The ONE resume-scan owner for every supervised restart path: prefers a
    health-stamped-CLEAN checkpoint over a merely-valid one (the old
    ``_newest_valid`` trusted the newest decodable checkpoint even if the run
    that wrote it was already diverging — exactly the state a rollback must
    not land on; regression-pinned in tests/test_anomaly.py). ``before_step``
    is the desync bound: a fingerprint mismatch at step S indicts the step-S
    checkpoint — durable and clean-STAMPED, because per-process anomaly
    counters cannot see cross-replica divergence — so that rollback must land
    strictly before it."""
    if not checkpoint_dir:
        return None
    # Lazy: utils.checkpoint imports jax/flax; the supervisor only pays that (import,
    # never backend init) when it actually has a checkpoint store to scan.
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.checkpoint import (
        newest_healthy_checkpoint,
    )
    return newest_healthy_checkpoint(checkpoint_dir, before_step=before_step)


def _cursor_for(path: str | None) -> dict | None:
    """The input-stream resume cursor the chosen checkpoint's manifest entry
    carries (``data/stream.py``-fed trainers key it in at save time): put it
    on the restart event so the stream alone answers WHERE the next attempt
    resumes in the data order, not just which file it restores."""
    if not path:
        return None
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.checkpoint import (
        cursor_for,
    )
    try:
        return cursor_for(path)
    except Exception:
        return None


def _sleep_interruptible(seconds: float, handler: PreemptionHandler) -> None:
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline and not handler.requested:
        time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))


def supervise(command: list[str], cfg: SupervisorConfig = SupervisorConfig(), *,
              env: dict | None = None) -> SuperviseResult:
    """Run ``python <command>`` as a supervised ``cfg.num_processes``-wide fleet until
    it completes, is preempted, or exhausts its restart budget.

    The supervisor latches SIGTERM/SIGINT itself and forwards SIGTERM to the fleet:
    preempting the supervisor preempts the run (children with ``--handle-preemption``
    stop at their next epoch boundary and exit 75)."""
    tele = _JsonlWriter(cfg.telemetry) if cfg.telemetry else None
    handler = PreemptionHandler().install()
    attempts = restarts = rollbacks = 0
    resume_history: list = []
    status, exit_code = "failed", 1
    # Accumulated rollback-and-skip set — SEEDED from any --skip-steps the
    # caller already put on the command (argparse last-occurrence-wins means
    # the appended flag REPLACES the original: without the seed, the first
    # poisoned restart would silently drop the user's known-bad windows).
    skip_windows: tuple = ()
    for i, arg in enumerate(command):
        if arg == "--skip-steps" and i + 1 < len(command):
            skip_windows = poison_mod.parse_skip_steps(command[i + 1])
        elif arg.startswith("--skip-steps="):
            skip_windows = poison_mod.parse_skip_steps(
                arg.split("=", 1)[1])
    desync_bound: int | None = None       # mismatch step: that checkpoint is
    #                                       indicted; roll back strictly past it
    fingerprint_verify = cfg.fingerprint_verify
    scanned_resume: str | None = None     # restart path pre-scans for its log line;
    have_scanned = False                  # the next attempt reuses it (the store
    #                                       cannot change while the fleet is dead)
    try:
        while True:
            attempts += 1
            resume = (scanned_resume if have_scanned
                      else _newest_healthy(cfg.checkpoint_dir))
            have_scanned = False
            resume_history.append(resume)
            cmd = list(command)
            if resume:
                cmd += ["--resume-from", resume]     # last occurrence wins in argparse
            if skip_windows:
                cmd += ["--skip-steps",
                        poison_mod.format_skip_steps(skip_windows)]
            if cfg.heartbeat_dir:
                hb.clear(cfg.heartbeat_dir)
                if "--heartbeat-dir" not in cmd:
                    cmd += ["--heartbeat-dir", cfg.heartbeat_dir]
            started_mono, started_wall = time.monotonic(), time.time()
            fleet = Fleet(cmd, num_processes=cfg.num_processes, platform=cfg.platform,
                          devices_per_process=cfg.devices_per_process, port=cfg.port,
                          env=env)
            reason: str | None = None
            rc = 0
            forwarded = False
            # Staleness checks glob + JSON-parse every beat file — throttle them to
            # a fraction of the timeout instead of every poll_s iteration.
            hb_interval = max(1.0, cfg.heartbeat_timeout_s / 10)
            next_hb_check = started_mono
            try:
                while True:
                    first_rc = fleet.poll()
                    if handler.requested and not forwarded:
                        fleet.send_signal(signal.SIGTERM)
                        forwarded = True
                    if first_rc is not None:
                        rc = first_rc
                        reason = ("preempted" if rc == EXIT_PREEMPTED
                                  else "poisoned" if rc == EXIT_POISONED
                                  else "crash")
                        if reason == "preempted":
                            # Peers are latched and still finishing their epoch +
                            # final checkpoint; drain before teardown's SIGKILL
                            # escalation can cost them the durable checkpoint.
                            drain = time.monotonic() + cfg.preempt_grace_s
                            while fleet.running and time.monotonic() < drain:
                                time.sleep(cfg.poll_s)
                        break
                    if not fleet.running:
                        # Re-poll before declaring success: exits can land between
                        # the poll above and the running check (e.g. every worker
                        # crashing at the same fault step).
                        final_rc = fleet.poll()
                        if final_rc is not None:
                            rc = final_rc
                            reason = ("preempted" if rc == EXIT_PREEMPTED
                                      else "poisoned" if rc == EXIT_POISONED
                                      else "crash")
                        else:
                            reason = "ok"
                        break
                    if (cfg.heartbeat_dir
                            and (fingerprint_verify
                                 or cfg.heartbeat_timeout_s > 0)
                            and time.monotonic() >= next_hb_check):
                        next_hb_check = time.monotonic() + hb_interval
                        if fingerprint_verify:
                            # Fingerprint-verify mode: replicas reporting a
                            # param fingerprint at the SAME step must agree
                            # bitwise — disagreement is silent state
                            # divergence (SDC, desync), torn down BEFORE the
                            # diverged state can be checkpointed as truth.
                            # Shares the heartbeat throttle; armed even with
                            # the staleness timeout off.
                            mismatch = hb.fingerprint_mismatch(
                                cfg.heartbeat_dir)
                            if mismatch is not None:
                                print(f"[supervisor] fingerprint mismatch at "
                                      f"step {mismatch['step']}: "
                                      f"{mismatch['fingerprints']}", flush=True)
                                # The state AT the mismatch step is the
                                # diverged one — its checkpoint is already
                                # durable and clean-stamped (per-process
                                # counters cannot see divergence), so the
                                # rollback must land strictly before it.
                                desync_bound = int(mismatch["step"])
                                rc, reason = EXIT_TORN_DOWN, "desync"
                                break
                        if cfg.heartbeat_timeout_s > 0:
                            stale = hb.stale_processes(
                                cfg.heartbeat_dir,
                                num_processes=cfg.num_processes,
                                timeout_s=cfg.heartbeat_timeout_s,
                                since=started_wall)
                            if stale:
                                rc, reason = EXIT_TORN_DOWN, "hung"
                                break
                    if (cfg.attempt_timeout_s > 0
                            and time.monotonic() - started_mono > cfg.attempt_timeout_s):
                        rc, reason = EXIT_TORN_DOWN, "timeout"
                        break
                    time.sleep(cfg.poll_s)
            finally:
                fleet.terminate()     # fail-fast teardown: never leave peers hanging
            if reason == "ok":
                status, exit_code = "ok", 0
                break
            if reason == "preempted" or (handler.requested
                                         and reason in ("crash", "poisoned")):
                # A preemption signal can also surface as teardown collateral on
                # peers; the supervisor's own latch disambiguates.
                status, exit_code = "preempted", EXIT_PREEMPTED
                break
            if restarts >= cfg.max_restarts:
                status, exit_code = "failed", rc
                break
            backoff = (min(cfg.backoff_s * (2 ** restarts), cfg.backoff_max_s)
                       if cfg.backoff_s > 0 else 0.0)
            restarts += 1
            escalation = ""
            if reason in ("poisoned", "desync"):
                rollbacks += 1
            if reason == "poisoned":
                # Rollback-and-skip: fold the dying trainer's poison window
                # into the skip set. Overlap with an already-skipped window
                # means the skip was too narrow — auto-widen; a disjoint
                # window next to an existing set is SCATTERED poison, which
                # smells like silent corruption, not one bad batch —
                # escalate to cross-replica fingerprint verification.
                marker = poison_mod.read_marker(cfg.checkpoint_dir)
                if marker is not None:
                    had_windows = bool(skip_windows)
                    skip_windows, widened = poison_mod.merge_windows(
                        skip_windows, marker["window"])
                    if widened:
                        escalation = "widened skip"
                    elif had_windows and not fingerprint_verify:
                        # Scattered poison: escalate to cross-replica state
                        # verification — which needs the heartbeat channel to
                        # carry fingerprints. Without one the mode would be a
                        # silent no-op, so say so instead of claiming it.
                        if cfg.heartbeat_dir:
                            fingerprint_verify = True
                            escalation = "fingerprint-verify armed"
                        else:
                            escalation = ("fingerprint-verify unavailable "
                                          "(no heartbeat dir)")
            next_resume = _newest_healthy(
                cfg.checkpoint_dir,
                before_step=desync_bound if reason == "desync" else None)
            desync_bound = None
            scanned_resume, have_scanned = next_resume, True
            if tele:
                tele.emit({"event": "restart", "attempt": attempts,
                           "restart": restarts, "reason": reason, "exit_code": rc,
                           "resume_from": next_resume or "",
                           "cursor": _cursor_for(next_resume),
                           "skip":
                           poison_mod.format_skip_steps(skip_windows),
                           "rollback": reason in ("poisoned", "desync"),
                           "backoff_s": backoff, "unix_time": time.time()})
            print(f"[supervisor] attempt {attempts} {reason} (exit {rc}); "
                  f"restart {restarts}/{cfg.max_restarts} in {backoff:.1f}s"
                  + (f" from {next_resume}" if next_resume else " from scratch")
                  + (f" skipping {poison_mod.format_skip_steps(skip_windows)}"
                     if skip_windows else "")
                  + (f" [{escalation}]" if escalation else ""),
                  flush=True)
            _sleep_interruptible(backoff, handler)
            if handler.requested:
                status, exit_code = "preempted", EXIT_PREEMPTED
                break
    finally:
        handler.uninstall()
        if tele:
            tele.emit({"event": "supervise_summary", "status": status,
                       "exit_code": exit_code, "attempts": attempts,
                       "restarts": restarts, "rollbacks": rollbacks,
                       "skip": poison_mod.format_skip_steps(skip_windows),
                       "unix_time": time.time()})
            tele.close()
    return SuperviseResult(status=status, exit_code=exit_code, attempts=attempts,
                           restarts=restarts, resume_history=resume_history,
                           skip_windows=skip_windows, rollbacks=rollbacks)
