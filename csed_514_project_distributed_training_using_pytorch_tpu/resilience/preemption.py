"""Cooperative preemption: SIGTERM/SIGINT → stop at the next epoch boundary.

Preemptible capacity (the ROADMAP's target fleet) is reclaimed with a signal and a
grace window, not a negotiation. The wrong response is to die mid-epoch — that wastes
the whole partial epoch and leaves whatever the signal happened to interrupt. The right
response is the one implemented here: the handler only *records* the request; the
trainer checks it at the next epoch boundary (after the per-epoch checkpoint is
durable), flushes telemetry, and exits with a distinct status — ``EXIT_PREEMPTED`` (75,
BSD's ``EX_TEMPFAIL``: "transient failure, retry later") — that the supervisor and any
outer scheduler treat as *resumable*, not failed.

The handler is flag-gated (``--handle-preemption``) and installs nothing by default:
a signal then keeps its normal kill semantics, exactly as before this module existed.
jax-free, like the rest of the resilience layer's process-management surface.
"""

from __future__ import annotations

import signal
import threading

#: Exit status of a run that stopped cooperatively after a preemption signal
#: (EX_TEMPFAIL). Distinct from crash codes so the supervisor can classify without
#: parsing logs.
EXIT_PREEMPTED = 75

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class Preempted(RuntimeError):
    """Raised by a trainer at the epoch boundary that honors a preemption request.

    Carries what the outer layer needs to hand off: the global step the run stopped
    at and the checkpoint that step is durable in. ``__main__`` entrypoints convert
    it to ``SystemExit(EXIT_PREEMPTED)``; in-process callers (tests, notebooks) can
    catch it and keep the partial result."""

    def __init__(self, step: int, checkpoint: str = ""):
        self.step = int(step)
        self.checkpoint = checkpoint
        super().__init__(f"preempted at step {step}"
                         + (f" (checkpoint {checkpoint})" if checkpoint else ""))


class PreemptionHandler:
    """Installable stop-request latch. ``requested`` flips on the first signal and
    stays set; a second SIGINT restores the default handler and re-raises, so an
    interactive Ctrl-C Ctrl-C still hard-exits instead of trapping the user."""

    def __init__(self, signals=DEFAULT_SIGNALS):
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self.signum: int | None = None
        self._old: dict[int, object] = {}
        self._counts: dict[int, int] = {}

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def _handle(self, signum, frame):
        self._counts[signum] = self._counts.get(signum, 0) + 1
        self.signum = signum
        self._requested.set()
        if signum == signal.SIGINT and self._counts[signum] > 1:
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt

    def install(self) -> "PreemptionHandler":
        """Install handlers (idempotent; previous handlers saved for uninstall).
        Signal handlers can only live in the main thread — elsewhere the handler
        degrades to an inert latch (``requested`` stays False) rather than failing
        the run it is supposed to protect."""
        for sig in self.signals:
            if sig in self._old:
                continue
            try:
                self._old[sig] = signal.signal(sig, self._handle)
            except ValueError:      # not the main thread
                break
        return self

    def uninstall(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):
                pass
        self._old.clear()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


def install(signals=DEFAULT_SIGNALS) -> PreemptionHandler:
    """Convenience: construct + install in one call (the trainers' entry point)."""
    return PreemptionHandler(signals).install()
