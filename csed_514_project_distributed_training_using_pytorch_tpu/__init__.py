"""TPU-native distributed-training framework.

A brand-new JAX/XLA re-design of the capabilities of the reference project
``abhishekiitm/CSED_514_Project_Distributed_Training_using_PyTorch`` (CPU PyTorch DDP over the
gloo TCP backend): an MNIST CNN trained single-process and data-parallel across devices/hosts,
with loss-curve and time-to-train-vs-worker-count benchmarking.

Instead of a DDP wrapper object, per-rank launcher scripts, and a backend string
(reference ``src/train_dist.py:63,146``, ``src/run1.py``/``src/run2.py``), this framework is
SPMD-first: one jit-compiled train step over a ``jax.sharding.Mesh``, with the gradient
all-reduce fused into the compiled program by XLA and laid onto ICI/DCN by the compiler.

Layout (mirrors the reference's five functional layers, SURVEY.md §1):

- ``ops/``       functional NN ops on ``jax.numpy``/``lax`` (the ATen-kernel analog)
- ``models/``    model definitions (reference ``src/model.py``)
- ``data/``      MNIST ingest + host input pipeline (reference data loaders), incl. a native
                 C++ batch-assembly path (the DataLoader-worker-pool analog)
- ``parallel/``  mesh construction, SPMD data-parallel train step, sharded sampler,
                 collectives (the C10D/gloo + DDP-Reducer analog)
- ``train/``     training drivers: single-process, distributed, p2p smoke test
                 (reference ``src/train.py``, ``src/train_dist.py``, ``src/run{1,2}.py``)
- ``utils/``     config, checkpointing (save *and* the restore path the reference lacks),
                 metrics/plots, profiling, determinism checks
"""

import os as _os

# Honor an explicit JAX_PLATFORMS env choice where an interpreter-startup hook has pinned a
# different platform through jax.config (this build container's sitecustomize does exactly
# that for its tunnelled "axon" TPU plugin, making `JAX_PLATFORMS=cpu python -m <trainer>`
# silently target the TPU). Scope the correction narrowly: only when the *current config*
# disagrees with the env because it holds that hook's pin — a programmatic
# jax.config.update() by the user sets any other value and is never overwritten.
_requested_platforms = _os.environ.get("JAX_PLATFORMS", "")
if _requested_platforms and "axon" not in _requested_platforms.split(","):
    # Sanctioned backend reach: this shim exists precisely to touch jax.config
    # BEFORE anything else does, fires only when the user already asked for a
    # platform via the env, and never initializes a backend itself.
    import jax as _jax  # graftlint: disable=backend-purity

    # The hook pins "axon" first in the platform priority list (observed: "axon,cpu").
    if (_jax.config.jax_platforms or "").split(",")[0] == "axon":
        try:
            # Same sanction as the jax import above: shim-internal, env-gated.
            from jax._src import xla_bridge as _xb  # graftlint: disable=backend-purity
            _too_late = _xb.backends_are_initialized()
        except (ImportError, AttributeError):   # private API — fail open
            _too_late = False
        if _too_late:
            # The config flip below would be a silent no-op (or an error): make the
            # platform mismatch visible instead (advisor finding r1).
            import warnings as _warnings
            _warnings.warn(
                f"JAX_PLATFORMS={_requested_platforms!r} was requested, but a JAX "
                f"backend already initialized under the startup hook's 'axon' pin — "
                f"import this package (or set the env var) before touching "
                f"jax.devices() to get the requested platform.", RuntimeWarning)
        else:
            _jax.config.update("jax_platforms", _requested_platforms)

# Lazy exports (PEP 562): importing ANY submodule executes this __init__, and
# the backend-free fleet side (serving/router.py, resilience/supervisor.py,
# utils/jsonl.py — see tools/graftlint's backend-purity checker) lives inside
# this package. An eager `from .models.cnn import Net` here charged every one
# of them for jax+flax at import time; the attribute shim keeps the public
# `package.Net` / `package.SingleProcessConfig` surface identical while
# deferring the heavyweight import to first touch.
_LAZY_EXPORTS = {
    "Net": ("csed_514_project_distributed_training_using_pytorch_tpu"
            ".models.cnn"),
    "SingleProcessConfig": ("csed_514_project_distributed_training_using"
                            "_pytorch_tpu.utils.config"),
    "DistributedConfig": ("csed_514_project_distributed_training_using"
                          "_pytorch_tpu.utils.config"),
}

__version__ = "0.1.0"

__all__ = [
    "Net",
    "SingleProcessConfig",
    "DistributedConfig",
    "__version__",
]


def __getattr__(name: str):
    if name in _LAZY_EXPORTS:
        import importlib

        value = getattr(importlib.import_module(_LAZY_EXPORTS[name]), name)
        globals()[name] = value      # cache: subsequent lookups skip __getattr__
        return value
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
