"""Scaled dot-product attention (the dense, single-device formulation).

The reference has no attention anywhere (its only model is a fixed-28×28-input CNN,
reference ``src/model.py:4-22``; SURVEY.md §2c marks sequence parallelism "structurally
inapplicable"). This op exists for the beyond-parity long-context surface this framework
adds on top of reference parity: it is the numerics oracle that the sequence-parallel
ring attention (``parallel/ring_attention.py``) is pinned against, and the default
attention implementation of the transformer model family (``models/transformer.py``).

TPU notes: both einsums are MXU matmuls; softmax statistics are computed in float32
regardless of activation dtype (bfloat16-safe), matching the online-softmax accumulation
the ring formulation uses so the two paths agree to float32 round-off.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Large-but-finite mask value: keeps ``exp`` exactly 0 for masked scores without the
# NaN hazards of -inf arithmetic in the online-softmax recurrence.
MASK_VALUE = -1e30


def validate_window(window: int | None) -> None:
    """Shared sliding-window validation (one owner for the error message — trainers
    call it fail-fast before any data load or rendezvous)."""
    if window is not None and window < 1:
        raise ValueError(f"attention window must be >= 1, got {window}")


def windowed_attention_fn(window: int):
    """The dense core with a fixed sliding window, in the pluggable
    ``(q, k, v, *, causal) -> out`` ``attention_fn`` contract — the single wiring
    helper behind every trainer's ``--attention-window``."""
    validate_window(window)
    import functools

    return functools.partial(full_attention, window=window)


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool = False,
                   window: int | None = None) -> jax.Array:
    """Dense softmax attention. ``q, k, v: [B, S, H, D]`` → ``[B, S, H, D]``.

    ``causal=True`` masks key positions strictly after the query position (decoder-style
    self-attention). ``window=W`` additionally restricts each query to keys within
    distance < W (sliding-window/local attention: causal → keys in ``(i-W, i]``;
    bidirectional → ``|i-j| < W``; every query always sees at least itself). Scores and
    the softmax run in float32; output is cast back to ``q.dtype``.
    """
    validate_window(window)
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal or window is not None:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        i = jnp.arange(s_q)[:, None]
        j = jnp.arange(s_k)[None, :]
        mask = jnp.ones((s_q, s_k), bool)
        if causal:
            mask &= i >= j
        if window is not None:
            mask &= (i - j < window) & (j - i < window)
        scores = jnp.where(mask[None, None], scores, MASK_VALUE)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
    return out.astype(q.dtype)
