"""Flash attention as first-party Pallas TPU kernels (forward + full backward).

The single-chip long-context hot path: dense attention materializes the ``[S, S]`` score
matrix in HBM (O(S²) memory and bandwidth); these kernels stream K/V blocks through VMEM
with the online-softmax recurrence, so HBM traffic is O(S·D) and the score matrix never
exists. This is the intra-chip complement of the cross-chip ring attention in
``parallel/ring_attention.py`` (same math, different memory wall).

Kernel layout (FlashAttention-2 style, in the canonical Pallas-TPU grid formulation):

- **Forward**: grid ``(B·H, S/BLOCK, S/BLOCK)`` in the packed ``[BH, S, D]`` layout; or,
  for the native layouts that feed the model's ``[B, S, H, D]`` viewed flat (a free
  reshape, no transpose repacks — ``_GridLayout``, r5): native-STRIDED at D%128==0
  (the same packed grid and kernel bodies, with D-wide LANE-BLOCK index maps
  ``(g//H, walk, g%H)`` addressing the flat operands) or native-UNROLL otherwise
  (grid ``(B, S/BLOCK, S/BLOCK)``, all-heads blocks ``[BLOCK, H·D]``, a static head
  unroll over per-head lane slices — Mosaic's last-two-dims tiling rules out a
  per-head grid axis on rank-4 blocks, and sublane-sliced bf16 operands crash its
  ``dot`` lowering, so heads ride the lane dim) — the innermost
  (fastest-varying) axis walks K/V blocks while the query block and the online-softmax
  accumulators ``(acc, m, l)`` persist in **VMEM scratch** across those steps
  (``@pl.when`` on the first/last K/V step initializes/finalizes them). Streaming and
  double-buffering come from Pallas's automatic grid pipelining — each operand's
  ``index_map`` names the block the step needs and the next block's copy overlaps the
  current block's math. VMEM residency is a handful of ``[128, D]`` blocks regardless
  of S, so sequence length is HBM-bound: an earlier full-K/V-in-VMEM variant hit the
  16 MB scoped-vmem wall at S=16k, and a hand-rolled in-kernel DMA variant
  (``run_scoped`` + ``make_async_copy`` double buffering) wedged this environment's AOT
  Mosaic compile helper the same way the (since-retired) whole-model fused CNN kernel
  did — the grid formulation compiles in seconds.
- **Backward**: the standard two-kernel recompute formulation — no O(S²) residuals, only
  ``(out, lse = m + log l)``. A ``dq`` kernel re-walks K/V blocks per query block; a
  ``dk/dv`` kernel walks query/dout blocks per key block; both recompute
  ``p = exp(q·kᵀ·scale − lse)`` blockwise and apply ``ds = p ∘ (dout·vᵀ − Δ)`` with
  ``Δ = rowsum(dout ∘ out)`` computed once outside the kernels (XLA fuses it).
- **Causal/banded dead blocks** cost no FLOPs (``@pl.when`` skip) and — r5 — no fetch
  either: the full walks clamp their index maps onto the nearest live block
  (``_elided_key_idx``), and Pallas skips the copy when consecutive steps request the
  same block; fully-visible interior blocks also skip the mask's iota/select chain
  (``_block_interior``). Static offsets get band-compressed grids; TRACED (zig-zag)
  offsets steer the band through scalar-prefetch index maps (``_dyn_band_reach``).

All matmuls request ``preferred_element_type=float32`` (MXU accumulation), block shapes
are lane-aligned (any multiple of 128 rows via the ``block`` parameter, default
``BLOCK = 128``; head dim on the lane axis), masks use 2-D ``broadcasted_iota``, and the
only in-kernel reshapes drop/add leading unit dims — every construct from the
v5e-probe-verified Mosaic lowering list (DESIGN.md §9). ``block`` is a pure
performance knob (numerics are block-invariant — pinned in tests): larger blocks
amortize grid/pipeline overhead per step against more VMEM per block; tune with
``bench_attention.py --block-sweep``.

Like the other Pallas modules: compiled on TPU, interpret mode elsewhere (the CPU test
platform), numerics pinned against ``ops.attention.full_attention`` in
``tests/test_pallas_attention.py`` (hardware-gated Mosaic re-check included). Sequences
must divide by the chosen ``block``; callers wanting odd lengths use the dense path
(the transformer family's default).
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
    MASK_VALUE as NEG,
    full_attention,
    validate_window,
)

BLOCK = 128            # base block rows (lane-aligned, MXU-shaped): the layout unit the
                       # ring merges are written against; every kernel accepts ``block``
                       # (a multiple of 128) for tuning — larger blocks amortize
                       # grid/pipeline overhead per step at the cost of more VMEM per
                       # block (see bench_attention.py --block)

MAX_AUTO_BLOCK = 1024  # r4 v5e sweep (bench_results/hw_r4/bench_attention_blocktune
                       # .jsonl): per-op time falls monotonically 128→1024 at every
                       # S >= 1024 (3.3× at S=2048), and 2048 hits the Mosaic
                       # VMEM/compile wall — 1024 is the measured sweet spot

MAX_AUTO_BLOCK_WINDOWED = 512  # banded grids do O(S·(W+block)) work, so oversize
                               # blocks defeat NARROW bands: b512 beats b1024
                               # 1.6× at S=8192 W=256 on v5e (r4 capture). WIDE
                               # bands amortize like the full walk — b1024 beats
                               # b512 12-13% at W=4096, S=8192/32768 under the
                               # r5 elision kernels (hw_r5/bench_attention_
                               # windowtune.jsonl) — so the cap is W-dependent
                               # (WIDE_WINDOW below)

WIDE_WINDOW = 4096             # smallest window the full MAX_AUTO_BLOCK cap is
                               # MEASURED to win at; narrower windows keep the
                               # windowed cap (the crossover lies somewhere in
                               # (256, 4096) — untested widths take the
                               # conservative side)

FLASH_MIN_SEQ = 2048   # measured flash/dense crossover on TPU v5e (same capture),
                       # windowed and not: dense wins 1.5-5× below (XLA keeps the
                       # whole score tile on-chip), flash wins 4.1-6.9× at and
                       # above (21× banded at S=8192 W=256)


NATIVE_BLOCK_ELEMS = 262144  # native-layout block·H·D cap (elements per operand
                             # block): native-flat blocks hold ALL H heads
                             # ([block, H·D] refs), so the VMEM working set
                             # scales with the product. Measured v5e envelope
                             # (r5): 512·8·64 and 256·8·128 compile; 512·8·128
                             # (524288) exceeds the 16 MB scoped-vmem limit by
                             # 740 KB in the fwd kernel's AOT stack allocation


def auto_block(s: int, window: int = 0, native_hd: int | None = None) -> int:
    """Largest lane-aligned block ≤ the measured per-regime cap that tiles ``s``
    evenly — the measured-fastest choice per shape (see ``MAX_AUTO_BLOCK`` /
    ``MAX_AUTO_BLOCK_WINDOWED``). ``native_hd`` (= H·D, the flat row width)
    caps the native layout's block·H·D VMEM product (``NATIVE_BLOCK_ELEMS``);
    packed callers leave it ``None``."""
    cap = (MAX_AUTO_BLOCK_WINDOWED if 0 < window < WIDE_WINDOW
           else MAX_AUTO_BLOCK)
    if native_hd is not None:
        if 128 * native_hd > NATIVE_BLOCK_ELEMS:
            # Even the smallest legal block would bust the measured scoped-vmem
            # envelope — same failure the explicit-block check rejects.
            raise ValueError(
                f"native-layout flash cannot tile heads*head_dim={native_hd}: "
                f"128*{native_hd} exceeds the {NATIVE_BLOCK_ELEMS}-element "
                f"VMEM envelope; use the packed layout for this shape")
        cap = min(cap, NATIVE_BLOCK_ELEMS // native_hd)
    for b in (1024, 512, 256, 128):
        if b <= min(s, cap) and s % b == 0:
            return b
    raise ValueError(
        f"flash attention requires sequence length divisible by 128, got {s} "
        f"(use ops.full_attention for odd lengths)")


def _interpret() -> bool:
    """Compiled on TPU; interpret mode on CPU/GPU (the test platforms)."""
    return jax.default_backend() != "tpu"


def _check_block(s: int, block: int) -> None:
    """Sequence/block compatibility: lane-aligned block, evenly tiled sequence."""
    if block < 128 or block % 128:
        raise ValueError(f"flash block must be a positive multiple of 128, got {block}")
    if s % block:
        raise ValueError(
            f"flash attention requires sequence length divisible by block={block}, "
            f"got {s} (use ops.full_attention for odd lengths)")


def _check_offset(q_offset: int, block: int) -> None:
    """Hop offsets must be block-quantized: the banded grids shift whole blocks
    (ring shard lengths are multiples of BLOCK, so this holds by construction)."""
    if q_offset % block:
        raise ValueError(
            f"q_offset must be a multiple of block={block}, got {q_offset}")


def _visibility_mask(iq, ik, bq, bk, *, causal: bool, window: int = 0,
                     q_offset: int = 0):
    """[bq, bk] visibility mask for query block iq vs key block ik (global positions):
    causal lower-triangle and/or the sliding-window band (distance < window).

    ``q_offset`` (static) shifts the QUERY positions by a global amount relative to
    the keys — the ring hop offset: a ring caller whose local K/V block originated
    ``delta`` shards away passes ``q_offset = delta · shard_len`` so the band/causal
    masks act on true global positions while both operands index locally."""
    q_pos = q_offset + iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = jnp.ones((bq, bk), bool)
    if causal:
        mask &= q_pos >= k_pos
    if window:
        mask &= (q_pos - k_pos < window) & (k_pos - q_pos < window)
    return mask


def _block_live(iq, j, bq, bk, *, causal: bool, window: int = 0,
                q_offset: int = 0):
    """Whether (query block iq, key block j) holds ANY visible pair — the grid-step
    skip predicate (skipped blocks cost no FLOPs; their fetch still pipelines).
    Same expression serves the dkv kernel with (i, ik) in the (iq, j) roles.
    ``q_offset`` shifts query positions globally (see ``_visibility_mask``)."""
    live = jnp.bool_(True)
    if causal:
        live &= j * bk <= q_offset + iq * bq + bq - 1     # not entirely future
    if window:
        # Not entirely older than the window: youngest key vs oldest query.
        live &= q_offset + iq * bq - (j * bk + bk - 1) < window
        if not causal:
            # Bidirectional band: not entirely newer either.
            live &= j * bk - (q_offset + iq * bq + bq - 1) < window
    return live


def _block_interior(iq, j, bq, bk, *, causal: bool, window: int = 0,
                    q_offset: int = 0):
    """Whether EVERY pair of (query block iq, key block j) is visible — such
    blocks skip the mask's iota/compare/select chain entirely (r5: the VPU work
    per element of that chain rivals the softmax exp, and at large S interior
    blocks dominate). Extreme-position arithmetic mirrors ``_visibility_mask``."""
    interior = jnp.bool_(True)
    if causal:
        interior &= q_offset + iq * bq >= j * bk + bk - 1   # oldest q ≥ youngest k
    if window:
        interior &= q_offset + iq * bq + bq - 1 - j * bk < window
        interior &= j * bk + bk - 1 - (q_offset + iq * bq) < window
    return interior


def _elided_key_idx(nq: int, off_blocks: int, reach, *, causal: bool):
    """Key-walk block index ``idx(i, j)`` for the FULL (non-banded) grid that
    aliases DEAD steps onto the nearest live block: Pallas skips the HBM→VMEM copy
    when consecutive grid steps request the same block, so the upper-triangle
    (causal) / out-of-band (windowed) fetches that previously still streamed now
    cost nothing (r5 — at S ≥ 8k causal the dead fetches made the kernels
    HBM-bound). Dead steps remain grid iterations; ``@pl.when`` already skips
    their FLOPs. The clamp is the identity for every LIVE step, so numerics are
    untouched."""

    def idx(i, j):
        lo = i + off_blocks - reach if reach is not None else 0
        hi = i + off_blocks if causal else (
            i + off_blocks + reach if reach is not None else nq - 1)
        return jnp.clip(jnp.clip(j, lo, hi), 0, nq - 1)

    return idx


def _elided_query_idx(nq: int, off_blocks: int, reach, *, causal: bool):
    """``_elided_key_idx``'s mirror for the dkv kernel, whose step axis walks QUERY
    blocks around key block ``i``: causal bounds queries from BELOW (only queries
    at/after the key see it), the window from above."""

    def idx(i, j):
        lo = i - off_blocks if causal else (
            i - off_blocks - reach if reach is not None else 0)
        hi = i - off_blocks + reach if reach is not None else nq - 1
        return jnp.clip(jnp.clip(j, lo, hi), 0, nq - 1)

    return idx


class _GridLayout:
    """Grid/spec factory shared by the fwd/dq/dkv ``pallas_call``s for the two
    operand layouts:

    - packed ``[BH, S, D]`` (``heads=None``) — refs ``[block, D]`` — the ring
      schedules' shard layout;
    - native-flat ``[B, S, H·D]`` (``heads=H``) — refs ``[block, H·D]`` with
      per-head LANE slices — the model's ``[B, S, H, D]`` viewed flat, which is
      a free contiguous reshape, NOT the ``[B,S,H,D] ↔ [BH,S,D]`` transpose
      repacks this layout exists to delete (11% of the r4 large-transformer
      step, ``bench_results/hw_r4/profile_large``).

    The flat form is forced by two Mosaic constraints the r5 chip runs hit
    (interpret mode enforces neither): a per-head grid axis puts a size-1
    block on the sublane (H) dim of a rank-4 block, which fails the
    last-two-dims tiling rule; and keeping H as a ref dim makes the per-head
    slice a SUBLANE slice, whose product feeding an MXU ``dot`` crashes the
    bf16 Mosaic compile outright. Lane slices at D-granularity compile and
    run for both dtypes. So both layouts share the rank-3 spec machinery —
    grid ``(prefix, nq, steps)``, query-block axis at program_id(1), K/V-walk
    axis at program_id(2) — and differ only in the kernels' static head unroll
    (``_ref_heads``) and the lse spec, whose ``(1, block)`` trailing block dims
    equal the array's (tiling-legal by equality).

    When the head width is a whole number of 128-lane registers
    (``D % 128 == 0``), ``per_head_grid=True`` selects a third form —
    native-STRIDED: the same
    flat ``[B, S, H·D]`` operands, but D-wide LANE BLOCKS addressed by index
    maps ``(g // H, walk, g % H)`` on the packed ``(B·H, nq, steps)`` grid.
    Kernels run their packed bodies (``heads=None`` — no unroll), refs are
    ``[block, D]``, the lse keeps the packed ``[B·H, nq, 1, block]`` shape,
    and VMEM per block matches the packed path — so the full measured
    ``MAX_AUTO_BLOCK`` applies, not the all-heads ``NATIVE_BLOCK_ELEMS``
    envelope. Zero repacks at packed-kernel efficiency; the price is a
    D-strided HBM access pattern the grid pipeline overlaps."""

    def __init__(self, shape, block: int, heads: int | None = None,
                 per_head_grid: bool = False):
        bh, s, last = shape
        self.block, self.s = block, s
        self.per_head_grid = per_head_grid
        if per_head_grid:
            if not heads or last % heads:
                raise ValueError(
                    f"per_head_grid needs heads dividing the flat width, got "
                    f"{heads} over {last}")
            self.heads = None              # kernels run their packed bodies
            self.gh = heads                # grid-folded head count
            self.prefix = (bh * heads,)
            self.hd = last // heads        # per-head lane-block width
        else:
            self.heads = heads
            self.gh = None
            self.prefix = (bh,)
            self.hd = last                 # D packed, H·D native-flat

    def grid(self, nq: int, steps: int) -> tuple:
        return self.prefix + (nq, steps)

    def _spec(self, idx_fn, prefetch: bool):
        """``idx_fn(i, j, *scalars)`` → S-block index. With ``prefetch`` the maps
        take the scalar-prefetch ref as a trailing arg (the
        ``PrefetchScalarGridSpec`` convention) — how a TRACED hop offset steers
        a banded walk (r5; previously dynamic offsets forced the full walk).
        Strided form: the grid's bh axis decomposes as (batch, head), and the
        head picks the D-wide lane block of the flat operand."""
        if self.per_head_grid:
            gh = self.gh
            if prefetch:
                return pl.BlockSpec(
                    (None, self.block, self.hd),
                    lambda g, i, j, off: (g // gh, idx_fn(i, j, off), g % gh),
                    memory_space=pltpu.VMEM)
            return pl.BlockSpec((None, self.block, self.hd),
                                lambda g, i, j: (g // gh, idx_fn(i, j), g % gh),
                                memory_space=pltpu.VMEM)
        if prefetch:
            return pl.BlockSpec((None, self.block, self.hd),
                                lambda b, i, j, off: (b, idx_fn(i, j, off), 0),
                                memory_space=pltpu.VMEM)
        return pl.BlockSpec((None, self.block, self.hd),
                            lambda b, i, j: (b, idx_fn(i, j), 0),
                            memory_space=pltpu.VMEM)

    def row_spec(self, prefetch: bool = False):
        return self._spec(lambda i, j, *_: i, prefetch)

    def walk_spec(self, idx_fn, prefetch: bool = False):
        return self._spec(idx_fn, prefetch)

    def _lse_spec(self, idx_fn, prefetch: bool):
        if self.heads:
            if prefetch:
                return pl.BlockSpec(
                    (None, self.heads, 1, 1, self.block),
                    lambda g, i, j, off: (g, 0, idx_fn(i, j, off), 0, 0),
                    memory_space=pltpu.VMEM)
            return pl.BlockSpec((None, self.heads, 1, 1, self.block),
                                lambda g, i, j: (g, 0, idx_fn(i, j), 0, 0),
                                memory_space=pltpu.VMEM)
        if prefetch:
            return pl.BlockSpec((None, 1, 1, self.block),
                                lambda b, i, j, off: (b, idx_fn(i, j, off), 0, 0),
                                memory_space=pltpu.VMEM)
        return pl.BlockSpec((None, 1, 1, self.block),
                            lambda b, i, j: (b, idx_fn(i, j), 0, 0),
                            memory_space=pltpu.VMEM)

    def lse_row_spec(self, prefetch: bool = False):
        return self._lse_spec(lambda i, j, *_: i, prefetch)

    def lse_walk_spec(self, idx_fn, prefetch: bool = False):
        return self._lse_spec(idx_fn, prefetch)

    def lse_shape(self, nq: int) -> tuple:
        if self.heads:
            return self.prefix + (self.heads, nq, 1, self.block)
        return self.prefix + (nq, 1, self.block)

    def out_shape(self, dtype):
        if self.per_head_grid:        # the array stays flat [B, S, H·D]
            return jax.ShapeDtypeStruct(
                (self.prefix[0] // self.gh, self.s, self.hd * self.gh), dtype)
        return jax.ShapeDtypeStruct((self.prefix[0], self.s, self.hd), dtype)

    def acc(self, width: int):
        """f32 VMEM scratch for a per-row accumulator of ``width`` columns:
        ``[block, width]`` packed, head-leading ``[H, block, width]``
        native-flat (leading-dim slices never cross the tiled trailing
        dims)."""
        if self.heads:
            return pltpu.VMEM((self.heads, self.block, width), jnp.float32)
        return pltpu.VMEM((self.block, width), jnp.float32)


def _ref_heads(heads):
    """Static head unroll: packed kernels (``heads=None``) run the body once on
    the whole ref (``h is None``); native-flat kernels run it per head. A
    Python loop over a STATIC bound — it unrolls at trace time, which Mosaic
    requires."""
    return range(heads) if heads else (None,)


def _hslice(ref, h, d):
    """Per-head ``[block, D]`` LANE slice of a ``[block, H·D]`` operand ref
    (identity when packed)."""
    return ref[:] if h is None else ref[:, h * d:(h + 1) * d]


def _stat_col(ref, h):
    """``[bq, 1]`` statistics column from an lse/delta ref (``[1, 1, block]``
    packed, ``[H, 1, 1, block]`` native-flat)."""
    row = ref[0] if h is None else ref[h, 0]
    return jnp.transpose(row)


def _dyn_band_reach(window: int, block: int) -> int:
    """Band reach for TRACED offsets: one block wider than the static reach, so
    the steered band stays correct for ANY offset value — the index maps steer by
    ``off // block``, and the discarded sub-block remainder can push visible
    pairs one block outside the quantized band. (In-repo zig-zag callers pass
    block-quantized offsets, but the kernels' correctness must not depend on
    that.)"""
    return _band_reach(window, block) + 1


def _dyn_banded(window: int, nq: int, block: int) -> bool:
    """Whether the traced-offset banded walk is narrower than the full walk."""
    return bool(window) and 2 * _dyn_band_reach(window, block) + 1 < nq


def _pallas_dispatch(kernel, lay, nq: int, steps: int, in_specs, out_specs,
                     out_shape, scratch_shapes, dyn: bool):
    """One owner for the dyn/static ``pallas_call`` shape (fwd, dq, and dkv all
    dispatch through here): traced offsets ride scalar prefetch
    (``PrefetchScalarGridSpec`` — the scalar is the first operand and reaches the
    index maps as their trailing arg), static paths use the plain grid."""
    if dyn:
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=lay.grid(nq, steps),
                in_specs=in_specs, out_specs=out_specs,
                scratch_shapes=scratch_shapes),
            out_shape=out_shape, interpret=_interpret())
    return pl.pallas_call(
        kernel, grid=lay.grid(nq, steps), in_specs=in_specs,
        out_specs=out_specs, out_shape=out_shape, scratch_shapes=scratch_shapes,
        interpret=_interpret())


def _dispatch_block(body, qi, ki, bq, bk, in_range, *, causal: bool,
                    window: int, q_offset):
    """Shared liveness/interior gating for all three kernels (fwd/dq/dkv):
    ``body(masked)`` runs only for live blocks, and fully-visible interior blocks
    take the mask-free specialization. One owner — an edit to the gating cannot
    desynchronize forward and backward masking."""
    live = in_range & _block_live(qi, ki, bq, bk, causal=causal, window=window,
                                  q_offset=q_offset)
    if causal or window:
        interior = _block_interior(qi, ki, bq, bk, causal=causal, window=window,
                                   q_offset=q_offset)
        pl.when(live & interior)(lambda: body(False))
        pl.when(live & ~interior)(lambda: body(True))
    else:
        pl.when(live)(lambda: body(False))


def _band_reach(window: int, block: int) -> int:
    """Max |query block − key block| with any in-window pair: the banded grid walks
    key-block offsets ``[-reach, +reach]`` (``[-reach, 0]`` causal) instead of all
    ``S/block`` key blocks, making grid overhead O(S·W/B²) rather than O((S/B)²) —
    at S=128k, W=4k, B=128 that is 33 steps per query block instead of 1024."""
    return (window + block - 2) // block


def _banded(window: int, causal: bool, nq: int, block: int) -> bool:
    """Use the band-compressed grid when it is actually narrower than the full walk."""
    if not window:
        return False
    reach = _band_reach(window, block)
    return (reach + 1 if causal else 2 * reach + 1) < nq


# =========================================================================================
# Forward
# =========================================================================================


def _fwd_kernel(*refs, scale, causal, num_steps, num_blocks,
                band_base=None, window=0, q_offset=0, dyn_offset=False,
                heads=None, head_dim=None):
    # ``dyn_offset``: the hop offset arrives as a TRACED int32 scalar via scalar
    # prefetch (the first operand) instead of the static ``q_offset`` — the
    # zig-zag schedules' chunk-pair offsets are device-dependent. r5: scalar-
    # prefetch index maps let the SAME traced offset steer a banded walk
    # (``band_base`` set), so dynamic windowed callers no longer pay the full
    # O((S/block)²) grid.
    # Layouts: packed refs are [block, D] with [block, ...] scratch; native-flat
    # refs are [block, H·D] with head-LEADING [H, block, ...] scratch, and the
    # body unrolls a static head loop over per-head LANE slices (``_ref_heads``
    # / ``_hslice``; ``heads``/``head_dim`` are static partial args). The
    # visibility mask depends only on (query block, key block) positions, so it
    # is hoisted out of the head loop.
    if dyn_offset:
        off_ref, refs = refs[0], refs[1:]
        q_offset = off_ref[0]
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    iq = pl.program_id(1)
    step = pl.program_id(2)
    bq = q_ref.shape[0]
    # Band-compressed grid: the step axis walks key-block OFFSETS around the query
    # block (shifted by the hop offset when the caller's queries live q_offset
    # positions past the keys); out-of-range offsets (clamped to a real block by
    # the index_map) are dead.
    if band_base is None:
        j, in_range = step, jnp.bool_(True)
    else:
        j = iq + q_offset // bq + step - band_base
        in_range = (j >= 0) & (j < num_blocks)

    @pl.when(step == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body(masked: bool):
        # Matmul operands keep the INPUT dtype (bf16 runs at the MXU's native
        # rate; f32 inputs behave as before) with f32 accumulation; the softmax
        # scale is applied to the f32 product, not the narrow operand.
        visible = (_visibility_mask(iq, j, bq, k_ref.shape[0], causal=causal,
                                    window=window, q_offset=q_offset)
                   if masked else None)
        for h in _ref_heads(heads):
            q = _hslice(q_ref, h, head_dim)                                # [bq, D]
            k_blk = _hslice(k_ref, h, head_dim)                            # [bk, D]
            s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if masked:
                s = jnp.where(visible, s, NEG)
            m = m_ref[:] if h is None else m_ref[h]
            l = l_ref[:] if h is None else l_ref[h]
            m_blk = jnp.max(s, axis=1, keepdims=True)                      # [bq, 1]
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(s - m_new)
            if masked:
                p = jnp.where(visible, p, 0.0)
            corr = jnp.exp(m - m_new)
            v_blk = _hslice(v_ref, h, head_dim)
            acc = acc_ref[:] if h is None else acc_ref[h]
            acc_new = acc * corr + jnp.dot(p.astype(v_blk.dtype), v_blk,
                                           preferred_element_type=jnp.float32)
            l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
            if h is None:
                acc_ref[:], m_ref[:], l_ref[:] = acc_new, m_new, l_new
            else:
                acc_ref[h], m_ref[h], l_ref[h] = acc_new, m_new, l_new

    # Causal/banded: key blocks with no visible pair contribute nothing — no FLOPs
    # (and with the elided walks, no fetch either). Fully-visible INTERIOR blocks
    # skip the mask chain — per element it costs iota+compare+2 selects of VPU
    # work, which rivals the softmax exp (r5).
    _dispatch_block(body, iq, j, bq, k_ref.shape[0], in_range, causal=causal,
                    window=window, q_offset=q_offset)

    @pl.when(step == num_steps - 1)
    def _():
        for h in _ref_heads(heads):
            l_cur = l_ref[:] if h is None else l_ref[h]
            l_safe = jnp.where(l_cur == 0.0, 1.0, l_cur)
            acc = acc_ref[:] if h is None else acc_ref[h]
            m_cur = m_ref[:] if h is None else m_ref[h]
            lse = jnp.transpose(m_cur + jnp.log(l_safe))               # [1, bq]
            if h is None:
                o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
                lse_ref[:] = lse.reshape(1, 1, bq)
            else:
                o_ref[:, h * head_dim:(h + 1) * head_dim] = (
                    acc / l_safe).astype(o_ref.dtype)
                lse_ref[h] = lse.reshape(1, 1, bq)


def _flash_forward(qx, kx, vx, *, causal: bool, block: int = BLOCK,
                   window: int = 0, q_offset: int = 0, q_offset_dyn=None,
                   heads: int | None = None, per_head_grid: bool = False):
    """Packed [BH, S, D]³ → (out [BH, S, D], lse [BH, S/block, 1, block]), or —
    with ``heads=H`` — native-flat [B, S, H·D]³ → (out [B, S, H·D],
    lse [B, H, S/block, 1, block]); ``per_head_grid`` selects the
    native-STRIDED form (packed grid + lane blocks over the flat operands,
    packed-shape lse [B·H, S/block, 1, block]) (``_GridLayout``).
    ``q_offset`` (static, a multiple of ``block``) shifts query positions globally
    relative to the keys — the ring hop offset (see ``_visibility_mask``).
    ``q_offset_dyn`` (a traced int32 scalar, mutually exclusive with a nonzero
    ``q_offset``) carries a DEVICE-DEPENDENT offset into the kernels via scalar
    prefetch — the zig-zag schedules' chunk-pair offsets. r5: the traced offset
    also STEERS the banded walk through scalar-prefetch index maps, so windowed
    dynamic callers pay O(S·W/block²) grid steps like the static path instead of
    the full O((S/block)²) walk. Unlike the static ``q_offset``, the traced
    offset need NOT be block-quantized: the dynamic band is one block wider
    (``_dyn_band_reach``) to absorb the sub-block remainder its floor-division
    steering discards."""
    s = qx.shape[1]
    if heads and qx.shape[-1] % heads:
        raise ValueError(
            f"native-flat operands need last dim divisible by heads, got "
            f"{qx.shape[-1]} % {heads}")
    d = qx.shape[-1] // (heads or 1)       # per-head width sets the softmax scale
    lay = _GridLayout(qx.shape, block, heads, per_head_grid=per_head_grid)
    unroll_heads = None if per_head_grid else heads
    _check_block(s, block)
    _check_offset(q_offset, block)
    dyn = q_offset_dyn is not None
    if dyn and q_offset:
        raise ValueError("q_offset and q_offset_dyn are mutually exclusive")
    scale = 1.0 / (d ** 0.5)
    nq = s // block
    off_blocks = q_offset // block
    # The dynamic-offset banded walk is bidirectional only: the causal one-sided
    # narrowing needs offset 0, and the zig-zag's dynamic pairs are non-causal.
    if not dyn and _banded(window, causal and not q_offset, nq, block):
        base = _band_reach(window, block)
        # A nonzero hop offset can put the whole band on one side of the local
        # diagonal, so the causal one-sided walk applies only at offset 0.
        num_steps = base + 1 if causal and not q_offset else 2 * base + 1
        key_idx = lambda i, o: jnp.clip(i + off_blocks + o - base, 0, nq - 1)
    elif dyn and not causal and _dyn_banded(window, nq, block):
        base = _dyn_band_reach(window, block)
        num_steps = 2 * base + 1
        key_idx = lambda i, o, off: jnp.clip(i + off[0] // block + o - base,
                                             0, nq - 1)
    else:
        base, num_steps = None, nq
        if not dyn and (causal or window):
            # Full walk with dead-step fetch elision (see _elided_key_idx).
            key_idx = _elided_key_idx(
                nq, off_blocks, _band_reach(window, block) if window else None,
                causal=causal)
        else:
            key_idx = lambda i, j, *_: j
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               num_steps=num_steps, num_blocks=nq, band_base=base,
                               window=window, q_offset=q_offset, dyn_offset=dyn,
                               heads=unroll_heads, head_dim=d)
    in_specs = [
        lay.row_spec(prefetch=dyn),
        lay.walk_spec(key_idx, prefetch=dyn),
        lay.walk_spec(key_idx, prefetch=dyn),
    ]
    out_specs = [
        lay.row_spec(prefetch=dyn),
        # lse rides with (1, block) trailing dims equal to the array's,
        # satisfying Mosaic's last-two-dims block constraint.
        lay.lse_row_spec(prefetch=dyn),
    ]
    out_shape = [
        lay.out_shape(qx.dtype),
        jax.ShapeDtypeStruct(lay.lse_shape(nq), jnp.float32),
    ]
    scratch_shapes = [
        lay.acc(d),    # acc
        lay.acc(1),    # running max m
        lay.acc(1),    # running normalizer l
    ]
    dyn_args = ((jnp.asarray(q_offset_dyn, jnp.int32).reshape(1),) if dyn else ())
    out, lse = _pallas_dispatch(kernel, lay, nq, num_steps, in_specs, out_specs,
                                out_shape, scratch_shapes, dyn)(
        *dyn_args, qx, kx, vx)
    return out, lse


# =========================================================================================
# Backward (recompute formulation: residuals are out + lse only)
# =========================================================================================


def _dq_kernel(*refs, scale, causal, num_steps, num_blocks,
               band_base=None, window=0, q_offset=0, dyn_offset=False,
               heads=None, head_dim=None):
    if dyn_offset:                      # traced hop offset (see _fwd_kernel)
        off_ref, refs = refs[0], refs[1:]
        q_offset = off_ref[0]
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
     dq_acc_ref) = refs
    iq = pl.program_id(1)
    step = pl.program_id(2)
    bq = q_ref.shape[0]
    if band_base is None:
        j, in_range = step, jnp.bool_(True)
    else:
        j = iq + q_offset // bq + step - band_base
        in_range = (j >= 0) & (j < num_blocks)

    @pl.when(step == 0)
    def _():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    def body(masked: bool):
        # Matmul operands keep the INPUT dtype (bf16 at the MXU's native rate),
        # f32 accumulation; softmax statistics and ds stay f32, narrowed only at
        # the matmul boundary (the standard TPU flash-backward precision split).
        visible = (_visibility_mask(iq, j, bq, k_ref.shape[0], causal=causal,
                                    window=window, q_offset=q_offset)
                   if masked else None)
        for h in _ref_heads(heads):
            q = _hslice(q_ref, h, head_dim)                       # [bq, D]
            do = _hslice(do_ref, h, head_dim)                     # [bq, D]
            lse = _stat_col(lse_ref, h)                           # [bq, 1]
            delta = _stat_col(delta_ref, h)                       # [bq, 1]
            k_blk = _hslice(k_ref, h, head_dim)
            v_blk = _hslice(v_ref, h, head_dim)
            s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if masked:
                s = jnp.where(visible, s, NEG)
            p = jnp.exp(s - lse)                                  # [bq, bk]
            if masked:
                p = jnp.where(visible, p, 0.0)
            dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            upd = jnp.dot(ds.astype(k_blk.dtype), k_blk,
                          preferred_element_type=jnp.float32)
            if h is None:
                dq_acc_ref[:] = dq_acc_ref[:] + upd
            else:
                dq_acc_ref[h] = dq_acc_ref[h] + upd

    _dispatch_block(body, iq, j, bq, k_ref.shape[0], in_range, causal=causal,
                    window=window, q_offset=q_offset)

    @pl.when(step == num_steps - 1)
    def _():
        for h in _ref_heads(heads):
            if h is None:
                dq_ref[:] = (dq_acc_ref[:] * scale).astype(dq_ref.dtype)
            else:
                dq_ref[:, h * head_dim:(h + 1) * head_dim] = (
                    dq_acc_ref[h] * scale).astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, num_steps, num_blocks,
                band_base=None, window=0, q_offset=0, dyn_offset=False,
                heads=None, head_dim=None):
    if dyn_offset:                      # traced hop offset (see _fwd_kernel)
        off_ref, refs = refs[0], refs[1:]
        q_offset = off_ref[0]
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
     dk_acc_ref, dv_acc_ref) = refs
    ik = pl.program_id(1)
    step = pl.program_id(2)
    bk = k_ref.shape[0]
    # Banded: the step axis walks QUERY-block offsets around this key block
    # (causal keys are only visible to queries at or after them, so offsets start
    # at the diagonal: band_base == 0). A hop offset shifts the visible query
    # range the OPPOSITE way: queries near global key position sit off_blocks
    # EARLIER in their local index space.
    if band_base is None:
        i, in_range = step, jnp.bool_(True)
    else:
        i = ik - q_offset // bk + step - band_base
        in_range = (i >= 0) & (i < num_blocks)

    @pl.when(step == 0)
    def _():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def body(masked: bool):
        # Same precision split as the dq kernel: operands in the input dtype,
        # f32 accumulation, p/ds narrowed only at the matmul boundary.
        visible = (_visibility_mask(i, ik, q_ref.shape[0], bk, causal=causal,
                                    window=window, q_offset=q_offset)
                   if masked else None)
        for h in _ref_heads(heads):
            k = _hslice(k_ref, h, head_dim)                       # [bk, D]
            v = _hslice(v_ref, h, head_dim)                       # [bk, D]
            q_blk = _hslice(q_ref, h, head_dim)                   # [bq, D]
            do_blk = _hslice(do_ref, h, head_dim)
            lse_blk = _stat_col(lse_ref, h)                       # [bq, 1]
            delta_blk = _stat_col(delta_ref, h)                   # [bq, 1]
            s = jax.lax.dot_general(q_blk, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if masked:
                s = jnp.where(visible, s, NEG)
            p = jnp.exp(s - lse_blk)                              # [bq, bk]
            if masked:
                p = jnp.where(visible, p, 0.0)
            # dv += pᵀ · do ; dk += dsᵀ · q
            dv_upd = jax.lax.dot_general(
                p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)               # [bk, D]
            dp = jax.lax.dot_general(do_blk, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta_blk)
            dk_upd = jax.lax.dot_general(
                ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if h is None:
                dv_acc_ref[:] = dv_acc_ref[:] + dv_upd
                dk_acc_ref[:] = dk_acc_ref[:] + dk_upd
            else:
                dv_acc_ref[h] = dv_acc_ref[h] + dv_upd
                dk_acc_ref[h] = dk_acc_ref[h] + dk_upd

    # Causal/banded: query blocks with no visible pair against this key block skip;
    # fully-visible interior blocks skip the mask chain (see _fwd_kernel).
    _dispatch_block(body, i, ik, q_ref.shape[0], bk, in_range, causal=causal,
                    window=window, q_offset=q_offset)

    @pl.when(step == num_steps - 1)
    def _():
        for h in _ref_heads(heads):
            if h is None:
                dk_ref[:] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
                dv_ref[:] = dv_acc_ref[:].astype(dv_ref.dtype)
            else:
                sl = slice(h * head_dim, (h + 1) * head_dim)
                dk_ref[:, sl] = (dk_acc_ref[h] * scale).astype(dk_ref.dtype)
                dv_ref[:, sl] = dv_acc_ref[h].astype(dv_ref.dtype)


def _flash_backward(res, g, *, causal: bool, block: int = BLOCK,
                    window: int = 0, heads: int | None = None,
                    per_head_grid: bool = False):
    qx, kx, vx, out, lse = res
    gsz, s = qx.shape[0], qx.shape[1]
    nq = s // block
    # Δ = rowsum(dout ∘ out) PER HEAD, reshaped to the lse layout — XLA fuses
    # this small pass (and in the native layouts the [G,S,H]→[G,H,S] permute is
    # D-free, so it is ~1/D the size of the operand repacks the layouts
    # removed).
    prod = g.astype(jnp.float32) * out.astype(jnp.float32)
    if heads:
        delta = jnp.sum(prod.reshape(gsz, s, heads, -1), axis=-1)  # [G, S, H]
        delta = jnp.transpose(delta, (0, 2, 1))                    # [G, H, S]
        if per_head_grid:   # packed-shape statistics on the folded (B·H) axis
            delta = delta.reshape(gsz * heads, nq, 1, block)
        else:
            delta = delta.reshape(gsz, heads, nq, 1, block)
    else:
        delta = jnp.sum(prod, axis=-1).reshape(gsz, nq, 1, block)
    return flash_backward_blocks(qx, kx, vx, g, lse, delta, causal=causal,
                                 block=block, window=window, heads=heads,
                                 per_head_grid=per_head_grid)


def flash_backward_blocks(qx, kx, vx, g, lse, delta, *, causal: bool,
                          block: int = BLOCK, window: int = 0,
                          q_offset: int = 0, q_offset_dyn=None,
                          heads: int | None = None,
                          per_head_grid: bool = False):
    """One flash-backward pass of a query-block set against a key/value-block set,
    given the GLOBAL softmax statistics: ``(dq, dk, dv)`` contributions.

    Packed layout (the ring schedules' shard form): ``qx/g: [BH, Sq, D]``,
    ``kx/vx: [BH, Sk, D]`` with ``Sq == Sk``, ``lse/delta: [BH, Sq/BLOCK, 1,
    BLOCK]``. Native-flat layout (the model form viewed ``[B, S, H·D]``, no
    transpose repacks — ``heads=H``): ``lse/delta: [B, H, S/BLOCK, 1, BLOCK]``.
    The statistics are of the FULL attention row (all
    keys, not just this block set): ``p = exp(q·kᵀ·scale − lse)`` then yields the
    true softmax coefficients restricted to these keys, so the returned
    contributions sum exactly over block sets — the per-hop building block of the
    trainable ring-of-flash (``parallel.ring_attention.ring_flash_attention``),
    where dk/dv ride the ring with their K/V blocks. ``causal=True`` masks with
    LOCAL block indices, i.e. it assumes q and k share a global origin — ring
    callers use it only for the diagonal hop."""
    s = qx.shape[1]
    if heads and qx.shape[-1] % heads:
        raise ValueError(
            f"native-flat operands need last dim divisible by heads, got "
            f"{qx.shape[-1]} % {heads}")
    d = qx.shape[-1] // (heads or 1)       # per-head width sets the softmax scale
    if kx.shape != qx.shape:
        raise ValueError(
            f"flash_backward_blocks needs equal q/k block sets, got {qx.shape} vs "
            f"{kx.shape}")
    lay = _GridLayout(qx.shape, block, heads, per_head_grid=per_head_grid)
    unroll_heads = None if per_head_grid else heads
    _check_block(s, block)
    _check_offset(q_offset, block)
    dyn = q_offset_dyn is not None
    if dyn and q_offset:
        raise ValueError("q_offset and q_offset_dyn are mutually exclusive")
    scale = 1.0 / (d ** 0.5)
    nq = s // block
    off_blocks = q_offset // block
    one_sided = causal and not q_offset
    # The dynamic-offset banded walk (r5, scalar-prefetch index maps) is
    # bidirectional only, like the forward's.
    dyn_banded = dyn and not causal and _dyn_banded(window, nq, block)
    if not dyn and _banded(window, one_sided, nq, block):
        reach = _band_reach(window, block)
        # dq walks key blocks around the query block (causal: only the past side);
        # dkv walks query blocks around the key block (causal: only the future
        # side). A hop offset shifts the dq walk's center forward and the dkv
        # walk's center backward in local index space.
        dq_base, dq_steps = reach, (reach + 1 if one_sided else 2 * reach + 1)
        kv_base = 0 if one_sided else reach
        kv_steps = reach + 1 if one_sided else 2 * reach + 1
    elif dyn_banded:
        reach = _dyn_band_reach(window, block)
        dq_base = kv_base = reach
        dq_steps = kv_steps = 2 * reach + 1
    else:
        dq_base = kv_base = None
        dq_steps = kv_steps = nq

    # Full (non-banded) walks elide dead-step fetches by aliasing onto the nearest
    # live block (see _elided_key_idx); traced offsets steer banded walks through
    # scalar prefetch when a window permits, else take the plain walk.
    full_reach = _band_reach(window, block) if window else None
    elide = not dyn and (causal or window)

    def _walk_idx(base, center_off=0, kv=False):
        if base is None:
            if elide:
                mk = _elided_query_idx if kv else _elided_key_idx
                return mk(nq, off_blocks, full_reach, causal=causal)
            return lambda i, j, *_: j
        if dyn:
            sign = -1 if kv else 1
            return lambda i, o, off: jnp.clip(
                i + sign * (off[0] // block) + o - base, 0, nq - 1)
        return lambda i, o: jnp.clip(i + center_off + o - base, 0, nq - 1)

    row_spec = lay.row_spec(prefetch=dyn)
    lse_row_spec = lay.lse_row_spec(prefetch=dyn)
    dyn_args = ((jnp.asarray(q_offset_dyn, jnp.int32).reshape(1),) if dyn else ())

    def call(kernel_fn, base, steps, in_specs, out_specs, out_shape, scratch):
        kernel = functools.partial(kernel_fn, scale=scale, causal=causal,
                                   num_steps=steps, num_blocks=nq, band_base=base,
                                   window=window, q_offset=q_offset,
                                   dyn_offset=dyn, heads=unroll_heads,
                                   head_dim=d)
        return _pallas_dispatch(kernel, lay, nq, steps, in_specs, out_specs,
                                out_shape, scratch, dyn)(
            *dyn_args, qx, kx, vx, g, lse, delta)

    dq_walk = lay.walk_spec(_walk_idx(dq_base, off_blocks), prefetch=dyn)
    dq = call(_dq_kernel, dq_base, dq_steps,
              [row_spec, dq_walk, dq_walk, row_spec, lse_row_spec, lse_row_spec],
              [row_spec], [lay.out_shape(qx.dtype)],
              [lay.acc(d)])[0]

    # dkv grid: the query-block axis walks (accumulators persist per key block).
    kv_idx = _walk_idx(kv_base, -off_blocks, kv=True)
    kv_walk = lay.walk_spec(kv_idx, prefetch=dyn)
    kv_lse_walk = lay.lse_walk_spec(kv_idx, prefetch=dyn)
    dk, dv = call(_dkv_kernel, kv_base, kv_steps,
                  [kv_walk, row_spec, row_spec, kv_walk, kv_lse_walk,
                   kv_lse_walk],
                  [row_spec, row_spec],
                  [lay.out_shape(kx.dtype), lay.out_shape(vx.dtype)],
                  [lay.acc(d), lay.acc(d)])
    return dq, dk, dv


# =========================================================================================
# Public API: custom-vjp op on [B, S, H, D], ops.full_attention-compatible
# =========================================================================================


@functools.lru_cache(maxsize=None)
def _make_op(causal: bool, block: int = BLOCK, window: int = 0,
             heads: int | None = None, per_head_grid: bool = False):
    @jax.custom_vjp
    def op(q3, k3, v3):
        out, _ = _flash_forward(q3, k3, v3, causal=causal, block=block,
                                window=window, heads=heads,
                                per_head_grid=per_head_grid)
        return out

    def fwd(q3, k3, v3):
        out, lse = _flash_forward(q3, k3, v3, causal=causal, block=block,
                                  window=window, heads=heads,
                                  per_head_grid=per_head_grid)
        return out, (q3, k3, v3, out, lse)

    def bwd(res, g):
        return _flash_backward(res, g, causal=causal, block=block,
                               window=window, heads=heads,
                               per_head_grid=per_head_grid)

    op.defvjp(fwd, bwd)
    return op


def flash_forward_with_lse(q3: jax.Array, k3: jax.Array, v3: jax.Array, *,
                           causal: bool = False, window: int = 0,
                           q_offset: int = 0, q_offset_dyn=None):
    """Forward-only flash attention that also returns the per-row log-sum-exp:
    ``[BH, S, D]³ → (out [BH, S, D], lse [BH, S/BLOCK, 1, BLOCK])``.

    The lse rows are what blockwise/ring merges need to combine partial attention
    results exactly (``parallel.ring_attention.ring_flash_attention``). Not wrapped in
    the custom VJP — differentiate through ``flash_attention`` instead. Always the
    default BLOCK: the ring merge layouts are written against it. ``window`` /
    ``q_offset`` bind the sliding band and the ring hop offset into the kernels'
    masks (``_visibility_mask``) — the windowed ring-of-flash building block.
    """
    return _flash_forward(q3, k3, v3, causal=causal, window=window,
                          q_offset=q_offset, q_offset_dyn=q_offset_dyn)


def native_mode(head_dim: int) -> str:
    """Which native-layout form a given head width gets: ``"strided"`` (packed
    grid + D-wide lane blocks over the flat operands — packed-kernel
    efficiency, zero repacks) when D is a whole number of 128-lane registers
    (``D % 128 == 0``), else ``"unroll"`` (all-heads blocks + static head
    unroll, the only form Mosaic accepts at sub-register head widths).
    ``FLASH_NATIVE_MODE=unroll`` forces the unroll form everywhere — a
    measurement knob for pricing the two. Anything else is rejected loudly:
    a typo'd mode silently timing the default form would poison exactly the
    measurements the knob exists for."""
    mode = os.environ.get("FLASH_NATIVE_MODE", "").strip().lower()
    if mode not in ("", "unroll"):
        raise ValueError(
            f"FLASH_NATIVE_MODE must be '' (auto: strided at D%128==0, else "
            f"unroll) or 'unroll', got {mode!r}")
    if head_dim % 128 == 0 and mode != "unroll":
        return "strided"
    return "unroll"


def _native_layout_default() -> bool:
    """Whether ``flash_attention`` feeds the kernels the model's [B, S, H, D]
    layout directly (no transpose repacks) instead of packing to [BH, S, D].
    Opt-in via ``FLASH_NATIVE_LAYOUT=1``; the r5 chip captures settled the
    default AGAINST it: deleting the repack copies (11% of the r4 large
    transformer step) buys less than the native forms' direct access patterns
    cost — 57.8% (strided) / 47.2% (unroll) vs packed's 59.5% MFU
    (``bench_results/hw_r5/``). The knob stays for geometries where the
    tradeoff may differ and for re-pricing on future hardware."""
    return os.environ.get("FLASH_NATIVE_LAYOUT", "0").strip().lower() in (
        "1", "true", "yes", "on")


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, block: int | None = None,
                    window: int | None = None,
                    native_layout: bool | None = None) -> jax.Array:
    """Drop-in for ``ops.full_attention``: ``[B, S, H, D]`` → ``[B, S, H, D]``.

    Requires ``S % block == 0`` with ``block`` a multiple of 128 (lane-aligned);
    ``block=None`` (the default) picks the measured-fastest size for the shape via
    ``auto_block``. Differentiable via the two-kernel flash backward; usable as the
    transformer family's ``attention_fn``. ``block`` is a pure performance knob
    (numerics are block-invariant — pinned in tests); tune it with
    ``bench_attention.py --block``. ``native_layout`` (default: the
    ``FLASH_NATIVE_LAYOUT`` env knob) skips the [B,S,H,D]↔[BH,S,D] repacks,
    feeding the kernels the flat [B,S,H·D] view in the form ``native_mode``
    picks for the head width: STRIDED at D%128==0 (packed grid and caps, lane-
    block index maps) or UNROLL otherwise (static head unroll over lane
    slices; auto-block caps block·H·D at ``NATIVE_BLOCK_ELEMS``). Measured on
    v5e: packed 59.5% MFU vs strided 57.8% vs unroll 47.2% at the large-
    transformer config — the repacks are cheaper than either direct access
    pattern, so packed stays the default (``bench_results/hw_r5/``).

    ``window=W`` is sliding-window/local attention with ``full_attention``'s exact
    semantics (distance < W; causal restricts to the past side) — and a BANDED grid:
    the step axis walks only key-block offsets within the band (``_band_reach``), so
    both compute AND grid/pipeline overhead are O(S·W) rather than O(S²) — the r2
    full-grid + ``@pl.when``-skip formulation still paid (S/B)² grid steps, which
    dominated at S ≥ 64k. Out-of-band blocks cost nothing: they are never stepped.
    """
    b, s, h, d = q.shape
    if native_layout is None:
        native_layout = _native_layout_default()
    strided = native_layout and native_mode(d) == "strided"
    if block is None:
        # The strided form keeps packed-size [block, D] refs, so it takes the
        # packed caps; only the all-heads unroll form pays the block·H·D
        # envelope. A geometry whose SMALLEST legal block (128·H·D) already
        # busts that envelope can't run native-unroll at any block — for the
        # auto path that is a layout preference, not a user contract, so fall
        # back to the packed layout (same math, repacks paid) with a warning
        # rather than dying at trace time; explicitly requested blocks below
        # keep the hard error.
        if (native_layout and not strided
                and 128 * h * d > NATIVE_BLOCK_ELEMS):
            warnings.warn(
                f"native-layout flash cannot tile heads*head_dim={h * d} "
                f"(128*{h * d} exceeds the {NATIVE_BLOCK_ELEMS}-element VMEM "
                f"envelope); falling back to the packed layout for this shape",
                stacklevel=2)
            native_layout = False
        block = auto_block(s, int(window or 0),
                           native_hd=h * d if native_layout and not strided
                           else None)
    elif native_layout and not strided and block * h * d > NATIVE_BLOCK_ELEMS:
        # Explicit blocks get the same VMEM envelope the auto path respects:
        # native-flat blocks hold all H heads, so block·H·D is the real
        # working-set knob and oversizing it is a Mosaic scoped-vmem compile
        # failure on chip, not a perf tradeoff.
        raise ValueError(
            f"native-layout flash needs block*heads*head_dim <= "
            f"{NATIVE_BLOCK_ELEMS} (got {block}*{h}*{d} = {block * h * d}); "
            f"pass a smaller block or use the packed layout")
    _check_block(s, block)
    validate_window(window)
    if native_layout:
        # [B, S, H, D] → [B, S, H·D] is a free contiguous view (the repack the
        # packed path pays is the S↔H transpose below, not this reshape).
        op = _make_op(bool(causal), int(block), int(window or 0), heads=h,
                      per_head_grid=strided)
        return op(q.reshape(b, s, h * d), k.reshape(b, s, h * d),
                  v.reshape(b, s, h * d)).reshape(b, s, h, d)
    op = _make_op(bool(causal), int(block), int(window or 0))
    to3 = lambda x: jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    out3 = op(to3(q), to3(k), to3(v))
    return jnp.transpose(out3.reshape(b, h, s, d), (0, 2, 1, 3))


def dispatch_uses_flash(s: int) -> bool:
    """The routing predicate behind ``dispatch_attention`` — exported so callers
    labelling measurements (bench_transformer.py) can't desync from the dispatch."""
    return s >= FLASH_MIN_SEQ and s % 128 == 0


def dispatch_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                       causal: bool = False,
                       window: int | None = None) -> jax.Array:
    """``full_attention``-compatible attention that picks the measured-faster
    implementation per shape: XLA's dense path below ``FLASH_MIN_SEQ`` (where the
    whole score tile stays on-chip and dense wins 1.5-5× on v5e), the flash
    kernels at and above it (4.7-6.9× the other way; the crossover was measured
    windowed too — 4.1× at S=2048 W=256) — so enabling ``--flash-attention`` can
    never regress throughput the way the r3 trainer capture did (45.96 vs 86.09
    steps/s at S=256, ``bench_results/hw_r3/bench_transformer_flash_tpu.json``).
    Shapes the kernels cannot tile (S not a multiple of 128) also take the dense
    path."""
    if not dispatch_uses_flash(q.shape[1]):
        return full_attention(q, k, v, causal=causal, window=window)
    return flash_attention(q, k, v, causal=causal, window=window)
