"""Paged-attention decode: attend through a page table, dequant fused in.

The TPU half of the paged KV cache (DESIGN.md §27). The gather adapters in
``models/lm.py`` materialize each slot's logical ``[S]`` view from the page
pool and run the contiguous attention on it — bitwise-exact, but the gather
writes the whole view back through HBM before attention reads it again. This
module's kernel fuses the two passes: a Pallas grid walks each slot's pages
with the PAGE TABLE as a scalar-prefetch operand (the index map reads
``table[b, j]`` to address the pool block directly, the
``PrefetchScalarGridSpec`` pattern from ``ops/pallas_attention.py``'s traced
ring offsets), streaming each page HBM→VMEM exactly once into an
online-softmax accumulator — and for int8/fp8 pools the per-head dequant
scale multiplies inside the kernel, so HBM streams the NARROW codes.

Two implementations, one contract:

- ``paged_attend_reference`` — pure-XLA gather-attend, the exact einsum/mask
  structure of ``decode_step_slots``'s attention block. The CPU/tier-1 path
  and the numerics oracle.
- ``paged_attend`` — the Pallas kernel (compiled on TPU, interpret mode
  elsewhere, same ``_interpret`` gate as the flash kernels). Online softmax
  changes the reduction ORDER, so the kernel is pinned allclose-tight (not
  bitwise) against the reference in ``tests/test_paged_attention.py``;
  the engine's default paged path stays on the gather adapters, which ARE
  bitwise, and opts into the kernel per-platform.

Layouts (decode-time, one query token per slot): ``q [B, G, R, D]`` (query
heads grouped by their shared KV head — GQA-ready; ``R == 1`` plain MHA is a
degenerate grouping), pools ``[num_pages, page_size, G, D]`` with optional
f32 scale pools ``[num_pages, page_size, G]`` (``ops.quant`` quantize-on-
write), ``table [B, P_max]`` int32, positions ``t [B]`` int32. Every
position ``<= t[b]`` must be mapped (the engine's reservation invariant);
unmapped entries point at the allocator's null page, whose junk the
``pos <= t`` (and sliding-window) mask hides exactly as in the dense path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
    MASK_VALUE as NEG,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
    _interpret,
)


def paged_attend_reference(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                           table: jax.Array, t: jax.Array, *,
                           seq_len: int, window: int = 0,
                           k_scale: jax.Array | None = None,
                           v_scale: jax.Array | None = None) -> jax.Array:
    """Gather-attend oracle: ``[B, G, R, D]`` out, ``decode_step_slots``'s
    exact attention math on the table's gathered view."""
    b, g, r, d = q.shape
    ps = k_pool.shape[1]
    p_max = table.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    def view(pool):
        return pool[table].reshape((b, p_max * ps) + pool.shape[2:])[:, :seq_len]

    k_read, v_read = view(k_pool), view(v_pool)
    if k_scale is not None:
        k_read = k_read.astype(jnp.float32) * view(k_scale)[..., None]
        v_read = v_read.astype(jnp.float32) * view(v_scale)[..., None]
    pos = jnp.arange(seq_len)[None]                              # [1, S]
    tb = t[:, None]
    visible = pos <= tb
    if window:
        visible &= tb - pos < window
    visible = visible[:, None, None, :]                          # [B, 1, 1, S]
    scores = jnp.einsum("bgrd,bsgd->bgrs", q * scale, k_read)
    scores = jnp.where(visible, scores, NEG)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bgrs,bsgd->bgrd", weights, v_read)


def _paged_kernel(*refs, groups, rep, head_dim, page_size, p_max, window,
                  quantized):
    # Scalar-prefetch operands come first: the flat page table [B·P_max] and
    # the positions t [B]. Then q [1, H, D] (H = G·R), the pool page blocks
    # [ps, G·D] (k, v[, k_scale, v_scale [ps, G]]), the out ref [1, H, D],
    # and the online-softmax scratch (acc [H, D], m [H, 1], l [H, 1] — f32
    # VMEM persisting across the page walk, exactly the flash forward's
    # accumulator discipline).
    table_ref, t_ref = refs[0], refs[1]
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs[2:]
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs[2:]
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)
    scale = 1.0 / (head_dim ** 0.5)
    t_b = t_ref[b]

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # A page whose first position is already past t holds no visible row —
    # skip its FLOPs (its fetch was aliased onto a live page by the index
    # map's clamp, so it costs no copy either).
    @pl.when(j * page_size <= t_b)
    def _():
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)                        # [1, ps]
        vis = pos <= t_b
        if window:
            vis &= t_b - pos < window
        for g in range(groups):
            kg = k_ref[:, g * head_dim:(g + 1) * head_dim]       # [ps, D]
            vg = v_ref[:, g * head_dim:(g + 1) * head_dim]
            if quantized:
                kg = kg.astype(jnp.float32) * ks_ref[:, g:g + 1]
                vg = vg.astype(jnp.float32) * vs_ref[:, g:g + 1]
            else:
                kg = kg.astype(jnp.float32)
                vg = vg.astype(jnp.float32)
            qg = q_ref[0, g * rep:(g + 1) * rep, :].astype(jnp.float32)  # [R, D]
            s = jax.lax.dot_general(
                qg, kg, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale      # [R, ps]
            s = jnp.where(vis, s, NEG)
            rows = slice(g * rep, (g + 1) * rep)
            m = m_ref[rows]
            l = l_ref[rows]
            m_blk = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(s - m_new)
            p = jnp.where(vis, p, 0.0)
            corr = jnp.exp(m - m_new)
            acc_ref[rows] = acc_ref[rows] * corr + jnp.dot(
                p, vg, preferred_element_type=jnp.float32)
            l_ref[rows] = l * corr + jnp.sum(p, axis=1, keepdims=True)
            m_ref[rows] = m_new

    @pl.when(j == p_max - 1)
    def _():
        l_safe = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def paged_attend(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                 table: jax.Array, t: jax.Array, *, window: int = 0,
                 k_scale: jax.Array | None = None,
                 v_scale: jax.Array | None = None,
                 interpret: bool | None = None) -> jax.Array:
    """Fused page-walk attention: ``[B, G, R, D]`` out without ever
    materializing the gathered ``[B, S]`` view. Grid ``(B, P_max)`` — the
    inner axis walks slot ``b``'s pages, the table (scalar prefetch) steers
    each step's pool block, dead pages (wholly past ``t[b]``) alias onto the
    last live one so they cost neither copy nor FLOPs."""
    b, g, rep, d = q.shape
    num_pages, ps = k_pool.shape[:2]
    p_max = table.shape[1]
    h = g * rep
    quantized = k_scale is not None
    if interpret is None:
        interpret = _interpret()

    q3 = q.reshape(b, h, d)
    kf = k_pool.reshape(num_pages, ps, g * d)
    vf = v_pool.reshape(num_pages, ps, g * d)
    # Dead steps clamp onto the newest live page (same fetch-elision trick as
    # the flash kernels' _elided_key_idx): consecutive steps requesting the
    # same block skip the copy.
    def page_idx(bb, jj, tbl, tt):
        live = jnp.maximum(tt[bb] // ps, 0)
        return tbl[bb, jnp.minimum(jj, live)]

    in_specs = [
        pl.BlockSpec((1, h, d), lambda bb, jj, tbl, tt: (bb, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, ps, g * d),
                     lambda bb, jj, tbl, tt: (page_idx(bb, jj, tbl, tt), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, ps, g * d),
                     lambda bb, jj, tbl, tt: (page_idx(bb, jj, tbl, tt), 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q3, kf, vf]
    if quantized:
        for sc in (k_scale, v_scale):
            in_specs.append(pl.BlockSpec(
                (None, ps, g),
                lambda bb, jj, tbl, tt: (page_idx(bb, jj, tbl, tt), 0, 0),
                memory_space=pltpu.VMEM))
            args.append(sc)
    kernel = functools.partial(
        _paged_kernel, groups=g, rep=rep, head_dim=d, page_size=ps,
        p_max=p_max, window=window, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, p_max),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, h, d),
                                   lambda bb, jj, tbl, tt: (bb, 0, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((h, d), jnp.float32),    # acc
                pltpu.VMEM((h, 1), jnp.float32),    # running max m
                pltpu.VMEM((h, 1), jnp.float32),    # running normalizer l
            ]),
        out_shape=jax.ShapeDtypeStruct((b, h, d), jnp.float32),
        interpret=interpret,
    )(table, t.astype(jnp.int32), *args)
    return out.reshape(b, g, rep, d)
