"""Functional neural-network ops (the framework's op layer).

These are the TPU-native equivalents of the ATen CPU kernels the reference leans on for every
forward/backward (reference ``src/model.py:16-22``; SURVEY.md §2b): each op is a pure function
on arrays, traced once under ``jax.jit`` and compiled by XLA into fused TPU kernels (conv/matmul
on the MXU, elementwise fused into neighbors).
"""

from csed_514_project_distributed_training_using_pytorch_tpu.ops.nn import (
    conv2d,
    max_pool2d,
    dense,
    relu,
    log_softmax,
    nll_loss,
    cross_entropy_loss,
    dropout,
    dropout2d,
    layer_norm,
    gelu,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
    full_attention,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
    dispatch_attention,
    flash_attention,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.initializers import (
    torch_kaiming_uniform,
    torch_fan_in_uniform,
)

__all__ = [
    "conv2d",
    "max_pool2d",
    "dense",
    "relu",
    "log_softmax",
    "nll_loss",
    "cross_entropy_loss",
    "dropout",
    "dropout2d",
    "layer_norm",
    "gelu",
    "full_attention",
    "flash_attention",
    "dispatch_attention",
    "torch_kaiming_uniform",
    "torch_fan_in_uniform",
]
