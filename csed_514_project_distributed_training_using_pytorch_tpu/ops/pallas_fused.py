"""Fully-fused Pallas train step: the ENTIRE forward+backward of the flagship CNN in one
TPU kernel, plus the fused SGD update.

The reference executes its hot loop as ~dozens of separate ATen kernels chained by the C++
autograd engine (forward ``src/model.py:15-22``, backward ``src/train.py:75``); the default
XLA path here compiles the same math into a fused-but-multi-kernel program. This module goes
one step further down the stack — the whole step body (both convs as shifted-slice matmul
accumulations on the MXU, both poolings, both dropouts, both dense layers, log-softmax + NLL,
and the full backward chain to every weight gradient) runs as ONE Pallas kernel, gridded over
batch blocks with gradient accumulation in VMEM-resident output refs, followed by the fused
SGD kernel from ``ops/pallas_kernels.py``. Per-step HBM traffic collapses to: batch in,
grads + loss out; every activation lives and dies in VMEM.

Mosaic lowering notes (verified on TPU v5e): the convs deliberately avoid im2col — Mosaic
rejects concatenation along the lane (last) dimension of narrow-channel patches, and rejects
lane-merging reshapes like ``[bb,4,4,20] -> [bb,320]`` (``infer-vector-layout: unsupported
shape cast``) — so conv1 (C_in=1) is 25 shifted broadcast-MACs on the VPU, conv2 is 25
shifted ``[bb*64, C1] @ [C1, C2]`` MXU matmuls, and fc1 is decomposed over the 16 spatial
positions of its input (matching the model's (H, W, C) flatten order). The 6-D
reshape-and-reduce max-pooling, zero-padded-shift scatter adds, in-kernel 2-D transposes,
and row-slice accumulation into output refs all lower cleanly.

Architecture constants are the flagship model's (models/cnn.py — 28×28×1 input, conv 5×5
1→10, pool, conv 5×5 10→20, pool, fc 320→50, fc 50→10); like production fused-attention
kernels, the kernel is specialized to its model. Dropout masks are sampled OUTSIDE the
kernel (two small bernoulli draws per step) and passed in as {0, 1/keep} scale arrays, so
the kernel stays deterministic given its inputs and the step stays reproducible from the
same fold-in RNG discipline as the unfused path (train/step.py).

Numerics: pinned by tests against a pure-jnp twin (identical math, including the
distribute-to-ties max-pool backward) and — with dropout disabled — against
``jax.value_and_grad`` of the real flax model.
"""

from __future__ import annotations

import functools
import os
import signal
import subprocess
import sys
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BATCH_BLOCK = 16      # batch rows per grid step (~6 MB peak VMEM residency)

# Flagship-model dimensions (models/cnn.py; reference src/model.py:9-13).
H = W = 28
K = 5
C1, C2 = 10, 20
R1 = H - K + 1        # 24 — conv1 output
P1 = R1 // 2          # 12 — pool1 output
R2 = P1 - K + 1       # 8  — conv2 output
P2 = R2 // 2          # 4  — pool2 output
F_IN = P2 * P2 * C2   # 320
F_HID = 50
F_OUT = 10


class FusedGrads(NamedTuple):
    """Flat gradient layout produced by the kernel (reshaped to model shapes by callers)."""

    w1: jax.Array   # [K*K, C1]
    b1: jax.Array   # [1, C1]
    w2: jax.Array   # [K*K*C1, C2]
    b2: jax.Array   # [1, C2]
    w3: jax.Array   # [F_IN, F_HID]
    b3: jax.Array   # [1, F_HID]
    w4: jax.Array   # [F_HID, F_OUT]
    b4: jax.Array   # [1, F_OUT]


def _dot(a, b):
    return jnp.dot(a, b, preferred_element_type=jnp.float32)


def _pool_fwd(z, side):
    """2×2 max pool of [BB, side, side, C] -> [BB, side//2, side//2, C]."""
    bb, _, _, c = z.shape
    zr = z.reshape(bb, side // 2, 2, side // 2, 2, c)
    return zr.max(axis=(2, 4))


def _pool_bwd(z, pooled, dpooled, side):
    """Distribute-to-ties backward of `_pool_fwd` (ties are measure-zero on conv outputs)."""
    bb, _, _, c = z.shape
    zr = z.reshape(bb, side // 2, 2, side // 2, 2, c)
    eq = (zr == pooled[:, :, None, :, None, :]).astype(jnp.float32)
    cnt = eq.sum(axis=(2, 4), keepdims=True)
    dz = eq * (dpooled[:, :, None, :, None, :] / cnt)
    return dz.reshape(bb, side, side, c)


def _fused_kernel(inv_total, x_ref, lab_ref, d2_ref, d1_ref,
                  w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, w4_ref, b4_ref,
                  loss_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, dw3_ref, db3_ref,
                  dw4_ref, db4_ref):
    """One batch block: full forward + backward; grads accumulate across grid steps."""
    bb = x_ref.shape[0]
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _zero():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        for r in (dw1_ref, db1_ref, dw2_ref, db2_ref, dw3_ref, db3_ref,
                  dw4_ref, db4_ref):
            r[:] = jnp.zeros_like(r)

    x = x_ref[:]                                        # [bb, 28, 28, 1]
    lab = lab_ref[:]                                    # [bb, 1] i32
    drop2 = d2_ref[:]                                   # [bb, C2] {0, 1/keep}
    drop1 = d1_ref[:]                                   # [bb, F_HID]
    w1, b1 = w1_ref[:], b1_ref[:]
    w2, b2 = w2_ref[:], b2_ref[:]
    w3, b3 = w3_ref[:], b3_ref[:]
    w4, b4 = w4_ref[:], b4_ref[:]

    # ---- forward ----
    # conv1 (C_in=1): 25 shifted broadcast-MACs — each tap contributes
    # x[:, ky:ky+24, kx:kx+24, :] * w1[tap, :] to every output channel at once.
    z1 = jnp.zeros((bb, R1, R1, C1), jnp.float32) + b1[0, :]
    for ky in range(K):
        for kx in range(K):
            z1 = z1 + x[:, ky:ky + R1, kx:kx + R1, :] * w1[ky * K + kx, :]
    p1 = _pool_fwd(z1, R1)                              # [bb, 12, 12, 10]
    a1 = jnp.maximum(p1, 0.0)

    # conv2: 25 shifted [bb*64, C1] @ [C1, C2] MXU matmuls accumulated.
    z2 = jnp.zeros((bb, R2, R2, C2), jnp.float32) + b2[0, :]
    for ky in range(K):
        for kx in range(K):
            i = (ky * K + kx) * C1
            s = a1[:, ky:ky + R2, kx:kx + R2, :].reshape(bb * R2 * R2, C1)
            z2 = z2 + _dot(s, w2[i:i + C1, :]).reshape(bb, R2, R2, C2)
    zd2 = z2 * drop2[:, None, None, :]                  # channelwise Dropout2d
    p2 = _pool_fwd(zd2, R2)                             # [bb, 4, 4, 20]
    a2 = jnp.maximum(p2, 0.0)

    # fc1 decomposed over the 16 spatial positions of a2, in the model's (H, W, C)
    # flatten order: position (y, x) pairs with weight rows [(y*4+x)*C2, +C2).
    z3 = jnp.zeros((bb, F_HID), jnp.float32) + b3       # [bb, 50]
    for y in range(P2):
        for xx in range(P2):
            i = (y * P2 + xx) * C2
            z3 = z3 + _dot(a2[:, y, xx, :], w3[i:i + C2, :])
    a3 = jnp.maximum(z3, 0.0)
    a3d = a3 * drop1                                    # elementwise dropout
    z4 = _dot(a3d, w4) + b4                             # [bb, 10]

    m = jnp.max(z4, axis=1, keepdims=True)
    s = z4 - m
    lse = jnp.log(jnp.sum(jnp.exp(s), axis=1, keepdims=True))
    classes = jax.lax.broadcasted_iota(jnp.int32, z4.shape, 1)
    onehot = (classes == lab).astype(jnp.float32)
    picked = jnp.sum(onehot * (s - lse), axis=1, keepdims=True)
    loss_ref[:] += -jnp.sum(picked) * inv_total         # mean over the FULL batch

    # ---- backward (of the mean loss) ----
    softmax = jnp.exp(s - lse)
    dz4 = (softmax - onehot) * inv_total                # [bb, 10]
    dw4_ref[:] += _dot(a3d.T, dz4)
    db4_ref[:] += jnp.sum(dz4, axis=0, keepdims=True)

    da3 = _dot(dz4, w4.T) * drop1                       # through dropout
    dz3 = da3 * (z3 > 0.0).astype(jnp.float32)
    db3_ref[:] += jnp.sum(dz3, axis=0, keepdims=True)

    # fc1 backward, per spatial position: weight-row gradients land in the matching
    # row slice of dw3; da2 is rebuilt as a sum of zero-padded single-position maps.
    da2 = jnp.zeros((bb, P2, P2, C2), jnp.float32)
    for y in range(P2):
        for xx in range(P2):
            i = (y * P2 + xx) * C2
            dw3_ref[i:i + C2, :] += _dot(a2[:, y, xx, :].T, dz3)
            piece = _dot(dz3, w3[i:i + C2, :].T).reshape(bb, 1, 1, C2)
            da2 = da2 + jnp.pad(
                piece, ((0, 0), (y, P2 - 1 - y), (xx, P2 - 1 - xx), (0, 0)))
    dp2 = da2 * (p2 > 0.0).astype(jnp.float32)
    dzd2 = _pool_bwd(zd2, p2, dp2, R2)
    dz2 = dzd2 * drop2[:, None, None, :]
    dz2f = dz2.reshape(bb * R2 * R2, C2)
    db2_ref[:] += jnp.sum(dz2f, axis=0, keepdims=True)

    # conv2 backward, per tap: dw2 rows accumulate patch^T @ dz2; da1 accumulates the
    # zero-padded shift of dz2 @ w2_tap^T (the adjoint of the forward's slicing).
    da1 = jnp.zeros((bb, P1, P1, C1), jnp.float32)
    for ky in range(K):
        for kx in range(K):
            i = (ky * K + kx) * C1
            s2 = a1[:, ky:ky + R2, kx:kx + R2, :].reshape(bb * R2 * R2, C1)
            dw2_ref[i:i + C1, :] += _dot(s2.T, dz2f)
            piece = _dot(dz2f, w2[i:i + C1, :].T).reshape(bb, R2, R2, C1)
            da1 = da1 + jnp.pad(
                piece, ((0, 0), (ky, P1 - R2 - ky), (kx, P1 - R2 - kx), (0, 0)))
    dp1 = da1 * (p1 > 0.0).astype(jnp.float32)
    dz1 = _pool_bwd(z1, p1, dp1, R1)
    db1_ref[:] += jnp.sum(dz1.reshape(bb * R1 * R1, C1), axis=0, keepdims=True)
    for ky in range(K):
        for kx in range(K):
            i = ky * K + kx
            dw1_ref[i:i + 1, :] += jnp.sum(
                x[:, ky:ky + R1, kx:kx + R1, :] * dz1, axis=(0, 1, 2)).reshape(1, C1)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("batch_block",))
def fused_loss_and_grads(params_flat: dict, images: jax.Array, labels: jax.Array,
                         drop2: jax.Array, drop1: jax.Array, *,
                         batch_block: int | None = None):
    """Run the fused kernel over the whole batch; returns (mean_loss, FusedGrads).

    ``params_flat``: dict with keys w1 [K*K, C1], b1 [1, C1], w2 [K*K*C1, C2], b2, w3, b3,
    w4, b4 (the model's HWIO conv kernels reshaped; see ``flatten_params``).
    ``drop2``/``drop1``: {0, 1/keep} scale arrays of shape [B, C2] / [B, F_HID].
    ``batch_block=None`` picks the largest divisor of the batch ≤ BATCH_BLOCK (any batch
    size works, at worst block 1); an explicit block must divide the batch.
    """
    b = images.shape[0]
    if batch_block is None:
        bb = next(d for d in range(min(BATCH_BLOCK, b), 0, -1) if b % d == 0)
    else:
        bb = batch_block
        if bb < 1:
            raise ValueError(f"batch block must be >= 1, got {bb}")
        if b % bb:
            raise ValueError(f"batch {b} not divisible by batch block {bb}")
    grid = (b // bb,)

    row = lambda width: pl.BlockSpec((bb,) + width, lambda i: (i,) + (0,) * len(width),
                                     memory_space=pltpu.VMEM)
    whole = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape),
                                       memory_space=pltpu.VMEM)
    p = params_flat
    out_shapes = [
        jax.ShapeDtypeStruct((1, 1), jnp.float32),                 # loss
        jax.ShapeDtypeStruct((K * K, C1), jnp.float32),
        jax.ShapeDtypeStruct((1, C1), jnp.float32),
        jax.ShapeDtypeStruct((K * K * C1, C2), jnp.float32),
        jax.ShapeDtypeStruct((1, C2), jnp.float32),
        jax.ShapeDtypeStruct((F_IN, F_HID), jnp.float32),
        jax.ShapeDtypeStruct((1, F_HID), jnp.float32),
        jax.ShapeDtypeStruct((F_HID, F_OUT), jnp.float32),
        jax.ShapeDtypeStruct((1, F_OUT), jnp.float32),
    ]
    outs = pl.pallas_call(
        functools.partial(_fused_kernel, 1.0 / b),
        grid=grid,
        in_specs=[
            row((H, W, 1)), row((1,)), row((C2,)), row((F_HID,)),
            whole((K * K, C1)), whole((1, C1)),
            whole((K * K * C1, C2)), whole((1, C2)),
            whole((F_IN, F_HID)), whole((1, F_HID)),
            whole((F_HID, F_OUT)), whole((1, F_OUT)),
        ],
        out_specs=[whole((1, 1))] + [whole(s.shape) for s in out_shapes[1:]],
        out_shape=out_shapes,
        interpret=_interpret(),
    )(images.astype(jnp.float32), labels.astype(jnp.int32)[:, None],
      drop2.astype(jnp.float32), drop1.astype(jnp.float32),
      p["w1"], p["b1"], p["w2"], p["b2"], p["w3"], p["b3"], p["w4"], p["b4"])
    loss = outs[0][0, 0]
    return loss, FusedGrads(*outs[1:])


def flatten_params(params: dict) -> dict:
    """Model params (models/cnn.py naming/shapes) -> the kernel's flat matmul layout."""
    return {
        "w1": params["conv1_kernel"].reshape(K * K, C1),
        "b1": params["conv1_bias"].reshape(1, C1),
        "w2": params["conv2_kernel"].reshape(K * K * C1, C2),
        "b2": params["conv2_bias"].reshape(1, C2),
        "w3": params["fc1_kernel"],
        "b3": params["fc1_bias"].reshape(1, F_HID),
        "w4": params["fc2_kernel"],
        "b4": params["fc2_bias"].reshape(1, F_OUT),
    }


def unflatten_grads(g: FusedGrads) -> dict:
    """Kernel gradient layout -> model params pytree (for the SGD update)."""
    return {
        "conv1_kernel": g.w1.reshape(K, K, 1, C1),
        "conv1_bias": g.b1.reshape(C1),
        "conv2_kernel": g.w2.reshape(K, K, C1, C2),
        "conv2_bias": g.b2.reshape(C2),
        "fc1_kernel": g.w3,
        "fc1_bias": g.b3.reshape(F_HID),
        "fc2_kernel": g.w4,
        "fc2_bias": g.b4.reshape(F_OUT),
    }


def probe_compiles(batch: int = BATCH_BLOCK) -> Exception | None:
    """Eagerly compile + run the fused kernel once on a dummy batch; returns the failure
    (or None).  On TPU this exercises the real Mosaic compile path — the interpreter used
    everywhere else cannot prove the hardware lowering works, so callers that opt into the
    fused step should probe before committing to it (advisor finding r1).  Block shapes
    are batch-dependent (the auto-picked block is the largest divisor of ``batch`` ≤
    BATCH_BLOCK), so probe with the batch size you will train at."""
    try:
        flat = {
            "w1": jnp.zeros((K * K, C1)), "b1": jnp.zeros((1, C1)),
            "w2": jnp.zeros((K * K * C1, C2)), "b2": jnp.zeros((1, C2)),
            "w3": jnp.zeros((F_IN, F_HID)), "b3": jnp.zeros((1, F_HID)),
            "w4": jnp.zeros((F_HID, F_OUT)), "b4": jnp.zeros((1, F_OUT)),
        }
        loss, _ = fused_loss_and_grads(
            flat, jnp.zeros((batch, H, W, 1)), jnp.zeros((batch,), jnp.int32),
            jnp.ones((batch, C2)), jnp.ones((batch, F_HID)))
        jax.block_until_ready(loss)
        return None
    except Exception as e:  # Mosaic/XLA compile errors span many exception types
        return e


# Child exit-code contract for the subprocess probe (see probe_compiles_subprocess).
_PROBE_RC_COMPILE_FAILED = 17
_PROBE_RC_NOT_TPU = 21

# Fixed allowance for the probe child's interpreter start + jax import + backend claim,
# on top of the per-batch compile budget.
_PROBE_STARTUP_ALLOWANCE_S = 60.0

_UNPROBED = object()     # sentinel: "no precomputed probe verdict was supplied"


def _configured_platform() -> str:
    """The first explicitly-configured jax platform ('' when unset), read from config/env
    WITHOUT initializing a backend — ``jax.default_backend()`` would claim the chip."""
    cfg = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    return cfg.split(",")[0].strip().lower()


def probe_compiles_subprocess(batches: tuple[int, ...] = (BATCH_BLOCK,), *,
                              timeout_s: float | None = None) -> Exception | None:
    """``probe_compiles`` for every batch size in ``batches``, in a fresh child
    interpreter with a hard deadline; returns the failure (or None).

    Why a child process: a Mosaic compile cannot be cancelled in-process, and through a
    remote-compile service it can take tens of minutes or hang outright (observed on this
    image's tunnelled TPU backend) — an in-process probe would turn the opt-in
    ``--experimental-fused-step`` into a trainer that never starts. The deadline
    (``FUSED_PROBE_TIMEOUT_SECONDS``, default 180 s **per batch size**, plus a fixed
    60 s child-startup allowance) treats slower-than-budget compiles as failures, which
    is the right verdict for a trainer that would face the same compile again for the
    real step.

    MUST run before this process touches the TPU: the chip's claim is exclusive, so a
    child probing while the parent holds the backend blocks until the deadline and
    reports a (safe, conservative) timeout. The child decides platform applicability
    itself — on a non-TPU backend it reports "nothing to probe" (interpret mode proves
    nothing the test suite doesn't already) and this returns None. Termination on
    timeout is graceful (SIGTERM first): SIGKILL on a process holding the tunnelled TPU
    claim can wedge the lease for the parent's own subsequent claim.
    """
    if _configured_platform() == "cpu":
        return None     # explicitly CPU: interpret mode, nothing Mosaic to probe —
        #                 skip the child entirely (it would only import jax to say so)
    if timeout_s is None:
        timeout_s = float(os.environ.get("FUSED_PROBE_TIMEOUT_SECONDS", "180"))
    # The per-batch budget scales to the whole child: one backend init plus one compile
    # per batch size — otherwise two legitimately-under-budget compiles would blow a
    # shared deadline and silently disable the fused step.
    total_timeout_s = _PROBE_STARTUP_ALLOWANCE_S + timeout_s * max(1, len(batches))
    child_code = (
        "import os, sys, time\n"
        "hold = float(os.environ.get('FUSED_PROBE_TEST_SLEEP', '0'))\n"
        "time.sleep(hold) if hold else None\n"
        "import jax\n"
        "from csed_514_project_distributed_training_using_pytorch_tpu.ops import "
        "pallas_fused as pf\n"
        f"if jax.default_backend() != 'tpu': sys.exit({_PROBE_RC_NOT_TPU})\n"
        f"for b in {tuple(batches)!r}:\n"
        "    err = pf.probe_compiles(batch=b)\n"
        "    if err is not None:\n"
        "        sys.stderr.write(f'batch {b}: {type(err).__name__}: {err}')\n"
        f"        sys.exit({_PROBE_RC_COMPILE_FAILED})\n"
        "sys.exit(0)\n")
    proc = subprocess.Popen([sys.executable, "-c", child_code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        _, err_text = proc.communicate(timeout=total_timeout_s)
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return TimeoutError(
            f"fused-kernel compile probe exceeded {total_timeout_s:.0f}s for batches "
            f"{tuple(batches)} (slow/hung Mosaic compile, or the TPU claim is already "
            f"held by this process — probe before the first jax operation)")
    if proc.returncode in (0, _PROBE_RC_NOT_TPU):
        return None
    # Keep enough stderr to act on, and don't blame Mosaic for an environment problem
    # (import failure, crashed interpreter, ...) — only rc 17 is a real compile verdict.
    tail = "\n".join((err_text or "").strip().splitlines()[-5:])
    if proc.returncode == _PROBE_RC_COMPILE_FAILED:
        return RuntimeError(f"fused kernel failed to compile in the probe child:\n{tail}"
                            if tail else "fused kernel failed to compile in the probe "
                                         "child (no stderr)")
    return RuntimeError(
        f"compile-probe child failed for a reason other than kernel compilation "
        f"(rc={proc.returncode}) — environment problem, not a Mosaic verdict:\n{tail}")


def make_fused_train_step(*, learning_rate: float, momentum: float,
                          conv_dropout_rate: float = 0.5,
                          fc_dropout_rate: float = 0.5,
                          fallback_on_compile_error: bool = False,
                          probe_batches: tuple[int, ...] = (BATCH_BLOCK,),
                          probe_result: Exception | None | object = _UNPROBED):
    """Drop-in replacement for ``train.step.make_train_step`` built on the fused kernel:
    ``step(state, images, labels, rng) -> (state, loss)``. Dropout masks are drawn outside
    the kernel from the same per-step fold-in discipline; the update runs through the fused
    Pallas SGD kernel.

    ``fallback_on_compile_error=True`` probes the kernel's real compile path first
    (``probe_compiles``, one probe per batch size in ``probe_batches`` — pass the batch
    sizes the trainer will actually step at, since Mosaic failures can be block-shape
    dependent) and, if any fails, warns and returns the standard unfused step with the
    same hyperparameters — so ``--experimental-fused-step`` degrades to a working trainer instead
    of crashing.  The probe only runs where Mosaic does (TPU backend): in interpret mode
    it could only confirm what the test suite already guarantees, at the cost of an extra
    startup compile.

    ``probe_result`` optionally supplies a precomputed verdict (from
    ``probe_compiles_subprocess``, run before this process first touched the TPU) instead
    of probing in-process here — the in-process probe cannot be cancelled if the Mosaic
    compile is slow or hung, so callers that can probe early (the trainers) should."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_kernels import (
        sgd_momentum_step,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        TrainState,
    )

    if fallback_on_compile_error and (
            probe_result is not _UNPROBED or jax.default_backend() == "tpu"):
        if probe_result is not _UNPROBED:
            err = probe_result
        else:
            err = next((e for e in map(probe_compiles, probe_batches) if e is not None),
                       None)
        if err is not None:
            import warnings

            from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import (
                Net,
            )
            from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
                make_train_step,
            )
            warnings.warn(
                f"fused Pallas step failed to compile on backend "
                f"'{jax.default_backend()}' ({type(err).__name__}: {err}); "
                f"falling back to the unfused XLA step", RuntimeWarning)
            return make_train_step(
                Net(conv_dropout_rate=conv_dropout_rate,
                    fc_dropout_rate=fc_dropout_rate),
                learning_rate=learning_rate, momentum=momentum)

    keep2, keep1 = 1.0 - conv_dropout_rate, 1.0 - fc_dropout_rate

    def step(state, images, labels, rng):
        b = images.shape[0]
        step_rng = jax.random.fold_in(rng, state.step)
        k2, k1 = jax.random.split(step_rng)
        drop2 = jax.random.bernoulli(k2, keep2, (b, C2)).astype(jnp.float32) / keep2
        drop1 = jax.random.bernoulli(k1, keep1, (b, F_HID)).astype(jnp.float32) / keep1
        loss, grads = fused_loss_and_grads(
            flatten_params(state.params), images, labels, drop2, drop1)
        params, velocity = sgd_momentum_step(
            state.params, state.velocity, unflatten_grads(grads),
            learning_rate=learning_rate, momentum=momentum)
        return TrainState(params, velocity, state.step + 1), loss

    return step
