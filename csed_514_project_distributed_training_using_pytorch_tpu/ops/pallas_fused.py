"""Fully-fused Pallas train step: the ENTIRE forward+backward of the flagship CNN in one
TPU kernel, plus the fused SGD update.

The reference executes its hot loop as ~dozens of separate ATen kernels chained by the C++
autograd engine (forward ``src/model.py:15-22``, backward ``src/train.py:75``); the default
XLA path here compiles the same math into a fused-but-multi-kernel program. This module goes
one step further down the stack — the whole step body (both convs via im2col matmuls on the
MXU, both poolings, both dropouts, both dense layers, log-softmax + NLL, and the full
backward chain to every weight gradient) runs as ONE Pallas kernel, gridded over batch
blocks with gradient accumulation in VMEM-resident output refs, followed by the fused SGD
kernel from ``ops/pallas_kernels.py``. Per-step HBM traffic collapses to: batch in, grads +
loss out; every activation lives and dies in VMEM.

Architecture constants are the flagship model's (models/cnn.py — 28×28×1 input, conv 5×5
1→10, pool, conv 5×5 10→20, pool, fc 320→50, fc 50→10); like production fused-attention
kernels, the kernel is specialized to its model. Dropout masks are sampled OUTSIDE the
kernel (two small bernoulli draws per step) and passed in as {0, 1/keep} scale arrays, so
the kernel stays deterministic given its inputs and the step stays reproducible from the
same fold-in RNG discipline as the unfused path (train/step.py).

Numerics: pinned by tests against a pure-jnp twin (identical math, including the
distribute-to-ties max-pool backward) and — with dropout disabled — against
``jax.value_and_grad`` of the real flax model.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BATCH_BLOCK = 16      # batch rows per grid step (~6 MB peak VMEM residency)

# Flagship-model dimensions (models/cnn.py; reference src/model.py:9-13).
H = W = 28
K = 5
C1, C2 = 10, 20
R1 = H - K + 1        # 24 — conv1 output
P1 = R1 // 2          # 12 — pool1 output
R2 = P1 - K + 1       # 8  — conv2 output
P2 = R2 // 2          # 4  — pool2 output
F_IN = P2 * P2 * C2   # 320
F_HID = 50
F_OUT = 10


class FusedGrads(NamedTuple):
    """Flat gradient layout produced by the kernel (reshaped to model shapes by callers)."""

    w1: jax.Array   # [K*K, C1]
    b1: jax.Array   # [1, C1]
    w2: jax.Array   # [K*K*C1, C2]
    b2: jax.Array   # [1, C2]
    w3: jax.Array   # [F_IN, F_HID]
    b3: jax.Array   # [1, F_HID]
    w4: jax.Array   # [F_HID, F_OUT]
    b4: jax.Array   # [1, F_OUT]


def _dot(a, b):
    return jnp.dot(a, b, preferred_element_type=jnp.float32)


def _pool_fwd(z, side):
    """2×2 max pool of [BB, side, side, C] -> [BB, side//2, side//2, C]."""
    bb, _, _, c = z.shape
    zr = z.reshape(bb, side // 2, 2, side // 2, 2, c)
    return zr.max(axis=(2, 4))


def _pool_bwd(z, pooled, dpooled, side):
    """Distribute-to-ties backward of `_pool_fwd` (ties are measure-zero on conv outputs)."""
    bb, _, _, c = z.shape
    zr = z.reshape(bb, side // 2, 2, side // 2, 2, c)
    eq = (zr == pooled[:, :, None, :, None, :]).astype(jnp.float32)
    cnt = eq.sum(axis=(2, 4), keepdims=True)
    dz = eq * (dpooled[:, :, None, :, None, :] / cnt)
    return dz.reshape(bb, side, side, c)


def _im2col(x, out_side):
    """[BB, s, s, C] -> [BB, out_side, out_side, K*K*C] patches in (ky, kx, c) order —
    matching an HWIO kernel reshaped to [K*K*C, C_out]."""
    cols = [x[:, ky:ky + out_side, kx:kx + out_side, :]
            for ky in range(K) for kx in range(K)]
    return jnp.concatenate(cols, axis=-1)


def _col2im(dpatches, out_side, in_side, c):
    """Adjoint of `_im2col`: scatter-add patch gradients back to the input feature map,
    expressed as a sum of zero-padded shifts (static shapes, Mosaic-friendly)."""
    bb = dpatches.shape[0]
    acc = jnp.zeros((bb, in_side, in_side, c), jnp.float32)
    for ky in range(K):
        for kx in range(K):
            i = (ky * K + kx) * c
            piece = dpatches[..., i:i + c]
            acc = acc + jnp.pad(
                piece,
                ((0, 0), (ky, in_side - out_side - ky), (kx, in_side - out_side - kx),
                 (0, 0)))
    return acc


def _fused_kernel(inv_total, x_ref, lab_ref, d2_ref, d1_ref,
                  w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, w4_ref, b4_ref,
                  loss_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, dw3_ref, db3_ref,
                  dw4_ref, db4_ref):
    """One batch block: full forward + backward; grads accumulate across grid steps."""
    bb = x_ref.shape[0]
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _zero():
        loss_ref[:] = jnp.zeros_like(loss_ref)
        for r in (dw1_ref, db1_ref, dw2_ref, db2_ref, dw3_ref, db3_ref,
                  dw4_ref, db4_ref):
            r[:] = jnp.zeros_like(r)

    x = x_ref[:]                                        # [bb, 28, 28, 1]
    lab = lab_ref[:]                                    # [bb, 1] i32
    drop2 = d2_ref[:]                                   # [bb, C2] {0, 1/keep}
    drop1 = d1_ref[:]                                   # [bb, F_HID]
    w1, b1 = w1_ref[:], b1_ref[:]
    w2, b2 = w2_ref[:], b2_ref[:]
    w3, b3 = w3_ref[:], b3_ref[:]
    w4, b4 = w4_ref[:], b4_ref[:]

    # ---- forward ----
    pat1 = _im2col(x, R1)                               # [bb, 24, 24, 25]
    z1 = (_dot(pat1.reshape(bb * R1 * R1, K * K), w1) + b1).reshape(bb, R1, R1, C1)
    p1 = _pool_fwd(z1, R1)                              # [bb, 12, 12, 10]
    a1 = jnp.maximum(p1, 0.0)

    pat2 = _im2col(a1, R2)                              # [bb, 8, 8, 250]
    z2 = (_dot(pat2.reshape(bb * R2 * R2, K * K * C1), w2) + b2).reshape(bb, R2, R2, C2)
    zd2 = z2 * drop2[:, None, None, :]                  # channelwise Dropout2d
    p2 = _pool_fwd(zd2, R2)                             # [bb, 4, 4, 20]
    a2 = jnp.maximum(p2, 0.0)
    f = a2.reshape(bb, F_IN)                            # (H, W, C) flatten == model's

    z3 = _dot(f, w3) + b3                               # [bb, 50]
    a3 = jnp.maximum(z3, 0.0)
    a3d = a3 * drop1                                    # elementwise dropout
    z4 = _dot(a3d, w4) + b4                             # [bb, 10]

    m = jnp.max(z4, axis=1, keepdims=True)
    s = z4 - m
    lse = jnp.log(jnp.sum(jnp.exp(s), axis=1, keepdims=True))
    classes = jax.lax.broadcasted_iota(jnp.int32, z4.shape, 1)
    onehot = (classes == lab).astype(jnp.float32)
    picked = jnp.sum(onehot * (s - lse), axis=1, keepdims=True)
    loss_ref[:] += -jnp.sum(picked) * inv_total         # mean over the FULL batch

    # ---- backward (of the mean loss) ----
    softmax = jnp.exp(s - lse)
    dz4 = (softmax - onehot) * inv_total                # [bb, 10]
    dw4_ref[:] += _dot(a3d.T, dz4)
    db4_ref[:] += jnp.sum(dz4, axis=0, keepdims=True)

    da3 = _dot(dz4, w4.T) * drop1                       # through dropout
    dz3 = da3 * (z3 > 0.0).astype(jnp.float32)
    dw3_ref[:] += _dot(f.T, dz3)
    db3_ref[:] += jnp.sum(dz3, axis=0, keepdims=True)

    da2 = _dot(dz3, w3.T).reshape(bb, P2, P2, C2)
    dp2 = da2 * (p2 > 0.0).astype(jnp.float32)
    dzd2 = _pool_bwd(zd2, p2, dp2, R2)
    dz2 = dzd2 * drop2[:, None, None, :]
    dz2f = dz2.reshape(bb * R2 * R2, C2)
    dw2_ref[:] += _dot(pat2.reshape(bb * R2 * R2, K * K * C1).T, dz2f)
    db2_ref[:] += jnp.sum(dz2f, axis=0, keepdims=True)

    dpat2 = _dot(dz2f, w2.T).reshape(bb, R2, R2, K * K * C1)
    da1 = _col2im(dpat2, R2, P1, C1)
    dp1 = da1 * (p1 > 0.0).astype(jnp.float32)
    dz1 = _pool_bwd(z1, p1, dp1, R1)
    dz1f = dz1.reshape(bb * R1 * R1, C1)
    dw1_ref[:] += _dot(pat1.reshape(bb * R1 * R1, K * K).T, dz1f)
    db1_ref[:] += jnp.sum(dz1f, axis=0, keepdims=True)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("batch_block",))
def fused_loss_and_grads(params_flat: dict, images: jax.Array, labels: jax.Array,
                         drop2: jax.Array, drop1: jax.Array, *,
                         batch_block: int | None = None):
    """Run the fused kernel over the whole batch; returns (mean_loss, FusedGrads).

    ``params_flat``: dict with keys w1 [K*K, C1], b1 [1, C1], w2 [K*K*C1, C2], b2, w3, b3,
    w4, b4 (the model's HWIO conv kernels reshaped; see ``flatten_params``).
    ``drop2``/``drop1``: {0, 1/keep} scale arrays of shape [B, C2] / [B, F_HID].
    ``batch_block=None`` picks the largest divisor of the batch ≤ BATCH_BLOCK (any batch
    size works, at worst block 1); an explicit block must divide the batch.
    """
    b = images.shape[0]
    if batch_block is None:
        bb = next(d for d in range(min(BATCH_BLOCK, b), 0, -1) if b % d == 0)
    else:
        bb = batch_block
        if bb < 1:
            raise ValueError(f"batch block must be >= 1, got {bb}")
        if b % bb:
            raise ValueError(f"batch {b} not divisible by batch block {bb}")
    grid = (b // bb,)

    row = lambda width: pl.BlockSpec((bb,) + width, lambda i: (i,) + (0,) * len(width),
                                     memory_space=pltpu.VMEM)
    whole = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape),
                                       memory_space=pltpu.VMEM)
    p = params_flat
    out_shapes = [
        jax.ShapeDtypeStruct((1, 1), jnp.float32),                 # loss
        jax.ShapeDtypeStruct((K * K, C1), jnp.float32),
        jax.ShapeDtypeStruct((1, C1), jnp.float32),
        jax.ShapeDtypeStruct((K * K * C1, C2), jnp.float32),
        jax.ShapeDtypeStruct((1, C2), jnp.float32),
        jax.ShapeDtypeStruct((F_IN, F_HID), jnp.float32),
        jax.ShapeDtypeStruct((1, F_HID), jnp.float32),
        jax.ShapeDtypeStruct((F_HID, F_OUT), jnp.float32),
        jax.ShapeDtypeStruct((1, F_OUT), jnp.float32),
    ]
    outs = pl.pallas_call(
        functools.partial(_fused_kernel, 1.0 / b),
        grid=grid,
        in_specs=[
            row((H, W, 1)), row((1,)), row((C2,)), row((F_HID,)),
            whole((K * K, C1)), whole((1, C1)),
            whole((K * K * C1, C2)), whole((1, C2)),
            whole((F_IN, F_HID)), whole((1, F_HID)),
            whole((F_HID, F_OUT)), whole((1, F_OUT)),
        ],
        out_specs=[whole((1, 1))] + [whole(s.shape) for s in out_shapes[1:]],
        out_shape=out_shapes,
        interpret=_interpret(),
    )(images.astype(jnp.float32), labels.astype(jnp.int32)[:, None],
      drop2.astype(jnp.float32), drop1.astype(jnp.float32),
      p["w1"], p["b1"], p["w2"], p["b2"], p["w3"], p["b3"], p["w4"], p["b4"])
    loss = outs[0][0, 0]
    return loss, FusedGrads(*outs[1:])


def flatten_params(params: dict) -> dict:
    """Model params (models/cnn.py naming/shapes) -> the kernel's flat matmul layout."""
    return {
        "w1": params["conv1_kernel"].reshape(K * K, C1),
        "b1": params["conv1_bias"].reshape(1, C1),
        "w2": params["conv2_kernel"].reshape(K * K * C1, C2),
        "b2": params["conv2_bias"].reshape(1, C2),
        "w3": params["fc1_kernel"],
        "b3": params["fc1_bias"].reshape(1, F_HID),
        "w4": params["fc2_kernel"],
        "b4": params["fc2_bias"].reshape(1, F_OUT),
    }


def unflatten_grads(g: FusedGrads) -> dict:
    """Kernel gradient layout -> model params pytree (for the SGD update)."""
    return {
        "conv1_kernel": g.w1.reshape(K, K, 1, C1),
        "conv1_bias": g.b1.reshape(C1),
        "conv2_kernel": g.w2.reshape(K, K, C1, C2),
        "conv2_bias": g.b2.reshape(C2),
        "fc1_kernel": g.w3,
        "fc1_bias": g.b3.reshape(F_HID),
        "fc2_kernel": g.w4,
        "fc2_bias": g.b4.reshape(F_OUT),
    }


def probe_compiles(batch: int = BATCH_BLOCK) -> Exception | None:
    """Eagerly compile + run the fused kernel once on a dummy batch; returns the failure
    (or None).  On TPU this exercises the real Mosaic compile path — the interpreter used
    everywhere else cannot prove the hardware lowering works, so callers that opt into the
    fused step should probe before committing to it (advisor finding r1).  Block shapes
    are batch-dependent (the auto-picked block is the largest divisor of ``batch`` ≤
    BATCH_BLOCK), so probe with the batch size you will train at."""
    try:
        flat = {
            "w1": jnp.zeros((K * K, C1)), "b1": jnp.zeros((1, C1)),
            "w2": jnp.zeros((K * K * C1, C2)), "b2": jnp.zeros((1, C2)),
            "w3": jnp.zeros((F_IN, F_HID)), "b3": jnp.zeros((1, F_HID)),
            "w4": jnp.zeros((F_HID, F_OUT)), "b4": jnp.zeros((1, F_OUT)),
        }
        loss, _ = fused_loss_and_grads(
            flat, jnp.zeros((batch, H, W, 1)), jnp.zeros((batch,), jnp.int32),
            jnp.ones((batch, C2)), jnp.ones((batch, F_HID)))
        jax.block_until_ready(loss)
        return None
    except Exception as e:  # Mosaic/XLA compile errors span many exception types
        return e


def make_fused_train_step(*, learning_rate: float, momentum: float,
                          conv_dropout_rate: float = 0.5,
                          fc_dropout_rate: float = 0.5,
                          fallback_on_compile_error: bool = False,
                          probe_batches: tuple[int, ...] = (BATCH_BLOCK,)):
    """Drop-in replacement for ``train.step.make_train_step`` built on the fused kernel:
    ``step(state, images, labels, rng) -> (state, loss)``. Dropout masks are drawn outside
    the kernel from the same per-step fold-in discipline; the update runs through the fused
    Pallas SGD kernel.

    ``fallback_on_compile_error=True`` probes the kernel's real compile path first
    (``probe_compiles``, one probe per batch size in ``probe_batches`` — pass the batch
    sizes the trainer will actually step at, since Mosaic failures can be block-shape
    dependent) and, if any fails, warns and returns the standard unfused step with the
    same hyperparameters — so ``--use-fused-step`` degrades to a working trainer instead
    of crashing.  The probe only runs where Mosaic does (TPU backend): in interpret mode
    it could only confirm what the test suite already guarantees, at the cost of an extra
    startup compile."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_kernels import (
        sgd_momentum_step,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        TrainState,
    )

    if fallback_on_compile_error and jax.default_backend() == "tpu":
        err = next((e for e in map(probe_compiles, probe_batches) if e is not None),
                   None)
        if err is not None:
            import warnings

            from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import (
                Net,
            )
            from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
                make_train_step,
            )
            warnings.warn(
                f"fused Pallas step failed to compile on backend "
                f"'{jax.default_backend()}' ({type(err).__name__}: {err}); "
                f"falling back to the unfused XLA step", RuntimeWarning)
            return make_train_step(
                Net(conv_dropout_rate=conv_dropout_rate,
                    fc_dropout_rate=fc_dropout_rate),
                learning_rate=learning_rate, momentum=momentum)

    keep2, keep1 = 1.0 - conv_dropout_rate, 1.0 - fc_dropout_rate

    def step(state, images, labels, rng):
        b = images.shape[0]
        step_rng = jax.random.fold_in(rng, state.step)
        k2, k1 = jax.random.split(step_rng)
        drop2 = jax.random.bernoulli(k2, keep2, (b, C2)).astype(jnp.float32) / keep2
        drop1 = jax.random.bernoulli(k1, keep1, (b, F_HID)).astype(jnp.float32) / keep1
        loss, grads = fused_loss_and_grads(
            flatten_params(state.params), images, labels, drop2, drop1)
        params, velocity = sgd_momentum_step(
            state.params, state.velocity, unflatten_grads(grads),
            learning_rate=learning_rate, momentum=momentum)
        return TrainState(params, velocity, state.step + 1), loss

    return step
