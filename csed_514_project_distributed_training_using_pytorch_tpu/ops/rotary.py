"""Rotary position embeddings (RoPE) — relative positions by rotation.

Beyond-parity op (the reference has no attention at all, reference
``src/model.py:4-22``): the standard RoPE formulation — each head-dim pair
``(2i, 2i+1)`` rotates by ``pos / base^(2i/D)`` radians — giving attention scores that
depend only on RELATIVE query/key distance (``⟨R(p)q, R(p')k⟩`` is a function of
``p - p'``; pinned as the shift-invariance property in ``tests/test_rotary.py``).

Applied to q/k AFTER projection and BEFORE the pluggable attention core, on the full
``[B, S, H, D]`` activations: the rotation is elementwise in the sequence dim, so under
GSPMD it shards with whatever layout the activations carry — RoPE composes with the
dense, flash, ring, and ulysses cores (and with GQA's broadcast K/V) with no
core-specific code. The LM decode path rotates its single position by the same formula
(``decode_step``), keeping the decode-parity invariant.

TPU notes: the rotation is a fused multiply-add on the VPU (cos/sin tables are
``[S, D/2]`` f32, computed inline — XLA hoists them out of the scan); no gather, no
complex numbers (the half-split formulation avoids interleaved strides).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _angles(positions: jax.Array, dim: int, base: float) -> jax.Array:
    """``[*pos_shape, dim/2]`` rotation angles for head dim ``dim``."""
    if dim % 2:
        raise ValueError(f"RoPE needs an even head dim, got {dim}")
    inv_freq = base ** (-jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    return positions.astype(jnp.float32)[..., None] * inv_freq


def apply_rotary(x: jax.Array, positions: jax.Array, *,
                 base: float = 10000.0) -> jax.Array:
    """Rotate ``x: [..., S, H, D]`` by per-position angles (``positions: [S]`` or a
    scalar for single-token decode on ``[..., H, D]``).

    Half-split layout (GPT-NeoX style): the first D/2 dims pair with the last D/2 —
    ``x1' = x1·cos − x2·sin``, ``x2' = x2·cos + x1·sin``. Runs in f32 and casts back.
    """
    d = x.shape[-1]
    ang = _angles(positions, d, base)                 # [..., D/2]
    if positions.ndim:                                # [S] → broadcast over H
        ang = ang[..., :, None, :]                    # [S, 1, D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1).astype(x.dtype)
