"""Quantized execution: the dtype/scale policy and every piece of scale math.

One module owns quantization so the three consumers — the serving engine's KV
cache (``models/lm.py``), the quantized-weight matmul paths, and the byte-true
accounting the roofline/telemetry layer reports — can never disagree about what
a scale means.

Two independent knobs make up a :class:`QuantPolicy`:

- ``kv_dtype`` — the serving KV-cache plane dtype. ``"model"`` (default) keeps
  today's behavior: planes in the model's activation dtype, bitwise-identical
  code path, no scales. ``"fp32"``/``"bf16"`` are plain-cast planes (no scales;
  bf16 halves cache bytes at bf16 rounding). ``"int8"`` (and ``"fp8"`` where the
  jax build has ``float8_e4m3fn``) are **quantize-on-write** planes: each
  written K/V row ``[KV_H, Dh]`` stores one symmetric scale per head alongside
  the narrow row (scale planes ``[..., S, KV_H]`` in f32), and attention
  **dequantizes in-kernel** — the narrow plane is what HBM streams; the upcast
  happens on-chip, fused into the score/value einsums. Per-head-per-position
  granularity is the finest the row-write layout gives for free, and it keeps
  the decode program count at one: scales are data written by the same
  fixed-shape row scatter as the planes.

- ``weights`` — ``"off"`` (fp32 kernels, untouched), ``"w8"`` (int8 kernels +
  per-output-channel scales, f32 activations: the weight-HBM-halving serving
  mode), or ``"w8a8"`` (int8 kernels AND dynamically int8-quantized
  activations: the int8-MXU matmul path, ``int8 x int8 -> int32`` accumulate —
  the form whose higher matmul peak the training MFU denominator cites).
  :func:`quantize_params` rewrites only 2-D ``*_kernel`` leaves into
  :class:`QuantizedTensor` pytree nodes; embeddings, LayerNorm params and
  biases stay exact. :func:`dense_any` dispatches on the leaf type, so code
  paths shared with the unquantized engine stay bitwise identical when the
  policy is off (a plain array takes the exact ``ops.dense`` call).

Accounting is **byte-true by construction**: :func:`tree_bytes` sums the real
``size * itemsize`` of live buffers (quantized planes, scale planes, int8
kernels, their f32 scales — everything), so a reported bytes/token is what HBM
actually moves, never a dtype-naive estimate.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.ops import nn as ops_nn

# Symmetric-quantization ranges. int8 uses +/-127 (not -128: symmetric, so a
# row and its negation quantize to negations — no bias toward either sign).
# fp8 (e4m3fn) has its own hardware rounding; the scale maps a row's amax to
# the format's max normal so the whole row lands in range.
INT8_QMAX = 127.0
FP8_QMAX = 448.0          # float8_e4m3fn max normal

KV_DTYPES = ("model", "fp32", "bf16", "int8", "fp8")
WEIGHT_POLICIES = ("off", "w8", "w8a8")


def fp8_dtype():
    """The fp8 storage dtype, or None when this jax build lacks it."""
    return getattr(jnp, "float8_e4m3fn", None)


@dataclasses.dataclass(frozen=True)
class QuantPolicy:
    """The dtype/scale policy threaded through engine construction.

    ``kv_dtype``: one of :data:`KV_DTYPES`; ``weights``: one of
    :data:`WEIGHT_POLICIES`. The default policy is a no-op — every path it
    touches stays bitwise identical to the unquantized code."""

    kv_dtype: str = "model"
    weights: str = "off"

    def __post_init__(self):
        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(f"kv_dtype {self.kv_dtype!r} not in {KV_DTYPES}")
        if self.weights not in WEIGHT_POLICIES:
            raise ValueError(f"weights {self.weights!r} not in "
                             f"{WEIGHT_POLICIES}")
        if self.kv_dtype == "fp8" and fp8_dtype() is None:
            raise ValueError("kv_dtype 'fp8' needs a jax build with "
                             "float8_e4m3fn")

    @property
    def off(self) -> bool:
        return self.kv_dtype == "model" and self.weights == "off"


def resolve_kv_dtype(spec: str, model_dtype) -> tuple[object, bool]:
    """``(plane_dtype, scaled)`` for a kv_dtype spec: ``scaled`` marks the
    quantize-on-write formats that carry per-head scale planes."""
    if spec == "model":
        return model_dtype, False
    if spec == "fp32":
        return jnp.float32, False
    if spec == "bf16":
        return jnp.bfloat16, False
    if spec == "int8":
        return jnp.int8, True
    if spec == "fp8":
        f8 = fp8_dtype()
        if f8 is None:
            raise ValueError("this jax build has no float8_e4m3fn")
        return f8, True
    raise ValueError(f"unknown kv_dtype {spec!r} (choices: {KV_DTYPES})")


def _qmax(qdtype) -> float:
    return INT8_QMAX if jnp.dtype(qdtype) == jnp.int8 else FP8_QMAX


# ---------------------------------------------------------------------------
# Row (KV-cache) quantization: one symmetric scale per last-axis vector
# ---------------------------------------------------------------------------


def quantize_rows(x: jax.Array, qdtype) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-row quantization over the LAST axis: ``[..., D]`` f32 ->
    (``[..., D]`` in ``qdtype``, ``[...]`` f32 scales).

    For a K/V row ``[KV_H, Dh]`` this is one scale per head — the granularity
    the KV cache stores. ``scale = amax / qmax`` (1.0 for an all-zero row, so
    dequant still returns exact zeros); int8 rounds-to-nearest and clips, fp8
    uses the format's own cast rounding."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(amax > 0, amax / _qmax(qdtype), 1.0)
    q = x / scale[..., None]
    if jnp.dtype(qdtype) == jnp.int8:
        q = jnp.clip(jnp.round(q), -INT8_QMAX, INT8_QMAX)
    return q.astype(qdtype), scale


def dequantize_rows(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Invert :func:`quantize_rows`: ``[..., D]`` narrow + ``[...]`` scales ->
    f32. Inside an attention kernel this is the in-kernel upcast — XLA fuses
    the cast/multiply into the einsum that consumes it, so HBM only ever
    streams the narrow plane."""
    return q.astype(jnp.float32) * scale[..., None]


# ---------------------------------------------------------------------------
# Weight quantization: per-output-channel int8 kernels + quantized matmuls
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """An int8 kernel + its per-output-channel f32 scales, as ONE pytree node.

    Drops into a flax params tree where the plain ``[in, out]`` kernel array
    sat, so checkpoint/device-put/tree_map plumbing is untouched; ``mode``
    (``"w8"`` / ``"w8a8"``) rides in the static treedef — it selects the
    matmul path at trace time, never at run time."""

    def __init__(self, q: jax.Array, scale: jax.Array, mode: str = "w8"):
        if mode not in ("w8", "w8a8"):
            raise ValueError(f"mode {mode!r} not in ('w8', 'w8a8')")
        self.q = q
        self.scale = scale
        self.mode = mode

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return int(self.q.size) * jnp.dtype(self.q.dtype).itemsize + \
            int(self.scale.size) * jnp.dtype(self.scale.dtype).itemsize

    def dequantize(self) -> jax.Array:
        return self.q.astype(jnp.float32) * self.scale

    def tree_flatten(self):
        return (self.q, self.scale), self.mode

    @classmethod
    def tree_unflatten(cls, mode, children):
        return cls(*children, mode=mode)

    def __repr__(self):
        return (f"QuantizedTensor(shape={tuple(self.q.shape)}, "
                f"mode={self.mode!r})")


def quantize_tensor(w: jax.Array, mode: str = "w8") -> QuantizedTensor:
    """Per-output-channel symmetric int8: ``[in, out]`` f32 -> int8 kernel +
    ``[out]`` scales (each output column scaled by its own amax)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, 1.0)
    q = jnp.clip(jnp.round(w / scale), -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return QuantizedTensor(q, scale, mode=mode)


def int8_matmul(x: jax.Array, w: QuantizedTensor) -> jax.Array:
    """The quantized matmul paths, selected by ``w.mode``:

    - ``w8`` (weight-only): f32 activations against the int8 kernel; the
      kernel's upcast fuses into the matmul (weight HBM is the win), the
      per-channel scale is applied to the f32 product — exact, since each
      output column shares one scale.
    - ``w8a8``: activations dynamically quantized per row (one scale per
      ``[..., in]`` vector), then ``int8 x int8 -> int32`` via ``dot_general``
      with an int32 accumulator — the MXU/VPU integer path whose higher matmul
      peak quantized-training MFU quotes — and one f32 rescale at the end.
    """
    if w.mode == "w8a8":
        xq, xscale = quantize_rows(x, jnp.int8)
        acc = jax.lax.dot_general(
            xq, w.q, (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * xscale[..., None] * w.scale
    out = jnp.matmul(x.astype(jnp.float32), w.q.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out * w.scale


def dense_any(x: jax.Array, w, b: jax.Array | None = None) -> jax.Array:
    """``ops.dense`` that tolerates a quantized kernel: a plain array takes
    the EXACT ``ops.dense`` call (bitwise-identical path — the policy-off
    pin), a :class:`QuantizedTensor` takes its quantized matmul."""
    if isinstance(w, QuantizedTensor):
        out = int8_matmul(x, w).astype(x.dtype)
        return out if b is None else out + b
    return ops_nn.dense(x, w, b)


def quantize_params(params, policy: QuantPolicy):
    """Rewrite a params tree for the policy: every 2-D ``*_kernel`` leaf
    becomes a :class:`QuantizedTensor` (mode = ``policy.weights``); everything
    else — embeddings, LayerNorm scales/biases, biases — is returned as-is
    (exact). ``weights="off"`` returns the tree untouched (the same object:
    not a copy, so the policy-off engine's params are bit-identical)."""
    if policy.weights == "off":
        return params
    mode = policy.weights
    rewritten = 0

    def walk(node):
        nonlocal rewritten
        if not isinstance(node, Mapping):
            return node
        out = {}
        for name, leaf in node.items():
            if isinstance(leaf, Mapping):
                out[name] = walk(leaf)
            elif name.endswith("_kernel") and getattr(leaf, "ndim", 0) == 2:
                out[name] = quantize_tensor(leaf, mode=mode)
                rewritten += 1
            else:
                out[name] = leaf
        return out

    quantized = walk(params)
    if rewritten == 0:
        # A weights-on policy that quantized nothing would silently serve fp32
        # kernels while every ledger reports the policy as on.
        raise ValueError("quantize_params found no 2-D *_kernel leaves to "
                         "quantize — unexpected params tree for policy "
                         f"weights={mode!r}")
    return quantized


# ---------------------------------------------------------------------------
# Byte-true accounting
# ---------------------------------------------------------------------------


def tree_bytes(tree) -> int:
    """Actual bytes of every array leaf in a pytree — ``size * itemsize`` of
    the REAL buffers (int8 planes count 1 byte/elem, their f32 scale planes
    count too), so downstream roofline math can never quietly assume a dtype
    the cache doesn't hold. QuantizedTensor leaves flatten to (q, scale) and
    are counted exactly."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dt = getattr(leaf, "dtype", None)
        if size is None or dt is None:
            continue
        total += int(size) * int(np.dtype(dt).itemsize)
    return total


def cache_layout(cache: dict) -> str:
    """Canonical signature of a KV cache's plane layout: leaf names, dtypes,
    and per-slot shapes of one layer (all layers are identical). This is the
    compatibility key the prefix cache stores with every snapshot — planes
    written under one layout must never install into an engine running
    another (an fp32 snapshot is garbage to an int8 engine's dequantizing
    attention kernel)."""
    if not cache:
        return "empty"
    layer = cache[sorted(cache)[0]]
    parts = []
    for name in sorted(layer):
        leaf = layer[name]
        shape = tuple(int(d) for d in leaf.shape[1:])   # drop the slot axis
        parts.append(f"{name}:{jnp.dtype(leaf.dtype).name}{list(shape)}")
    return f"layers={len(cache)};" + ",".join(parts)
