"""Pallas TPU kernels for the framework's fusible hot spots.

The reference executes its loss and optimizer as separate ATen CPU kernels chained by the
autograd engine (``F.log_softmax`` reference ``src/model.py:22`` → ``F.nll_loss``
``src/train.py:74`` → ``optimizer.step()`` ``src/train.py:76``). On TPU, XLA already fuses
most of this; these Pallas kernels make the two memory-bound fusions explicit, first-party
native code — the kernel-level counterpart of the reference's C++ compute substrate
(SURVEY.md §2b):

- ``nll_from_logits``: log-softmax + negative-log-likelihood in ONE VMEM pass over the
  logits (one read, no materialized ``[B, C]`` log-probability intermediate in HBM), with a
  custom VJP whose backward pass is a second single-pass kernel emitting
  ``(softmax - onehot) * upstream`` directly.
- ``sgd_momentum_step``: the fused SGD-with-momentum update ``v ← μv + g; p ← p − λv`` over a
  flattened parameter leaf — reads (p, v, g) once, writes (p, v) once; HBM-bandwidth optimal
  for the elementwise optimizer the reference applies per-tensor
  (``torch.optim.SGD``, reference ``src/train.py:60-61``).

Both kernels run compiled on TPU and in Pallas interpret mode elsewhere (CPU tests), chosen
automatically. Numerics match the ``ops.nn`` / ``ops.optim`` reference implementations to
float32 round-off (asserted by tests/test_pallas.py); the train step uses them when
``use_pallas_kernels`` is enabled in config.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128          # TPU lane width: last-dim tile granularity
BATCH_BLOCK = 256   # rows per grid step for the loss kernels
SGD_ROW_BLOCK = 1024  # rows per grid step for the optimizer kernel (5×512 KiB in VMEM)


def _interpret() -> bool:
    """Compiled on TPU; interpret mode on CPU/GPU (the test platforms)."""
    return jax.default_backend() != "tpu"


def _pad_to(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


# =========================================================================================
# Fused log-softmax + NLL loss
# =========================================================================================


def _nll_fwd_kernel(logits_ref, labels_ref, nll_ref):
    """One [bb, C] block: per-row -log_softmax(logits)[label].

    Padded class columns hold -1e30 → exp underflows to 0, so they contribute nothing to
    the log-sum-exp; padded batch rows produce garbage that the wrapper slices off.
    """
    x = logits_ref[:]                                       # [bb, C] f32
    lab = labels_ref[:]                                     # [bb, 1] i32
    m = jnp.max(x, axis=1, keepdims=True)
    s = x - m
    lse = jnp.log(jnp.sum(jnp.exp(s), axis=1, keepdims=True))
    classes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(classes == lab, s - lse, 0.0), axis=1, keepdims=True)
    nll_ref[:] = -picked                                    # [bb, 1]


def _nll_bwd_kernel(logits_ref, labels_ref, ct_ref, dlogits_ref):
    """One [bb, C] block of d/dlogits: (softmax(logits) - onehot(label)) * ct_row."""
    x = logits_ref[:]
    lab = labels_ref[:]
    ct = ct_ref[:]                                          # [bb, 1] f32
    m = jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x - m)
    softmax = e / jnp.sum(e, axis=1, keepdims=True)
    classes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = jnp.where(classes == lab, 1.0, 0.0)
    dlogits_ref[:] = (softmax - onehot) * ct


def _padded_call(kernel, extra_inputs, logits, labels, out_cols):
    """Pad [B, C] to tile-aligned shape, run `kernel` over a batch grid, unpad."""
    b, c = logits.shape
    bp, cp = _pad_to(b, BATCH_BLOCK), _pad_to(c, LANE)
    logits_p = jnp.full((bp, cp), -1e30, jnp.float32).at[:b, :c].set(
        logits.astype(jnp.float32))
    labels_p = jnp.zeros((bp, 1), jnp.int32).at[:b, 0].set(labels.astype(jnp.int32))
    extras_p = [jnp.zeros((bp, 1), jnp.float32).at[:b, :].set(e) for e in extra_inputs]

    grid = (bp // BATCH_BLOCK,)
    row_block = lambda width: pl.BlockSpec((BATCH_BLOCK, width), lambda i: (i, 0),
                                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[row_block(cp), row_block(1)] + [row_block(1)] * len(extras_p),
        out_specs=row_block(out_cols),
        out_shape=jax.ShapeDtypeStruct((bp, out_cols), jnp.float32),
        interpret=_interpret(),
    )(logits_p, labels_p, *extras_p)
    return out[:b]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def nll_from_logits(logits: jax.Array, labels: jax.Array,
                    reduction: str = "mean") -> jax.Array:
    """Fused ``nll_loss(log_softmax(logits), labels)`` as one Pallas kernel pass.

    Drop-in for the composition of ``ops.log_softmax`` + ``ops.nll_loss`` (the reference's
    two objectives — ``src/train.py:74`` and, by log-softmax idempotence, the distributed
    CrossEntropyLoss path ``src/train_dist.py:67`` — see ``ops.cross_entropy_loss``).
    Differentiable via a custom VJP with a fused backward kernel.
    """
    return _nll_reduce(_padded_call(_nll_fwd_kernel, [], logits, labels, 1)[:, 0],
                       reduction)


def _nll_reduce(per_example: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return jnp.mean(per_example)
    if reduction == "sum":
        return jnp.sum(per_example)
    if reduction == "none":
        return per_example
    raise ValueError(f"unknown reduction {reduction!r}")


def _nll_fwd(logits, labels, reduction):
    per_example = _padded_call(_nll_fwd_kernel, [], logits, labels, 1)[:, 0]
    return _nll_reduce(per_example, reduction), (logits, labels)


def _nll_bwd(reduction, residuals, ct):
    logits, labels = residuals
    b = logits.shape[0]
    if reduction == "mean":
        ct_rows = jnp.full((b, 1), 1.0 / b, jnp.float32) * ct
    elif reduction == "sum":
        ct_rows = jnp.full((b, 1), 1.0, jnp.float32) * ct
    else:  # none: ct is per-example
        ct_rows = ct.astype(jnp.float32)[:, None]
    dlogits = _padded_call(_nll_bwd_kernel, [ct_rows], logits, labels,
                           _pad_to(logits.shape[1], LANE))[:, :logits.shape[1]]
    return dlogits.astype(logits.dtype), None


nll_from_logits.defvjp(_nll_fwd, _nll_bwd)


# =========================================================================================
# Fused SGD-momentum update
# =========================================================================================


def _sgd_kernel(momentum: float, learning_rate: float, p_ref, v_ref, g_ref,
                new_p_ref, new_v_ref):
    v = momentum * v_ref[:] + g_ref[:]
    new_v_ref[:] = v
    new_p_ref[:] = p_ref[:] - learning_rate * v


def _sgd_leaf(p: jax.Array, v: jax.Array, g: jax.Array, *, learning_rate: float,
              momentum: float) -> tuple[jax.Array, jax.Array]:
    """Fused update for one parameter leaf: flatten → [rows, LANE] tiles → kernel → unflatten.

    Gridded over SGD_ROW_BLOCK-row blocks so VMEM residency stays bounded (5 buffers ×
    block × LANE × 4 B ≈ 2.5 MiB) regardless of leaf size — an ungridded call would place
    the whole padded leaf in VMEM and fail to compile for multi-million-param leaves.
    """
    shape, dtype, n = p.shape, p.dtype, p.size
    rows8 = _pad_to(max(n, 1), LANE * 8) // LANE     # sublane-aligned row count
    block = min(rows8, SGD_ROW_BLOCK)
    rows = _pad_to(rows8, block)                     # whole number of grid blocks

    def tile(a):
        flat = jnp.zeros(rows * LANE, jnp.float32).at[:n].set(
            a.astype(jnp.float32).reshape(-1))
        return flat.reshape(rows, LANE)

    kernel = functools.partial(_sgd_kernel, momentum, learning_rate)
    row_block = pl.BlockSpec((block, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)
    new_p, new_v = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[row_block, row_block, row_block],
        out_specs=[row_block, row_block],
        out_shape=[jax.ShapeDtypeStruct((rows, LANE), jnp.float32)] * 2,
        interpret=_interpret(),
    )(tile(p), tile(v), tile(g))
    unflatten = lambda a: a.reshape(-1)[:n].reshape(shape).astype(dtype)
    return unflatten(new_p), unflatten(new_v)


def sgd_momentum_step(params, velocity, grads, *, learning_rate: float, momentum: float):
    """Pytree-wide fused SGD-momentum step — the Pallas counterpart of
    ``ops.optim.sgd_update`` (torch-SGD semantics, reference ``src/train.py:60-61``).

    Returns ``(new_params, new_velocity)``.
    """
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_v = treedef.flatten_up_to(velocity)
    flat_g = treedef.flatten_up_to(grads)
    out = [_sgd_leaf(p, v, g, learning_rate=learning_rate, momentum=momentum)
           for p, v, g in zip(flat_p, flat_v, flat_g)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return new_p, new_v
