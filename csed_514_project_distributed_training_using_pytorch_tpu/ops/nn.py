"""Core functional NN ops, NHWC/TPU-first.

TPU-native analog of the ATen kernels invoked by the reference model's forward
(reference ``src/model.py:15-22``): conv2d, max-pool, dense, dropout (elementwise and
channelwise), log_softmax, and the two loss formulations the reference uses
(``F.nll_loss`` at ``src/train.py:74,94`` and ``nn.CrossEntropyLoss`` at
``src/train_dist.py:67``).

Layout note: everything here is NHWC (``[batch, height, width, channels]``) with HWIO conv
kernels — the layout XLA:TPU tiles best onto the MXU — rather than the reference's NCHW.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv2d(x: jax.Array, w: jax.Array, b: jax.Array | None = None, *,
           stride: int = 1, padding: str = "VALID") -> jax.Array:
    """2-D convolution, NHWC x HWIO -> NHWC.

    Equivalent of ``nn.Conv2d`` with default stride/no padding as used at reference
    ``src/model.py:9-10`` (kernel 5, valid padding). Runs on the MXU.
    """
    out = lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if b is not None:
        out = out + b
    return out


def max_pool2d(x: jax.Array, window: int = 2, stride: int | None = None) -> jax.Array:
    """Max pooling over spatial dims of an NHWC tensor.

    Equivalent of ``F.max_pool2d(x, 2)`` at reference ``src/model.py:16-17``.
    """
    if stride is None:
        stride = window
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def dense(x: jax.Array, w: jax.Array, b: jax.Array | None = None) -> jax.Array:
    """Affine layer ``x @ w + b`` with ``w: [in, out]``.

    Equivalent of ``nn.Linear`` at reference ``src/model.py:12-13``. Batched matmul on the MXU;
    accumulation is requested in float32 regardless of input dtype so bfloat16 activations
    keep full-precision sums.
    """
    out = jnp.matmul(x, w, preferred_element_type=jnp.float32)
    out = out.astype(x.dtype)
    if b is not None:
        out = out + b
    return out


def relu(x: jax.Array) -> jax.Array:
    """Rectified linear unit (``F.relu``, reference ``src/model.py:16-19``)."""
    return jnp.maximum(x, 0)


def log_softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    """Numerically-stable log-softmax (``F.log_softmax(x)``, reference ``src/model.py:22``)."""
    shifted = x - lax.stop_gradient(jnp.max(x, axis=axis, keepdims=True))
    return shifted - jnp.log(jnp.sum(jnp.exp(shifted), axis=axis, keepdims=True))


def nll_loss(log_probs: jax.Array, labels: jax.Array, *, reduction: str = "mean",
             label_smoothing: float = 0.0) -> jax.Array:
    """Negative log-likelihood of integer labels under ``log_probs``.

    Equivalent of ``F.nll_loss`` (reference ``src/train.py:74``) and of its deprecated
    ``size_average=False`` sum-reduction form (reference ``src/train.py:94``) via
    ``reduction="sum"``.

    ``label_smoothing=s`` trains against the smoothed target distribution
    ``(1−s)·onehot + s/C`` — torch ``CrossEntropyLoss(label_smoothing=s)`` semantics
    (pinned against real torch in ``tests/test_ops.py``); per-example loss becomes
    ``(1−s)·nll + s·mean_c(−log_probs)``.
    """
    picked = jnp.take_along_axis(log_probs, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    if label_smoothing:
        smooth = jnp.mean(log_probs, axis=-1)
        picked = (1.0 - label_smoothing) * picked + label_smoothing * smooth
    if reduction == "mean":
        return -jnp.mean(picked)
    if reduction == "sum":
        return -jnp.sum(picked)
    if reduction == "none":
        return -picked
    raise ValueError(f"unknown reduction {reduction!r}")


def cross_entropy_loss(logits: jax.Array, labels: jax.Array, *, reduction: str = "mean") -> jax.Array:
    """Softmax cross-entropy from unnormalized (or, as in the reference's distributed path,
    already-log-softmaxed) inputs.

    Equivalent of ``nn.CrossEntropyLoss`` (reference ``src/train_dist.py:67``). Note the
    reference feeds it the output of a model that already ends in log_softmax
    (``src/model.py:22``) — an effective double log-softmax (SURVEY.md §2d.1). Since
    log_softmax is idempotent, that composition is *mathematically identical* to the
    single-process ``log_softmax + nll`` objective (verified in tests/test_ops.py), so this
    framework uses the one canonical ``nll_loss(model(x))`` formulation everywhere; this
    function is provided for API parity and for users porting loss code.
    """
    return nll_loss(log_softmax(logits), labels, reduction=reduction)


def layer_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               *, eps: float = 1e-5) -> jax.Array:
    """Layer normalization over the last axis with learned scale/shift.

    Not used by the reference's CNN (it has no normalization layers) — this is part of the
    beyond-parity attention model family (``models/transformer.py``). Statistics are computed
    in float32 so bfloat16 activations normalize accurately, then cast back.
    """
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    normed = (xf - mean) * lax.rsqrt(var + eps)
    return (normed * gamma.astype(jnp.float32)
            + beta.astype(jnp.float32)).astype(x.dtype)


def gelu(x: jax.Array) -> jax.Array:
    """Gaussian-error linear unit (tanh approximation — the transformer-standard
    nonlinearity; XLA fuses it into the surrounding matmuls)."""
    return jax.nn.gelu(x, approximate=True)


def dropout(rng: jax.Array, x: jax.Array, rate: float, *, deterministic: bool) -> jax.Array:
    """Elementwise inverted dropout (``F.dropout``, reference ``src/model.py:20``).

    ``deterministic=True`` (eval mode) is the identity, mirroring ``model.eval()`` semantics
    at reference ``src/train.py:91`` / ``src/train_dist.py:93``.
    """
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def dropout2d(rng: jax.Array, x: jax.Array, rate: float, *, deterministic: bool) -> jax.Array:
    """Channelwise (spatial) dropout on NHWC: zeroes whole feature maps.

    Equivalent of ``nn.Dropout2d`` (reference ``src/model.py:11,17``), which drops entire
    channels rather than independent elements.
    """
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask_shape = (x.shape[0], 1, 1, x.shape[-1])
    mask = jax.random.bernoulli(rng, keep, mask_shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
