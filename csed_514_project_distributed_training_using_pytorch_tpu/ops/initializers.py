"""Parameter initializers matching PyTorch layer defaults.

The reference never sets initializers explicitly, so its training dynamics (loss starting at
~2.30 and the SGD lr=0.01/0.02 momentum=0.5 schedule converging, BASELINE.md) are those of
PyTorch's defaults for ``nn.Conv2d``/``nn.Linear``: ``kaiming_uniform_(a=sqrt(5))`` for weights
— which reduces to U(-1/sqrt(fan_in), 1/sqrt(fan_in)) — and the same fan-in-uniform bound for
biases. We reproduce those distributions here (with JAX PRNG keys) so convergence behavior is
comparable; any ``jax.nn.initializers`` callable can be swapped in.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _fan_in(shape: tuple[int, ...]) -> int:
    """Fan-in for HWIO conv kernels (h*w*in) and [in, out] dense kernels."""
    if len(shape) == 2:  # dense [in, out]
        return shape[0]
    if len(shape) == 4:  # conv HWIO
        return shape[0] * shape[1] * shape[2]
    raise ValueError(f"unsupported param shape {shape}")


def torch_kaiming_uniform(key: jax.Array, shape: tuple[int, ...],
                          dtype: jnp.dtype = jnp.float32) -> jax.Array:
    """PyTorch default weight init: ``kaiming_uniform_(a=sqrt(5))`` == U(±1/sqrt(fan_in))."""
    bound = 1.0 / jnp.sqrt(_fan_in(shape))
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def torch_fan_in_uniform(fan_in: int):
    """PyTorch default bias init: U(±1/sqrt(fan_in)) with fan-in taken from the weight."""
    def init(key: jax.Array, shape: tuple[int, ...], dtype: jnp.dtype = jnp.float32) -> jax.Array:
        bound = 1.0 / jnp.sqrt(jnp.asarray(fan_in, dtype=jnp.float32))
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)
    return init
