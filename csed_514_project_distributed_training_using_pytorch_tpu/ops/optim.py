"""SGD with momentum — the reference's only optimizer, as a pure pytree transform.

Reproduces ``torch.optim.SGD(lr, momentum)`` semantics exactly (reference
``src/train.py:60-61`` lr=0.01 mom=0.5; ``src/train_dist.py:66`` lr=0.02 mom=0.5), i.e. the
torch update with no dampening/nesterov/weight-decay:

    v <- momentum * v + g
    p <- p - lr * v

(Torch initializes the buffer to the first gradient; starting from v=0 gives the identical
sequence since ``momentum*0 + g == g``.) Implemented first-party rather than via optax to keep
the update rule explicit and dependency-free; it is a drop-in ``(init_fn, update_fn)`` pair in
the optax style, so an optax ``GradientTransformation`` can be substituted where desired.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sgd_init(params):
    """Zero velocity buffers, one per parameter leaf (the torch momentum_buffer analog)."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_update(params, velocity, grads, *, learning_rate: float, momentum: float):
    """One SGD-momentum step; returns (new_params, new_velocity)."""
    new_velocity = jax.tree_util.tree_map(
        lambda v, g: momentum * v + g, velocity, grads)
    new_params = jax.tree_util.tree_map(
        lambda p, v: p - learning_rate * v, params, new_velocity)
    return new_params, new_velocity
