"""Optimizers as pure pytree transforms: SGD-momentum (the parity surface) + AdamW.

SGD reproduces ``torch.optim.SGD(lr, momentum)`` semantics exactly (reference
``src/train.py:60-61`` lr=0.01 mom=0.5; ``src/train_dist.py:66`` lr=0.02 mom=0.5), i.e. the
torch update with no dampening/nesterov/weight-decay:

    v <- momentum * v + g
    p <- p - lr * v

(Torch initializes the buffer to the first gradient; starting from v=0 gives the identical
sequence since ``momentum*0 + g == g``.) Implemented first-party rather than via optax to keep
the update rule explicit and dependency-free.

AdamW (beyond-parity — the reference's only optimizer is SGD) reproduces
``torch.optim.AdamW`` semantics (decoupled weight decay, bias correction) and is pinned
against real torch in ``tests/test_optim.py``:

    t <- t + 1
    m <- b1*m + (1-b1)*g          v <- b2*v + (1-b2)*g²
    p <- p - lr*(m/(1-b1^t) / (sqrt(v/(1-b2^t)) + eps) + weight_decay*p)

State-shape contract (what keeps every sharding/checkpoint path working unchanged):
``TrainState.velocity`` holds the optimizer state. For SGD it is a params-congruent
velocity tree (the historical layout — old checkpoints restore as-is). For AdamW it is
``{"m": <params tree>, "v": <params tree>, "count": int32 scalar}`` — each moment subtree
is params-congruent, so the path/shape-driven partition-spec rules (``tensor_parallel``,
``fsdp``) derive the SAME shardings for the moments as for their parameters (ZeRO-style)
without pairing against the params tree; only code that restructures the state wholesale
(the pipeline stack/unstack bridge) needs ``map_param_trees`` below.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    """``(init, update, name, hyperparams)``: ``init(params) -> opt_state``;
    ``update(params, opt_state, grads) -> (new_params, new_opt_state)``.
    ``hyperparams`` records the constructor knobs — consumers that re-implement the
    update (the fused Pallas SGD kernel path) read them from here so they can never
    diverge from what the ``update`` closure applies."""

    init: Callable
    update: Callable
    name: str
    hyperparams: dict


def sgd_init(params):
    """Zero velocity buffers, one per parameter leaf (the torch momentum_buffer analog)."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_update(params, velocity, grads, *, learning_rate: float, momentum: float):
    """One SGD-momentum step; returns (new_params, new_velocity)."""
    new_velocity = jax.tree_util.tree_map(
        lambda v, g: momentum * v + g, velocity, grads)
    new_params = jax.tree_util.tree_map(
        lambda p, v: p - learning_rate * v, params, new_velocity)
    return new_params, new_velocity


def sgd(learning_rate: float, momentum: float) -> Optimizer:
    """The reference's optimizer as an ``Optimizer`` pair (state = velocity tree).

    ``update(..., lr_scale=s)`` applies a step-dependent multiplier to the learning
    rate only (torch ``lr_scheduler`` semantics: the velocity accumulates RAW
    gradients; the rate applies at the parameter write)."""

    def update(params, velocity, grads, *, lr_scale=1.0):
        return sgd_update(params, velocity, grads,
                          learning_rate=learning_rate * lr_scale,
                          momentum=momentum)

    return Optimizer(init=sgd_init, update=update, name="sgd",
                     hyperparams={"learning_rate": learning_rate,
                                  "momentum": momentum})


def adamw_init(params):
    """Zero first/second moments + step count (torch ``state['step']`` analog)."""
    zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
    return {"m": zeros(), "v": zeros(), "count": jnp.zeros((), jnp.int32)}


def adamw(learning_rate: float, *, b1: float = 0.9, b2: float = 0.999,
          eps: float = 1e-8, weight_decay: float = 0.0) -> Optimizer:
    """AdamW with torch semantics (decoupled decay; bias-corrected moments)."""

    def update(params, opt_state, grads, *, lr_scale=1.0):
        count = opt_state["count"] + 1
        c = count.astype(jnp.float32)
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1.0 - b1) * g,
                                   opt_state["m"], grads)
        v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1.0 - b2) * g * g,
                                   opt_state["v"], grads)
        bc1 = 1.0 - jnp.power(b1, c)
        bc2 = 1.0 - jnp.power(b2, c)

        def leaf(p, m_, v_):
            step_dir = (m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps)
            # lr_scale multiplies the whole scheduled rate — including the decoupled
            # decay term, matching torch AdamW under an lr_scheduler (decay is
            # p -= lr_t * weight_decay * p there too).
            return p - learning_rate * lr_scale * (step_dir + weight_decay * p)

        new_params = jax.tree_util.tree_map(leaf, params, m, v)
        return new_params, {"m": m, "v": v, "count": count}

    return Optimizer(init=adamw_init, update=update, name="adamw",
                     hyperparams={"learning_rate": learning_rate, "b1": b1,
                                  "b2": b2, "eps": eps,
                                  "weight_decay": weight_decay})


def make_optimizer(name: str, *, learning_rate: float, momentum: float,
                   weight_decay: float = 0.0) -> Optimizer:
    """CLI-name → ``Optimizer`` (the trainers' ``--optimizer`` surface)."""
    if name == "sgd":
        if weight_decay:
            raise ValueError("--weight-decay is an AdamW knob — the reference-parity "
                             "SGD has none (reference src/train.py:60-61)")
        return sgd(learning_rate, momentum)
    if name == "adamw":
        return adamw(learning_rate, weight_decay=weight_decay)
    raise ValueError(f"unknown optimizer {name!r} — choose 'sgd' or 'adamw'")


def global_l2_norm(tree) -> jax.Array:
    """Global L2 norm of a pytree in f32 (torch ``clip_grad_norm_``'s norm). The ONE
    owner of the formula — the clip below, the health-stats grad norm
    (``train/step.py``), and the telemetry param norm (``utils/telemetry.py``) all
    reduce through it, so they can never drift apart."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def clip_by_global_norm(grads, max_norm: float, *, eps: float = 1e-6):
    """Global-norm gradient clipping with ``torch.nn.utils.clip_grad_norm_``'s exact
    semantics (including its ``eps`` in the denominator): returns
    ``(clipped_grads, global_norm)``. Grads are scaled by
    ``min(1, max_norm / (norm + eps))`` — a no-op whenever the norm is within bounds.
    Pinned against real torch in ``tests/test_optim.py``."""
    gnorm = global_l2_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (gnorm + eps))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), gnorm


def make_lr_schedule(name: str, *, warmup_steps: int = 0,
                     total_steps: int = 0) -> Callable | None:
    """Step → learning-rate multiplier in (0, 1], traced inside the compiled step.

    - ``"constant"``: 1.0, with an optional linear warmup ramp over the first
      ``warmup_steps`` updates (scale ``(step+1)/warmup_steps``, so step 0 trains at
      ``1/warmup_steps`` rather than 0 — torch LambdaLR convention for a ramp that
      never multiplies by zero).
    - ``"cosine"``: the warmup ramp, then cosine decay from 1 → 0 across the
      remaining ``total_steps - warmup_steps`` updates (the standard half-period
      schedule); requires ``total_steps > warmup_steps``.

    Returns ``None`` for a warmup-free constant schedule so callers can skip the
    multiply entirely (the hot-loop fast path stays untouched).
    """
    if warmup_steps < 0:
        raise ValueError(f"warmup_steps must be >= 0, got {warmup_steps}")

    def ramp(step):
        s = step.astype(jnp.float32)
        return jnp.minimum(1.0, (s + 1.0) / warmup_steps)

    if name == "constant":
        return ramp if warmup_steps > 0 else None
    if name == "cosine":
        if total_steps <= warmup_steps:
            raise ValueError(
                f"cosine schedule needs total_steps > warmup_steps, got "
                f"{total_steps} <= {warmup_steps}")

        def sched(step):
            s = step.astype(jnp.float32)
            t = jnp.clip((s - warmup_steps) / (total_steps - warmup_steps), 0.0, 1.0)
            cos = 0.5 * (1.0 + jnp.cos(jnp.pi * t))
            return (ramp(step) if warmup_steps > 0 else 1.0) * cos

        return sched
    raise ValueError(f"unknown lr schedule {name!r} — choose 'constant' or 'cosine'")


def is_adam_state(opt_state) -> bool:
    """True for the AdamW moment-state layout (see the module docstring contract)."""
    return isinstance(opt_state, dict) and set(opt_state) == {"m", "v", "count"}


def map_param_trees(opt_state, fn: Callable, scalar_fn: Callable | None = None):
    """Apply ``fn`` to every params-congruent subtree of an optimizer state.

    SGD state IS one params-congruent tree → ``fn(state)``. AdamW state maps ``fn``
    over the two moment trees and ``scalar_fn`` (default: identity) over the count —
    the single seam that lets structure-rewriting code (the pipeline stack/unstack
    bridge, the stacked-layout shardings) stay optimizer-agnostic.
    """
    if is_adam_state(opt_state):
        keep = scalar_fn if scalar_fn is not None else (lambda x: x)
        return {"m": fn(opt_state["m"]), "v": fn(opt_state["v"]),
                "count": keep(opt_state["count"])}
    return fn(opt_state)
