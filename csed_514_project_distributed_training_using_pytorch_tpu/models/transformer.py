"""Transformer model family (the framework's long-context/attention surface).

The reference has exactly one model — the 28×28 MNIST CNN (reference ``src/model.py:4-22``)
— and no attention op anywhere, so sequence parallelism is "structurally inapplicable" for
parity (SURVEY.md §2c). This module is the beyond-parity model family that makes the
framework's sequence-parallel machinery (``parallel/ring_attention.py``) a first-class,
exercised capability rather than dead plumbing:

- ``TransformerClassifier`` treats an image as a **sequence of flat pixel-chunk tokens**
  (``seq_len`` tokens of ``784 // seq_len`` consecutive pixels in raster order) and
  classifies it with a pre-LN transformer encoder. It accepts the same ``[B, 28, 28, 1]``
  input and exposes the same ``(x, *, deterministic)`` call signature as ``models.cnn.Net``,
  so it is **drop-in** for every existing trainer, checkpointer, and eval path
  (``train/step.py`` treats the model as an opaque apply + params pytree).
- The attention implementation is **pluggable** (``attention_fn``): the default is the
  dense single-device ``ops.full_attention``; passing
  ``parallel.make_ring_attention_fn(mesh)`` runs the identical model with its sequence
  axis sharded across the mesh — numerics pinned equal in ``tests/test_transformer.py``.

TPU-first choices: all matmuls are MXU-shaped einsums/denses; softmax/LayerNorm statistics
run in float32 while activations may be bfloat16 (``dtype`` field); dropout uses the same
explicit ``'dropout'`` PRNG collection as the CNN so the trainers' key threading works
unchanged; the whole forward is pure and traced once per ``deterministic`` variant.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as fnn
import jax
import jax.numpy as jnp

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.ops import rotary
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    expert_parallel as ep,  # submodule has no deps back into models/ (no cycle)
)


# Stock flax initializers (transformer-standard trunc-free normal(0.02) embeddings/
# projections, zero biases, unit LN scales) — the torch-parity initializers in
# ops/initializers.py are CNN-specific and stay there.
_normal_init = fnn.initializers.normal
_zeros_init = fnn.initializers.zeros_init()
_ones_init = fnn.initializers.ones_init()


def tokenize_images(x: jax.Array, seq_len: int) -> jax.Array:
    """``[B, H, W, C]`` images → ``[B, seq_len, feat]`` pixel-chunk tokens.

    Zero-pads the flat pixel stream up to ``seq_len·ceil(total/seq_len)`` so ANY
    seq_len tokenizes (e.g. the flash kernels' 128-aligned lengths on 784-pixel
    MNIST). Padding lands in the last tokens' trailing FEATURES — the sequence length
    is exactly ``seq_len`` either way, so attention structure is unchanged. Shared by
    ``TransformerClassifier`` and the pipelined stage engine
    (``parallel.pipeline.PipelinedClassifier``), which must tokenize identically."""
    b = x.shape[0]
    total = x.shape[1] * x.shape[2] * x.shape[3]
    feat = -(-total // seq_len)          # ceil: features per token
    if total % seq_len:
        x = jnp.pad(x.reshape(b, total), ((0, 0), (0, seq_len * feat - total)))
    return x.reshape(b, seq_len, feat)


class MultiHeadSelfAttention(fnn.Module):
    """Multi-head self-attention with a pluggable core.

    ``attention_fn(q, k, v, *, causal) -> out`` operates on ``[B, S, H, D]``; the module
    owns only the projections, so swapping the dense core for the sequence-parallel ring
    core changes no parameters — the two variants share checkpoints bit-for-bit.

    ``num_kv_heads < num_heads`` is grouped-query attention (GQA; ``== 1`` is MQA):
    K/V project to only that many heads — a ``num_heads/num_kv_heads``× smaller KV
    projection and, in the LM decode path, an equally smaller KV cache — and each K/V
    head serves a contiguous group of query heads (broadcast before the core, so EVERY
    pluggable core works unchanged). ``None`` keeps standard MHA with the historical
    fused ``qkv_kernel`` parameter layout (old checkpoints restore as-is); GQA uses
    split ``q_kernel``/``kv_kernel`` parameters.
    """

    num_heads: int
    num_kv_heads: int | None = None
    attention_fn: Callable = ops.full_attention
    causal: bool = False
    rope: bool = False          # rotary position embeddings on q/k (applied before
                                # the core, so every pluggable core composes)
    dtype: jnp.dtype = jnp.float32

    @fnn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, e = x.shape
        if e % self.num_heads:
            raise ValueError(f"embed dim {e} not divisible by {self.num_heads} heads")
        head_dim = e // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        if kv_heads < 1 or self.num_heads % kv_heads:
            raise ValueError(f"num_heads {self.num_heads} not divisible by "
                             f"num_kv_heads {kv_heads} (need a positive divisor)")

        if kv_heads == self.num_heads:
            wqkv = self.param("qkv_kernel", _normal_init(0.02), (e, 3 * e))
            bqkv = self.param("qkv_bias", _zeros_init, (3 * e,))
            qkv = ops.dense(x, wqkv.astype(self.dtype), bqkv.astype(self.dtype))
            qkv = qkv.reshape(b, s, 3, self.num_heads, head_dim)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            wq = self.param("q_kernel", _normal_init(0.02), (e, e))
            bq = self.param("q_bias", _zeros_init, (e,))
            wkv = self.param("kv_kernel", _normal_init(0.02),
                             (e, 2 * kv_heads * head_dim))
            bkv = self.param("kv_bias", _zeros_init, (2 * kv_heads * head_dim,))
            q = ops.dense(x, wq.astype(self.dtype),
                          bq.astype(self.dtype)).reshape(b, s, self.num_heads,
                                                         head_dim)
            kv = ops.dense(x, wkv.astype(self.dtype), bkv.astype(self.dtype))
            kv = kv.reshape(b, s, 2, kv_heads, head_dim)
            k, v = kv[:, :, 0], kv[:, :, 1]

        if self.rope:
            # Rotate BEFORE the GQA broadcast (rotation is head-independent): the
            # narrow kv_heads-wide K costs rep× less VPU work — same order the
            # decode path uses.
            positions = jnp.arange(s)
            q = rotary.apply_rotary(q, positions)
            k = rotary.apply_rotary(k, positions)
        if kv_heads != self.num_heads:
            # Broadcast each K/V head over its query-head group so any pluggable
            # core (dense/flash/ring/ulysses) sees matched head counts.
            rep = self.num_heads // kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        out = self.attention_fn(q, k, v, causal=self.causal)
        out = out.reshape(b, s, e)

        wo = self.param("out_kernel", _normal_init(0.02), (e, e))
        bo = self.param("out_bias", _zeros_init, (e,))
        return ops.dense(out, wo.astype(self.dtype), bo.astype(self.dtype))



def remat_policy_fn(name: str):
    """Map a ``--remat-policy`` name to a ``jax.checkpoint`` policy.

    ``"recompute-all"`` (the default) saves nothing — maximum memory savings,
    ~1/3 extra FLOPs. ``"save-dots"`` (``jax.checkpoint_policies.dots_saveable``)
    keeps matmul outputs and recomputes only the cheap elementwise work between
    them — the TPU-recommended middle ground: the MXU results that are expensive
    to recompute stay resident, the VPU work replays. Policies change ONLY what
    is saved; the trajectory is bit-identical (pinned in tests)."""
    if name in ("", "recompute-all"):
        return None
    if name == "save-dots":
        return jax.checkpoint_policies.dots_saveable
    raise ValueError(f"unknown remat policy {name!r} — choose "
                     f"'recompute-all' or 'save-dots'")


def validate_remat_policy(remat: bool, remat_policy: str) -> None:
    """Shared fail-fast for every ``--remat-policy`` surface: the policy modifies
    ``--remat`` (alone it does nothing), and the name must be known."""
    if remat_policy:
        if not remat:
            raise ValueError("--remat-policy modifies --remat; add --remat")
        remat_policy_fn(remat_policy)   # raises on unknown names

class TransformerBlock(fnn.Module):
    """Pre-LN encoder block: ``x + MHA(LN(x))`` then ``x + FFN(LN(x))``.

    ``num_experts > 0`` replaces the dense MLP with the Switch-style top-1 MoE
    feed-forward (``parallel/expert_parallel.py``): per-token routed experts on the
    residual path (a dropped over-capacity token degrades to identity). The router's
    load-balance auxiliary loss is ``sow``n into the ``"aux_loss"`` collection;
    ``train.step.make_train_step`` collects it automatically (``aux_loss_weight``), and
    direct callers can pull it with ``model.apply(..., mutable=["aux_loss"])``.

    Capacity note (standard Switch semantics): the expert capacity budget is computed
    over the whole ``B·S`` token batch, so which over-capacity tokens drop depends on
    batch composition — an example's output can differ slightly between batch sizes.
    Parameter names match ``expert_parallel``'s layout (``router_kernel``/``up_kernel``/
    ``up_bias``/``down_kernel``/``down_bias``), so its partition specs apply per block.
    """

    num_heads: int
    num_kv_heads: int | None = None
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    attention_fn: Callable = ops.full_attention
    causal: bool = False
    rope: bool = False
    dtype: jnp.dtype = jnp.float32
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    expert_top_k: int = 1               # 1 = Switch top-1 routing; 2 = GShard top-2
                                        # (renormalized pair gates)
    expert_mesh: object = None          # optional Mesh: pin dispatched tokens onto its
                                        # 'expert' axis (EP execution; numerics identical)

    @fnn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        # `deterministic` is positional-or-keyword (not keyword-only) so fnn.remat can
        # mark it static by argnum when the classifier enables rematerialization.
        e = x.shape[-1]

        g1 = self.param("ln1_scale", _ones_init, (e,))
        b1 = self.param("ln1_bias", _zeros_init, (e,))
        h = ops.layer_norm(x, g1, b1)
        h = MultiHeadSelfAttention(
            num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
            attention_fn=self.attention_fn,
            causal=self.causal, rope=self.rope, dtype=self.dtype, name="attn")(h)
        if not deterministic:
            h = ops.dropout(self.make_rng("dropout"), h, self.dropout_rate,
                            deterministic=False)
        x = x + h

        g2 = self.param("ln2_scale", _ones_init, (e,))
        b2 = self.param("ln2_bias", _zeros_init, (e,))
        h = ops.layer_norm(x, g2, b2)
        hidden = self.mlp_ratio * e
        if self.num_experts > 0:
            moe_params = {
                "router_kernel": self.param("router_kernel", _normal_init(0.02),
                                            (e, self.num_experts)),
                "up_kernel": self.param("up_kernel", _normal_init(0.02),
                                        (self.num_experts, e, hidden)),
                "up_bias": self.param("up_bias", _zeros_init,
                                      (self.num_experts, hidden)),
                "down_kernel": self.param("down_kernel", _normal_init(0.02),
                                          (self.num_experts, hidden, e)),
                "down_bias": self.param("down_bias", _zeros_init,
                                        (self.num_experts, e)),
            }
            # Activations may be bfloat16 (master weights stay f32, same as the dense
            # branch); moe_apply keeps router softmax statistics in f32 internally.
            moe_params = {k: v.astype(self.dtype) for k, v in moe_params.items()}
            b, s, _ = h.shape
            tokens = h.astype(self.dtype).reshape(b * s, e)
            routed, aux = ep.moe_apply(
                moe_params, tokens, capacity_factor=self.expert_capacity_factor,
                num_selected=self.expert_top_k, mesh=self.expert_mesh)
            self.sow("aux_loss", "load_balance", aux)
            h = routed.reshape(b, s, e)
        else:
            w_up = self.param("mlp_up_kernel", _normal_init(0.02), (e, hidden))
            b_up = self.param("mlp_up_bias", _zeros_init, (hidden,))
            h = ops.gelu(ops.dense(h, w_up.astype(self.dtype),
                                   b_up.astype(self.dtype)))
            w_dn = self.param("mlp_down_kernel", _normal_init(0.02), (hidden, e))
            b_dn = self.param("mlp_down_bias", _zeros_init, (e,))
            h = ops.dense(h, w_dn.astype(self.dtype), b_dn.astype(self.dtype))
        if not deterministic:
            h = ops.dropout(self.make_rng("dropout"), h, self.dropout_rate,
                            deterministic=False)
        return x + h


class TransformerClassifier(fnn.Module):
    """Image classifier over a pixel-token sequence, emitting log-probabilities.

    Accepts ``[B, 28, 28, 1]`` images (tokenized internally to ``seq_len`` tokens of
    ``784 // seq_len`` features) or an already-tokenized ``[B, S, F]`` batch. The output
    contract matches ``models.cnn.Net`` (``[B, num_classes]`` log-probs), so trainers,
    eval, metrics, and checkpointing work unchanged.
    """

    num_classes: int = 10
    seq_len: int = 16           # 784 = 16 tokens × 49 features; divisible by an 8-way mesh
    embed_dim: int = 64
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int | None = None  # < num_heads = grouped-query attention (GQA)
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    attention_fn: Callable = ops.full_attention
    causal: bool = False
    rope: bool = False               # rotary q/k rotation in every block (the learned
                                     # additive pos_embed remains — harmless, and the
                                     # parameter layout stays checkpoint-stable)
    dtype: jnp.dtype = jnp.float32
    remat: bool = False         # rematerialize each block on backward (jax.checkpoint):
                                # activation memory drops from O(layers) to O(1) blocks at
                                # ~1/3 extra FLOPs — the long-context memory knob the
                                # brief's HBM math calls for; numerics unchanged
                                # (pinned in tests/test_transformer.py)
    remat_policy: str = ""      # what remat SAVES: '' / 'recompute-all' (nothing)
                                # or 'save-dots' (keep matmul outputs, replay the
                                # elementwise work) — see remat_policy_fn
    num_experts: int = 0        # >0: every block's MLP becomes a routed MoE with
                                # this many experts (see TransformerBlock docstring for
                                # the sown load-balance aux loss)
    expert_capacity_factor: float = 1.25
    expert_top_k: int = 1       # 1 = Switch; 2 = GShard top-2
    expert_mesh: object = None  # optional Mesh with an 'expert' axis → EP execution

    @fnn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        if x.ndim == 4:
            x = tokenize_images(x, self.seq_len)
        b, s, f = x.shape
        if s != self.seq_len:
            raise ValueError(f"expected seq_len {self.seq_len}, got {s}")
        x = x.astype(self.dtype)

        w_embed = self.param("embed_kernel", _normal_init(0.02), (f, self.embed_dim))
        b_embed = self.param("embed_bias", _zeros_init, (self.embed_dim,))
        h = ops.dense(x, w_embed.astype(self.dtype), b_embed.astype(self.dtype))
        pos = self.param("pos_embed", _normal_init(0.02), (self.seq_len, self.embed_dim))
        h = h + pos.astype(self.dtype)[None]

        block_cls = TransformerBlock
        if self.remat:
            # Recompute the block's activations during backward instead of storing them;
            # `deterministic` is a static argument (two traces, not a traced branch).
            block_cls = fnn.remat(TransformerBlock, static_argnums=(2,),
                                  policy=remat_policy_fn(self.remat_policy))
        for i in range(self.num_layers):
            h = block_cls(
                num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
                mlp_ratio=self.mlp_ratio,
                dropout_rate=self.dropout_rate, attention_fn=self.attention_fn,
                causal=self.causal, rope=self.rope, dtype=self.dtype,
                num_experts=self.num_experts,
                expert_capacity_factor=self.expert_capacity_factor,
                expert_top_k=self.expert_top_k,
                expert_mesh=self.expert_mesh, name=f"block_{i}")(
                    h, deterministic)

        g = self.param("ln_f_scale", _ones_init, (self.embed_dim,))
        beta = self.param("ln_f_bias", _zeros_init, (self.embed_dim,))
        h = ops.layer_norm(h, g, beta)
        h = jnp.mean(h, axis=1)  # mean-pool over tokens

        w_head = self.param("head_kernel", _normal_init(0.02),
                            (self.embed_dim, self.num_classes))
        b_head = self.param("head_bias", _zeros_init, (self.num_classes,))
        logits = ops.dense(h, w_head.astype(self.dtype), b_head.astype(self.dtype))
        return ops.log_softmax(logits.astype(jnp.float32))
