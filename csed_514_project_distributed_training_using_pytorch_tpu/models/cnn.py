"""The MNIST CNN (``Net``), TPU-native.

Re-expression of the reference's only model (reference ``src/model.py:4-22``):

    conv(1→10, k5) → maxpool2 → relu → conv(10→20, k5) → Dropout2d → maxpool2 → relu
    → flatten(320) → fc(320→50) → relu → dropout → fc(50→10) → log_softmax

21,840 trainable parameters (conv1 260 + conv2 5,020 + fc1 16,050 + fc2 510 — the oracle in
SURVEY.md §3.4). Differences from the reference are deliberate TPU-first choices:

- **NHWC layout** (``[B, 28, 28, 1]`` input) instead of NCHW — what XLA:TPU tiles best.
- The whole forward is pure and jit-traceable; train/eval mode is the static
  ``deterministic`` flag (so each variant compiles once), not mutable module state
  (reference ``network.train()``/``network.eval()`` at ``src/train.py:70,91``).
- Dropout randomness comes from an explicit ``'dropout'`` PRNG collection threaded per step
  (and folded per-replica under SPMD) instead of a global RNG.
"""

from __future__ import annotations

import flax.linen as fnn
import jax
import jax.numpy as jnp

from csed_514_project_distributed_training_using_pytorch_tpu import ops


class Net(fnn.Module):
    """MNIST classifier emitting log-probabilities (reference ``src/model.py:22``)."""

    num_classes: int = 10
    conv_dropout_rate: float = 0.5   # nn.Dropout2d default p, reference src/model.py:11
    fc_dropout_rate: float = 0.5     # F.dropout default p, reference src/model.py:20
    dtype: jnp.dtype = jnp.float32

    @fnn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        """Forward pass. ``x: [B, 28, 28, 1]`` float. Returns ``[B, num_classes]`` log-probs."""
        x = x.astype(self.dtype)

        w1 = self.param("conv1_kernel", ops.torch_kaiming_uniform, (5, 5, 1, 10))
        b1 = self.param("conv1_bias", ops.torch_fan_in_uniform(5 * 5 * 1), (10,))
        x = ops.conv2d(x, w1.astype(self.dtype), b1.astype(self.dtype))   # [B,24,24,10]
        x = ops.relu(ops.max_pool2d(x, 2))                                # [B,12,12,10]

        w2 = self.param("conv2_kernel", ops.torch_kaiming_uniform, (5, 5, 10, 20))
        b2 = self.param("conv2_bias", ops.torch_fan_in_uniform(5 * 5 * 10), (20,))
        x = ops.conv2d(x, w2.astype(self.dtype), b2.astype(self.dtype))   # [B,8,8,20]
        if not deterministic:
            x = ops.dropout2d(self.make_rng("dropout"), x, self.conv_dropout_rate,
                              deterministic=False)
        x = ops.relu(ops.max_pool2d(x, 2))                                # [B,4,4,20]

        x = x.reshape((x.shape[0], -1))                                   # [B,320]

        w3 = self.param("fc1_kernel", ops.torch_kaiming_uniform, (320, 50))
        b3 = self.param("fc1_bias", ops.torch_fan_in_uniform(320), (50,))
        x = ops.relu(ops.dense(x, w3.astype(self.dtype), b3.astype(self.dtype)))
        if not deterministic:
            x = ops.dropout(self.make_rng("dropout"), x, self.fc_dropout_rate,
                            deterministic=False)

        w4 = self.param("fc2_kernel", ops.torch_kaiming_uniform, (50, self.num_classes))
        b4 = self.param("fc2_bias", ops.torch_fan_in_uniform(50), (self.num_classes,))
        x = ops.dense(x, w4.astype(self.dtype), b4.astype(self.dtype))

        return ops.log_softmax(x.astype(jnp.float32))


def param_count(params) -> int:
    """Total trainable parameter count of a params pytree (oracle: 21,840 for ``Net``)."""
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
