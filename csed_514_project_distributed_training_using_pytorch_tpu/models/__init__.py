"""Model zoo.

The reference defines exactly one model — the MNIST CNN ``Net`` (reference
``src/model.py:4-22``); ``models.cnn.Net`` is its TPU-native re-expression.
``models.transformer`` is the beyond-parity attention family that exercises the
framework's sequence-parallel machinery (``parallel/ring_attention.py``); both share the
same call contract, so every trainer accepts either.
"""

from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
    TransformerClassifier,
    validate_remat_policy,
)


import jax.numpy as jnp

VALID_MODELS = ("cnn", "transformer")


def validate_model_config(name: str, *, remat: bool = False,
                          causal: bool = False,
                          attention_window: int = 0,
                          kv_heads: int = 0, rope: bool = False,
                          remat_policy: str = "") -> None:
    """Fail fast on a bad ``--model`` value or model/knob combination — callers run this
    before any data download, dataset load, or cluster rendezvous so typos cost
    milliseconds, not side effects (on a fleet: not a full rendezvous per host)."""
    if name not in VALID_MODELS:
        raise ValueError(
            f"unknown model {name!r} — choose one of {', '.join(VALID_MODELS)}")
    if remat and name == "cnn":
        raise ValueError("--remat applies to the transformer family only "
                         "(the CNN's activations are a few hundred KB)")
    validate_remat_policy(remat, remat_policy)
    if causal and name == "cnn":
        raise ValueError("--causal applies to the transformer family only "
                         "(the CNN has no attention to mask)")
    if attention_window and name == "cnn":
        raise ValueError("--attention-window applies to the transformer family only "
                         "(the CNN has no attention to window)")
    if attention_window < 0:
        raise ValueError(f"--attention-window must be >= 0, got {attention_window}")
    if kv_heads and name == "cnn":
        raise ValueError("--kv-heads applies to the transformer family only "
                         "(the CNN has no attention heads)")
    if rope and name == "cnn":
        raise ValueError("--rope applies to the transformer family only "
                         "(the CNN has no attention positions)")
    if kv_heads < 0:
        raise ValueError(f"--kv-heads must be >= 0, got {kv_heads}")
    if kv_heads and TransformerClassifier.num_heads % kv_heads:
        # The classifier's head count is fixed; reject non-divisors pre-side-effects.
        raise ValueError(f"--kv-heads {kv_heads} must divide the transformer's "
                         f"{TransformerClassifier.num_heads} heads")


def build_model(name: str, *, bf16: bool = False, remat: bool = False,
                causal: bool = False, attention_window: int = 0,
                kv_heads: int = 0, rope: bool = False,
                remat_policy: str = ""):
    """Model factory behind the trainers' ``--model`` flag. Both families share the
    ``(x, *, deterministic)`` call contract on ``[B, 28, 28, 1]`` input, so every
    trainer/eval/checkpoint path works with either.

    ``bf16`` runs activations in bfloat16 (the MXU's native dtype) with float32 master
    weights and float32 softmax/loss statistics. ``remat`` (transformer only) recomputes
    each block's activations on backward — the ``jax.checkpoint`` memory/FLOPs trade.
    ``causal`` (transformer only) masks attention decoder-style. ``attention_window``
    (transformer only; 0 = full attention) restricts attention to a sliding window of
    that width (``ops.full_attention``'s ``window`` semantics) — the local-attention
    long-context knob.
    """
    validate_model_config(name, remat=remat, causal=causal,
                          attention_window=attention_window, kv_heads=kv_heads,
                          remat_policy=remat_policy)
    dtype = jnp.bfloat16 if bf16 else jnp.float32
    if name == "cnn":
        return Net(dtype=dtype)
    kwargs = {}
    if rope:
        kwargs["rope"] = True
    if kv_heads:
        kwargs["num_kv_heads"] = kv_heads
    if attention_window:
        from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
            windowed_attention_fn,
        )
        kwargs["attention_fn"] = windowed_attention_fn(attention_window)
    return TransformerClassifier(dtype=dtype, remat=remat, causal=causal,
                                 remat_policy=remat_policy, **kwargs)


__all__ = ["Net", "TransformerClassifier", "build_model", "validate_model_config", "validate_remat_policy",
           "VALID_MODELS"]
