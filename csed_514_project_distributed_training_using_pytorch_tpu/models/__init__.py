"""Model zoo. The reference defines exactly one model — the MNIST CNN ``Net``
(reference ``src/model.py:4-22``); ours is the TPU-native re-expression of it."""

from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net

__all__ = ["Net"]
