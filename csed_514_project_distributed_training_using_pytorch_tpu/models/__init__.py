"""Model zoo.

The reference defines exactly one model — the MNIST CNN ``Net`` (reference
``src/model.py:4-22``); ``models.cnn.Net`` is its TPU-native re-expression.
``models.transformer`` is the beyond-parity attention family that exercises the
framework's sequence-parallel machinery (``parallel/ring_attention.py``); both share the
same call contract, so every trainer accepts either.
"""

from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
    TransformerClassifier,
)


VALID_MODELS = ("cnn", "transformer")


def validate_model_name(name: str) -> None:
    """Fail fast on a bad ``--model`` value — callers run this before any data download,
    dataset load, or cluster init so typos cost milliseconds, not side effects."""
    if name not in VALID_MODELS:
        raise ValueError(
            f"unknown model {name!r} — choose one of {', '.join(VALID_MODELS)}")


def build_model(name: str):
    """Model factory behind the trainers' ``--model`` flag. Both families share the
    ``(x, *, deterministic)`` call contract on ``[B, 28, 28, 1]`` input, so every
    trainer/eval/checkpoint path works with either."""
    validate_model_name(name)
    return Net() if name == "cnn" else TransformerClassifier()


__all__ = ["Net", "TransformerClassifier", "build_model", "validate_model_name",
           "VALID_MODELS"]
