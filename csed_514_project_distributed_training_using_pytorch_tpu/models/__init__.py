"""Model zoo.

The reference defines exactly one model — the MNIST CNN ``Net`` (reference
``src/model.py:4-22``); ``models.cnn.Net`` is its TPU-native re-expression.
``models.transformer`` is the beyond-parity attention family that exercises the
framework's sequence-parallel machinery (``parallel/ring_attention.py``); both share the
same call contract, so every trainer accepts either.
"""

from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
    TransformerClassifier,
)

__all__ = ["Net", "TransformerClassifier"]
