"""Autoregressive pixel language model — the decoder family with KV-cache generation.

The reference has one model, a feed-forward MNIST classifier (reference
``src/model.py:4-22``); this module is beyond-parity surface that makes the framework's
CAUSAL machinery (causal attention, zig-zag rings, causal ring-of-flash) serve a real
autoregressive workload instead of an artificially-masked classifier:

- ``TransformerLM``: a decoder-only transformer over quantized pixel tokens. An MNIST
  image becomes a 784-token stream (``tokenize_images_to_ids``); training is standard
  teacher-forced next-token prediction (shift-right with BOS); the blocks are the SAME
  ``TransformerBlock`` as the classifier (same parameter layout, so the TP/FSDP/PP
  partition rules and the checkpoint format apply unchanged) with ``causal=True``.
- ``init_cache`` / ``decode_step`` / ``generate``: incremental decoding with per-layer
  K/V caches — plus ``decode_step_slots`` / ``reset_slots``, the PER-SLOT-position
  variant the continuous-batching serving engine (``serving/``) compiles exactly once
  and drives forever, and ``prefill_chunk``, the batched prefill that fills one
  slot's cache ``chunk`` prompt positions at a time (the engine's admission path;
  one compile per size in ``PREFILL_CHUNK_SIZES``) — one token's projections per step, attention against the cached prefix,
  cache append via ``lax.dynamic_update_slice``. The sampling loop is a handful of
  ``lax.scan`` segments under ``jit`` (compiler-friendly: static shapes, each segment
  attending over a static prefix that grows by ``DECODE_SEGMENT`` — masked prefix
  instead of dynamic slices), so generation runs on-device with no per-token Python
  dispatch and O(t)-amortized cache reads.

The decode path re-expresses the block math for a single position; its numerics are
pinned against the full teacher-forced forward at every position in
``tests/test_lm.py`` — the duplication is safe because the test fails if they drift.

TPU-first choices mirror the classifier: MXU-shaped denses, f32 softmax/LN statistics
under a ``dtype`` knob, pluggable ``attention_fn`` (ring/ulysses/flash cores drop in for
long-context training — S=784 divides an 8-way mesh).
"""

from __future__ import annotations

import functools
from typing import Callable

import flax.linen as fnn
import jax
import jax.numpy as jnp
from jax import lax

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
    quant as quant_ops,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
    MASK_VALUE,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.rotary import (
    apply_rotary,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
    TransformerBlock,
    _normal_init,
    _ones_init,
    _zeros_init,
    remat_policy_fn,
)

# torchvision's MNIST normalization constants (reference src/train.py:28-30): the
# datasets store (x/255 - MEAN) / STD; the tokenizer inverts this to bin raw
# intensity. Imported from the data pipeline so the two can never drift.
from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
    MNIST_MEAN as _MNIST_MEAN,
    MNIST_STD as _MNIST_STD,
)


def tokenize_images_to_ids(x: jax.Array, *, num_levels: int = 16) -> jax.Array:
    """``[B, H, W, C]`` normalized images → ``[B, H·W·C]`` int32 token ids in
    ``[0, num_levels)``: un-normalize to raw [0, 1] intensity, then quantize to
    ``num_levels`` uniform gray levels (vocab ids ``0..num_levels-1``; the LM reserves
    id ``num_levels`` for BOS)."""
    b = x.shape[0]
    raw = x * _MNIST_STD + _MNIST_MEAN
    ids = jnp.clip(jnp.round(raw * (num_levels - 1)), 0, num_levels - 1)
    return ids.reshape(b, -1).astype(jnp.int32)


def ids_to_images(ids: jax.Array, *, num_levels: int = 16,
                  shape=(28, 28, 1)) -> jax.Array:
    """Invert ``tokenize_images_to_ids`` (up to quantization): token ids →
    ``[B, H, W, C]`` raw [0, 1] intensity images (for saving sampled digits)."""
    raw = ids.astype(jnp.float32) / (num_levels - 1)
    return raw.reshape((ids.shape[0],) + tuple(shape))


class TransformerLM(fnn.Module):
    """Decoder-only LM over pixel tokens: ``[B, S]`` ids → ``[B, S, vocab]`` log-probs.

    ``vocab_size`` counts the BOS id (``num_levels + 1`` for the pixel vocabulary).
    The input is the shift-right stream (BOS first); position ``t``'s output predicts
    the t-th target token. Blocks reuse ``TransformerBlock`` (``block_i`` naming), so
    TP/FSDP partition specs and the PP stack/unstack bridge apply as-is.
    """

    vocab_size: int = 17        # 16 gray levels + BOS
    seq_len: int = 784
    embed_dim: int = 64
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int | None = None  # < num_heads = GQA: smaller KV projection AND a
                                     # proportionally smaller decode KV cache
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    attention_fn: Callable = ops.full_attention
    attention_window: int = 0   # sliding-window causal attention over the pixel
                                # stream (0 = full); composes with the DEFAULT dense
                                # core only — the KV-cache decode path honors the
                                # same window, keeping the decode-parity invariant
    rope: bool = False          # rotary position embeddings on q/k; when set, the
                                # learned additive pos_embed is skipped (RoPE owns
                                # position) — decode rotates its single position by
                                # the same formula, keeping decode parity
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    remat_policy: str = ""      # see models.transformer.remat_policy_fn

    def _attention_fn(self) -> Callable:
        if not self.attention_window:
            return self.attention_fn
        if self.attention_fn is not ops.full_attention:
            raise ValueError(
                "attention_window composes with the default dense core only — "
                "bake the window into your custom attention_fn instead")
        return ops.attention.windowed_attention_fn(self.attention_window)

    @fnn.compact
    def __call__(self, ids: jax.Array, *, deterministic: bool = True) -> jax.Array:
        b, s = ids.shape
        if s != self.seq_len:
            raise ValueError(f"expected seq_len {self.seq_len}, got {s}")
        # Tolerate float zeros from shape-only init paths (train.step.create_train_state
        # initializes with jnp.zeros(sample_input_shape)).
        ids = ids.astype(jnp.int32)

        tok = self.param("tok_embed", _normal_init(0.02),
                         (self.vocab_size, self.embed_dim))
        h = tok.astype(self.dtype)[ids]
        if not self.rope:   # RoPE owns position; no additive embedding then
            pos = self.param("pos_embed", _normal_init(0.02),
                             (self.seq_len, self.embed_dim))
            h = h + pos.astype(self.dtype)[None]

        block_cls = TransformerBlock
        if self.remat:
            block_cls = fnn.remat(TransformerBlock, static_argnums=(2,),
                                  policy=remat_policy_fn(self.remat_policy))
        attention_fn = self._attention_fn()
        for i in range(self.num_layers):
            h = block_cls(
                num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
                mlp_ratio=self.mlp_ratio,
                dropout_rate=self.dropout_rate, attention_fn=attention_fn,
                causal=True, rope=self.rope, dtype=self.dtype,
                name=f"block_{i}")(h, deterministic)

        g = self.param("ln_f_scale", _ones_init, (self.embed_dim,))
        beta = self.param("ln_f_bias", _zeros_init, (self.embed_dim,))
        h = ops.layer_norm(h, g, beta)
        w_head = self.param("head_kernel", _normal_init(0.02),
                            (self.embed_dim, self.vocab_size))
        b_head = self.param("head_bias", _zeros_init, (self.vocab_size,))
        logits = ops.dense(h, w_head.astype(self.dtype), b_head.astype(self.dtype))
        return ops.log_softmax(logits.astype(jnp.float32))

    def shift_right(self, targets: jax.Array) -> jax.Array:
        """Teacher-forcing input stream: ``[BOS, t_0, …, t_{S-2}]`` (BOS id =
        ``vocab_size - 1``)."""
        bos = jnp.full((targets.shape[0], 1), self.vocab_size - 1, targets.dtype)
        return jnp.concatenate([bos, targets[:, :-1]], axis=1)


def next_token_loss(model: TransformerLM, params, targets: jax.Array, rng,
                    *, deterministic: bool = False,
                    label_smoothing: float = 0.0) -> jax.Array:
    """Mean next-token NLL over all ``B·S`` positions (the LM training objective).
    ``label_smoothing`` follows torch ``CrossEntropyLoss`` semantics (the smoothed
    target ``(1−s)·onehot + s/V`` over the vocabulary)."""
    kwargs = {"deterministic": True} if deterministic else {"deterministic": False}
    rngs = {} if deterministic else {"dropout": rng}
    log_probs = model.apply({"params": params}, model.shift_right(targets),
                            rngs=rngs, **kwargs)
    picked = jnp.take_along_axis(log_probs, targets[..., None], axis=-1)[..., 0]
    if label_smoothing:
        smooth = jnp.mean(log_probs, axis=-1)
        picked = (1.0 - label_smoothing) * picked + label_smoothing * smooth
    return -jnp.mean(picked)


# =========================================================================================
# Incremental decoding (explicit functional KV cache)
# =========================================================================================


DECODE_SEGMENT = 128   # generate()'s static-prefix growth unit: segment j attends
                       # over min((j+1)·128, S) cache rows — small enough to halve
                       # the amortized cache re-read, big enough that the handful
                       # of per-segment scan bodies compile in seconds


# Axis SEMANTICS of the cache planes init_cache builds, by leaf name — the
# contract serving/shard.py maps onto a device mesh (slots are independent
# requests -> slot-DP; attention is embarrassingly parallel over KV heads ->
# TP). Kept here, next to the allocation, so a plane-layout change and its
# sharding rule can never drift apart.
KV_PLANE_AXES: dict[str, tuple[str, ...]] = {
    "k": ("slot", "position", "kv_head", "head_dim"),
    "v": ("slot", "position", "kv_head", "head_dim"),
    "k_scale": ("slot", "position", "kv_head"),
    "v_scale": ("slot", "position", "kv_head"),
}


def init_cache(model: TransformerLM, batch: int, *,
               kv_dtype: str | None = None) -> dict:
    """Zeroed per-layer K/V caches ``[B, seq_len, KV_H, Dh]`` in the model's
    activation dtype — a bf16 model decodes against a bf16 cache, halving the HBM
    read that dominates batched decode (the score/value einsums still accumulate
    in f32: mixed-dtype promotion upcasts on-chip, after the narrow HBM read).
    f32 models keep an f32 cache and bit-exact decode parity. Under GQA the cache
    holds only the ``num_kv_heads`` K/V heads — the decode-memory win.

    ``kv_dtype`` (an ``ops.quant.KV_DTYPES`` spec; ``None`` == ``"model"``, the
    bitwise-unchanged default) selects the plane dtype. ``"fp32"``/``"bf16"``
    are plain-cast planes. ``"int8"``/``"fp8"`` are QUANTIZE-ON-WRITE planes:
    every written row carries one symmetric scale per KV head, stored in
    ``k_scale``/``v_scale`` planes ``[B, seq_len, KV_H]`` (f32) alongside the
    narrow planes — the decode/prefill paths quantize rows as they write and
    dequantize inside the attention einsums, so HBM streams ~quarter the bytes
    while the scale adds 4 bytes per head per position."""
    head_dim = model.embed_dim // model.num_heads
    kvh = model.num_kv_heads or model.num_heads
    shape = (batch, model.seq_len, kvh, head_dim)
    dtype, scaled = quant_ops.resolve_kv_dtype(kv_dtype or "model", model.dtype)

    def layer():
        planes = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        if scaled:
            planes["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
            planes["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        return planes

    return {f"block_{i}": layer() for i in range(model.num_layers)}


def decode_step(model: TransformerLM, params, cache: dict, ids_t: jax.Array,
                t: jax.Array, *, prefix_len: int | None = None
                ) -> tuple[dict, jax.Array]:
    """One incremental step: token ids at position ``t`` → log-probs for position
    ``t``'s prediction, with every layer's K/V appended to the cache.

    ``ids_t: [B]``, ``t``: int32 scalar (traced). Re-expresses the block math for a
    single position (pre-LN attn + MLP residuals) attending against the masked cached
    prefix — pinned equal to the full forward at every position in tests.

    ``prefix_len`` (a STATIC int, default the full ``seq_len``) bounds the cache
    region the attention reads: callers that know ``t < prefix_len`` (the segmented
    ``generate`` scan) slice the score/value einsums to ``cache[:, :prefix_len]``,
    cutting decode's dominant HBM term — the per-step cache re-read — from
    O(seq_len) to O(t) amortized, with every shape still static. Positions beyond
    ``t`` inside the prefix are masked exactly as before, so the math is unchanged.
    """
    if "k_scale" in cache.get("block_0", {}):
        # Quantized (int8/fp8) planes are a serving-path feature: the slot entry
        # points quantize-on-write and dequantize-in-kernel. This path would
        # astype raw values into the narrow dtype (no scale) and attend against
        # the codes — garbage, silently.
        raise ValueError(
            "decode_step reads raw K/V planes only — use decode_step_slots/"
            "prefill_chunk for a quantized cache, or init_cache() without "
            "kv_dtype")
    b = ids_t.shape[0]
    e, nh = model.embed_dim, model.num_heads
    hd = e // nh
    kvh = model.num_kv_heads or nh
    rep = nh // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    pl_ = model.seq_len if prefix_len is None else prefix_len
    if not 0 < pl_ <= model.seq_len:
        raise ValueError(f"prefix_len {pl_} outside (0, {model.seq_len}]")

    h = params["tok_embed"].astype(jnp.float32)[ids_t]           # [B, E]
    if not model.rope:
        h = h + params["pos_embed"].astype(jnp.float32)[t]

    for i in range(model.num_layers):
        p = params[f"block_{i}"]
        a = p["attn"]
        x = ops.layer_norm(h, p["ln1_scale"], p["ln1_bias"])
        if kvh == nh:
            qkv = ops.dense(x, a["qkv_kernel"], a["qkv_bias"])    # [B, 3E]
            q = qkv[:, :e].reshape(b, nh, hd)
            k = qkv[:, e:2 * e].reshape(b, kvh, hd)
            v = qkv[:, 2 * e:].reshape(b, kvh, hd)
        else:  # GQA: split projections, kvh-head K/V (the smaller cache)
            q = ops.dense(x, a["q_kernel"], a["q_bias"]).reshape(b, nh, hd)
            kv = ops.dense(x, a["kv_kernel"], a["kv_bias"]).reshape(b, 2, kvh, hd)
            k, v = kv[:, 0], kv[:, 1]
        if model.rope:
            q = apply_rotary(q, t)
            k = apply_rotary(k, t)
        layer = cache[f"block_{i}"]
        k_cache = lax.dynamic_update_slice(
            layer["k"], k[:, None].astype(layer["k"].dtype), (0, t, 0, 0))
        v_cache = lax.dynamic_update_slice(
            layer["v"], v[:, None].astype(layer["v"].dtype), (0, t, 0, 0))
        cache = {**cache, f"block_{i}": {"k": k_cache, "v": v_cache}}
        # Masked-prefix attention: full-length scores with positions > t masked out —
        # static shapes (scan/jit-friendly) instead of a dynamic-length slice. A
        # windowed model masks the same sliding band it trained with (the
        # decode-parity invariant covers windowed configs too). Query heads group
        # over their shared K/V head (GQA); rep == 1 degenerates to plain MHA.
        qg = q.reshape(b, kvh, rep, hd)
        scores = jnp.einsum("bgrd,bsgd->bgrs", qg * scale,
                            k_cache[:, :pl_])                 # [B,G,R,pl]
        pos = jnp.arange(pl_)[None, None, None]
        visible = pos <= t
        if model.attention_window:
            visible &= t - pos < model.attention_window
        scores = jnp.where(visible, scores, MASK_VALUE)
        weights = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bgrs,bsgd->bgrd", weights,
                          v_cache[:, :pl_]).reshape(b, e)
        h = h + ops.dense(attn, a["out_kernel"], a["out_bias"])

        x = ops.layer_norm(h, p["ln2_scale"], p["ln2_bias"])
        up = ops.gelu(ops.dense(x, p["mlp_up_kernel"], p["mlp_up_bias"]))
        h = h + ops.dense(up, p["mlp_down_kernel"], p["mlp_down_bias"])

    h = ops.layer_norm(h, params["ln_f_scale"], params["ln_f_bias"])
    logits = ops.dense(h, params["head_kernel"], params["head_bias"])
    return cache, ops.log_softmax(logits.astype(jnp.float32))


def decode_step_slots(model: TransformerLM, params, cache: dict,
                      ids_t: jax.Array, t: jax.Array
                      ) -> tuple[dict, jax.Array]:
    """One incremental step at PER-SLOT positions: ``ids_t: [B]``, ``t: [B]`` int32.

    The serving engine's decode program (``serving/engine.py``): batch row ``b`` is
    an independent decode SLOT at its own position ``t[b]``, so one fixed-shape
    program advances every in-flight request one token regardless of their mix of
    prompt/output lengths — the zero-retracing requirement of continuous batching.
    Same per-position math as ``decode_step`` (pinned token-identical to sequential
    ``generate`` in ``tests/test_serving.py``): each slot's K/V row is written at
    its own position via a vmapped ``lax.dynamic_update_index_in_dim``, the causal
    (and sliding-window) mask is per-slot ``pos <= t[b]``, and RoPE rotates each
    slot by its own position. No ``prefix_len`` narrowing: slots sit at arbitrary
    positions, so every step reads the full ``[B, S]`` cache — the serving cache
    re-read is O(S) per token by design (fixed shapes beat a per-mix recompile).

    A QUANTIZED cache (``init_cache(..., kv_dtype="int8"/"fp8")`` — detected by
    its ``k_scale`` planes) changes only the plane I/O, never the program count:
    the freshly projected K/V rows are quantized on write (one scale per KV
    head, written by the same vmapped row scatter), and the score/value einsums
    read the dequantized planes — an on-chip upcast fused into the einsum, so
    the per-step HBM read is the NARROW plane plus the scale vector. Params may
    likewise hold ``ops.quant.QuantizedTensor`` kernels (``quantize_params``);
    plain arrays take the exact ``ops.dense`` path, so the unquantized trace is
    bitwise identical to the pre-quantization code.
    """
    b = ids_t.shape[0]
    e, nh = model.embed_dim, model.num_heads
    hd = e // nh
    kvh = model.num_kv_heads or nh
    rep = nh // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))

    h = params["tok_embed"].astype(jnp.float32)[ids_t]           # [B, E]
    if not model.rope:
        h = h + params["pos_embed"].astype(jnp.float32)[t]       # gather per slot

    # [S, KV, Dh] cache, [KV, Dh] row, scalar position — batched over slots.
    write_row = jax.vmap(
        lambda c, row, pos: lax.dynamic_update_index_in_dim(c, row, pos, 0))
    pos = jnp.arange(model.seq_len)[None]                        # [1, S]
    tb = t[:, None]                                              # [B, 1]
    visible = pos <= tb
    if model.attention_window:
        visible &= tb - pos < model.attention_window
    visible = visible[:, None, None, :]                          # [B, 1, 1, S]

    for i in range(model.num_layers):
        p = params[f"block_{i}"]
        a = p["attn"]
        x = ops.layer_norm(h, p["ln1_scale"], p["ln1_bias"])
        if kvh == nh:
            qkv = quant_ops.dense_any(x, a["qkv_kernel"], a["qkv_bias"])  # [B, 3E]
            q = qkv[:, :e].reshape(b, nh, hd)
            k = qkv[:, e:2 * e].reshape(b, kvh, hd)
            v = qkv[:, 2 * e:].reshape(b, kvh, hd)
        else:  # GQA: split projections, kvh-head K/V (the smaller cache)
            q = quant_ops.dense_any(x, a["q_kernel"], a["q_bias"]).reshape(b, nh, hd)
            kv = quant_ops.dense_any(x, a["kv_kernel"],
                                     a["kv_bias"]).reshape(b, 2, kvh, hd)
            k, v = kv[:, 0], kv[:, 1]
        if model.rope:
            # positions [B] on [B, H, D]: the batch dim takes apply_rotary's
            # sequence slot, giving each slot its own rotation angle.
            q = apply_rotary(q, t)
            k = apply_rotary(k, t)
        layer = cache[f"block_{i}"]
        if "k_scale" in layer:   # quantize-on-write planes with per-head scales
            kq, ks = quant_ops.quantize_rows(k, layer["k"].dtype)
            vq, vs = quant_ops.quantize_rows(v, layer["v"].dtype)
            k_cache = write_row(layer["k"], kq, t)
            v_cache = write_row(layer["v"], vq, t)
            ks_cache = write_row(layer["k_scale"], ks, t)
            vs_cache = write_row(layer["v_scale"], vs, t)
            cache = {**cache, f"block_{i}": {
                "k": k_cache, "v": v_cache,
                "k_scale": ks_cache, "v_scale": vs_cache}}
            # Dequantize-in-kernel: the upcast/rescale fuses into the einsum
            # that consumes it — HBM streamed the narrow plane.
            k_read = quant_ops.dequantize_rows(k_cache, ks_cache)
            v_read = quant_ops.dequantize_rows(v_cache, vs_cache)
        else:
            k_cache = write_row(layer["k"], k.astype(layer["k"].dtype), t)
            v_cache = write_row(layer["v"], v.astype(layer["v"].dtype), t)
            cache = {**cache, f"block_{i}": {"k": k_cache, "v": v_cache}}
            k_read, v_read = k_cache, v_cache
        qg = q.reshape(b, kvh, rep, hd)
        scores = jnp.einsum("bgrd,bsgd->bgrs", qg * scale, k_read)   # [B,G,R,S]
        scores = jnp.where(visible, scores, MASK_VALUE)
        weights = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bgrs,bsgd->bgrd", weights, v_read).reshape(b, e)
        h = h + quant_ops.dense_any(attn, a["out_kernel"], a["out_bias"])

        x = ops.layer_norm(h, p["ln2_scale"], p["ln2_bias"])
        up = ops.gelu(quant_ops.dense_any(x, p["mlp_up_kernel"],
                                          p["mlp_up_bias"]))
        h = h + quant_ops.dense_any(up, p["mlp_down_kernel"],
                                    p["mlp_down_bias"])

    h = ops.layer_norm(h, params["ln_f_scale"], params["ln_f_bias"])
    logits = quant_ops.dense_any(h, params["head_kernel"], params["head_bias"])
    return cache, ops.log_softmax(logits.astype(jnp.float32))


def decode_nll(model: TransformerLM, params, targets: jax.Array, *,
               kv_dtype: str | None = None) -> jax.Array:
    """Teacher-forced mean next-token NLL scored through the SERVING decode
    path (``decode_step_slots``) — the accuracy-budget probe for quantized
    execution: run it with ``kv_dtype=None`` for the fp32 oracle and with
    ``kv_dtype="int8"`` (and/or quantized ``params``) for the policy under
    test, and the difference is the NLL cost of the policy, measured through
    the exact kernels the engine serves with (quantize-on-write rounding on
    every cached row included). ``targets``: ``[B, seq_len]`` token ids; wrap
    in ``jax.jit`` for repeated use — the scan traces once."""
    b, s = targets.shape
    if s != model.seq_len:
        raise ValueError(f"expected seq_len {model.seq_len}, got {s}")
    params = jax.tree_util.tree_map(jnp.asarray, params)
    targets = targets.astype(jnp.int32)
    cache = init_cache(model, b, kv_dtype=kv_dtype)
    inputs = jnp.transpose(model.shift_right(targets))        # [S, B]
    target_cols = jnp.transpose(targets)                      # [S, B]

    def step(cache, xs):
        t, ids_t, tgt_t = xs
        cache, logp = decode_step_slots(model, params, cache, ids_t,
                                        jnp.full((b,), t, jnp.int32))
        return cache, jnp.take_along_axis(logp, tgt_t[:, None], axis=-1)[:, 0]

    positions = jnp.arange(s, dtype=jnp.int32)
    _, picked = lax.scan(step, cache, (positions, inputs, target_cols))
    return -jnp.mean(picked)


PREFILL_CHUNK_SIZES = (32, 128, 512)   # the serving engine's default static chunk
                                       # set: admission of ANY prompt length
                                       # compiles at most one program per size


def prefill_chunk(model: TransformerLM, params, cache: dict, prompt: jax.Array,
                  slot: jax.Array, start: jax.Array, length: jax.Array,
                  fresh: jax.Array, *, chunk: int) -> dict:
    """Batched prefill: write ``length`` prompt positions of ONE slot's KV cache in
    a single ``[chunk]``-wide causal forward.

    The serving engine's answer to the one-token-per-step prompt tax: where
    prefill-as-decode pays one ``decode_step_slots`` invocation per prompt token,
    this runs full-sequence causal attention for ``chunk`` positions at once —
    MXU-shaped ``[chunk, E]`` matmuls instead of ``[B, E]`` single-token ones — and
    bulk-writes the chunk's K/V rows, so a length-P prompt costs
    ``ceil(P / chunk)`` program invocations. ``chunk`` is STATIC (one compile per
    size in the engine's small chunk set); everything else is data:

    - ``prompt``: the engine's device-resident ``[num_slots, S]`` prompt buffer;
    - ``slot``, ``start``, ``length``: traced int32 scalars — which slot, the first
      position of the chunk, and how many of the ``chunk`` rows are real (the tail
      chunk of a prompt pads up; padded rows' K/V writes are DROPPED, not clamped,
      so a partial chunk never clobbers live rows);
    - ``fresh``: traced bool — wipe the slot's planes first (recycled-slot hygiene,
      same contract as ``reset_slots``; False when a prefix-cache hit installed
      rows that must survive).

    Token-identity with the per-token path is by construction, not luck: the chunk
    writes its K/V into the slot's FULL ``[S]`` plane first and then attends
    against that plane under the same ``pos <= t`` (and sliding-window) mask and
    the same einsum/reduction structure as ``decode_step_slots`` — position ``t``
    reads exactly the rows (cached prefix + in-chunk causal) it would have seen
    one token at a time, at the same cache dtype rounding — including under a
    QUANTIZED cache (``k_scale`` planes present), where the chunk's rows are
    quantized on write with the identical per-head scale math as
    ``decode_step_slots`` and attention reads the dequantized plane. No logits:
    prompt tokens are forced, so prefill only has to leave the cache behind.
    """
    s = model.seq_len
    e, nh = model.embed_dim, model.num_heads
    hd = e // nh
    kvh = model.num_kv_heads or nh
    rep = nh // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    if not 0 < chunk <= s:
        raise ValueError(f"chunk {chunk} outside (0, {s}]")

    positions = start + jnp.arange(chunk, dtype=jnp.int32)       # [C]
    valid = jnp.arange(chunk) < length
    # Padded rows may run past seq_len: every gather clips, every write drops.
    safe_pos = jnp.clip(positions, 0, s - 1)
    write_pos = jnp.where(valid, safe_pos, s)                    # s = dropped
    row = prompt[slot]                                           # [S]
    # Shift-right input stream: position 0 reads BOS, position p reads prompt[p-1].
    prev = row[jnp.clip(positions - 1, 0, s - 1)]
    inp = jnp.where(positions == 0, model.vocab_size - 1, prev)

    h = params["tok_embed"].astype(jnp.float32)[inp]             # [C, E]
    if not model.rope:
        h = h + params["pos_embed"].astype(jnp.float32)[safe_pos]

    pos_s = jnp.arange(s)[None]                                  # [1, S]
    visible = pos_s <= positions[:, None]
    if model.attention_window:
        visible &= positions[:, None] - pos_s < model.attention_window
    visible = visible[:, None, None, :]                          # [C, 1, 1, S]

    for i in range(model.num_layers):
        p = params[f"block_{i}"]
        a = p["attn"]
        x = ops.layer_norm(h, p["ln1_scale"], p["ln1_bias"])
        if kvh == nh:
            qkv = quant_ops.dense_any(x, a["qkv_kernel"], a["qkv_bias"])  # [C, 3E]
            q = qkv[:, :e].reshape(chunk, nh, hd)
            k = qkv[:, e:2 * e].reshape(chunk, kvh, hd)
            v = qkv[:, 2 * e:].reshape(chunk, kvh, hd)
        else:  # GQA: split projections, kvh-head K/V (the smaller cache)
            q = quant_ops.dense_any(x, a["q_kernel"],
                                    a["q_bias"]).reshape(chunk, nh, hd)
            kv = quant_ops.dense_any(x, a["kv_kernel"],
                                     a["kv_bias"]).reshape(chunk, 2, kvh, hd)
            k, v = kv[:, 0], kv[:, 1]
        if model.rope:
            q = apply_rotary(q, positions)
            k = apply_rotary(k, positions)
        layer = cache[f"block_{i}"]
        quantized = "k_scale" in layer
        if quantized:
            # Same quantize-on-write as decode_step_slots — a chunk-prefilled
            # row is bit-identical to the row the per-token path would have
            # cached, so the decode-parity argument carries over unchanged.
            k, ks = quant_ops.quantize_rows(k, layer["k"].dtype)
            v, vs = quant_ops.quantize_rows(v, layer["v"].dtype)
        plane_k, plane_v = layer["k"][slot], layer["v"][slot]    # [S, KV, Dh]
        # Wipe-then-write keeps a recycled slot bit-identical to a fresh one
        # (reset_slots' contract; fresh is False mid-plan and on prefix hits).
        zero = jnp.zeros((), plane_k.dtype)
        plane_k = jnp.where(fresh, zero, plane_k)
        plane_v = jnp.where(fresh, zero, plane_v)
        plane_k = plane_k.at[write_pos].set(k.astype(plane_k.dtype), mode="drop")
        plane_v = plane_v.at[write_pos].set(v.astype(plane_v.dtype), mode="drop")
        new_layer = {
            "k": lax.dynamic_update_index_in_dim(layer["k"], plane_k, slot, 0),
            "v": lax.dynamic_update_index_in_dim(layer["v"], plane_v, slot, 0)}
        if quantized:
            plane_ks = jnp.where(fresh, jnp.zeros((), jnp.float32),
                                 layer["k_scale"][slot])         # [S, KV]
            plane_vs = jnp.where(fresh, jnp.zeros((), jnp.float32),
                                 layer["v_scale"][slot])
            plane_ks = plane_ks.at[write_pos].set(ks, mode="drop")
            plane_vs = plane_vs.at[write_pos].set(vs, mode="drop")
            new_layer["k_scale"] = lax.dynamic_update_index_in_dim(
                layer["k_scale"], plane_ks, slot, 0)
            new_layer["v_scale"] = lax.dynamic_update_index_in_dim(
                layer["v_scale"], plane_vs, slot, 0)
            k_read = quant_ops.dequantize_rows(plane_k, plane_ks)
            v_read = quant_ops.dequantize_rows(plane_v, plane_vs)
        else:
            k_read, v_read = plane_k, plane_v
        cache = {**cache, f"block_{i}": new_layer}
        # Attend against the full written plane under the per-position mask —
        # decode_step_slots' exact score/value structure, batched over the chunk.
        qg = q.reshape(chunk, kvh, rep, hd)
        scores = jnp.einsum("cgrd,sgd->cgrs", qg * scale, k_read)    # [C,G,R,S]
        scores = jnp.where(visible, scores, MASK_VALUE)
        weights = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("cgrs,sgd->cgrd", weights, v_read).reshape(chunk, e)
        h = h + quant_ops.dense_any(attn, a["out_kernel"], a["out_bias"])

        x = ops.layer_norm(h, p["ln2_scale"], p["ln2_bias"])
        up = ops.gelu(quant_ops.dense_any(x, p["mlp_up_kernel"],
                                          p["mlp_up_bias"]))
        h = h + quant_ops.dense_any(up, p["mlp_down_kernel"],
                                    p["mlp_down_bias"])
    return cache


def verify_chunk(model: TransformerLM, params, cache: dict, ids: jax.Array,
                 t: jax.Array, draft: jax.Array, *, k: int
                 ) -> tuple[dict, jax.Array]:
    """Batched K-token verify: score ``k`` draft tokens per slot in ONE
    fixed-shape causal forward over the slot planes — the program that lets
    speculative decoding amortize each full-cache read over up to ``k + 1``
    emitted tokens instead of one.

    ``ids: [B]`` is each slot's last accepted token, ``t: [B]`` its position
    (``decode_step_slots`` conventions), ``draft: [B, k]`` the drafter's
    proposals for positions ``t+1 .. t+k``. ``k`` is the only STATIC argument
    (one compile per configured width — the engine pins ``verify_trace_counts``
    at <= 1 per ``k``); everything else is data. The chunk inputs are
    ``[ids, d_1, .., d_k]`` at positions ``t .. t+k``; row ``j``'s log-probs
    are the target distribution for the token AT position ``t+j`` — row 0
    re-derives plain decode, rows ``1..k`` score the drafts, and the last row
    is the bonus/correction distribution when every draft survives. Returns
    ``(cache, log_probs [B, k+1, V])``; ACCEPTANCE is the caller's (the
    engine's jitted verify program folds greedy prefix-match or rejection
    sampling on top, so the accept rule is data too).

    Cache semantics are ``prefill_chunk``'s, batched over slots: the chunk
    bulk-writes all ``k+1`` rows into each slot's full ``[S]`` plane FIRST
    (quantize-on-write with the identical per-head scale math when the planes
    carry ``k_scale`` — a verify-written row is bit-identical to the row the
    per-token path would have cached) and then attends against that plane
    under the same per-position ``pos <= t+j`` (and sliding-window) mask and
    einsum structure as ``decode_step_slots`` — token-identity of greedy
    acceptance with sequential decode is by construction. Rows past
    ``seq_len`` DROP (never clamp onto live rows). Rollback needs no cache
    surgery: rows written for REJECTED drafts sit at positions strictly
    beyond the new accepted position, and the next verify/decode step's
    write-before-attend covers every such row before any query can see it —
    accepted rows are never rewritten, rejected rows are never read.
    """
    s = model.seq_len
    e, nh = model.embed_dim, model.num_heads
    hd = e // nh
    kvh = model.num_kv_heads or nh
    rep = nh // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    if not 1 <= k < s:
        raise ValueError(f"k {k} outside [1, {s})")
    w = k + 1                                                    # chunk width
    b = ids.shape[0]

    x = jnp.concatenate([ids[:, None], draft], axis=1).astype(jnp.int32)  # [B,W]
    positions = t[:, None] + jnp.arange(w, dtype=jnp.int32)      # [B, W]
    safe_pos = jnp.clip(positions, 0, s - 1)
    write_pos = jnp.where(positions < s, safe_pos, s)            # s = dropped
    slot_idx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, w))

    h = params["tok_embed"].astype(jnp.float32)[x]               # [B, W, E]
    if not model.rope:
        h = h + params["pos_embed"].astype(jnp.float32)[safe_pos]

    pos_s = jnp.arange(s)[None, None]                            # [1, 1, S]
    visible = pos_s <= positions[:, :, None]
    if model.attention_window:
        visible &= positions[:, :, None] - pos_s < model.attention_window
    visible = visible[:, :, None, None, :]                       # [B, W, 1, 1, S]

    def flat_dense(y, kern, bias):
        # The projections run in the [rows, E] 2-D shape decode/prefill use, so
        # the per-row numerics (and the w8a8 per-row activation quantization)
        # are position-for-position identical to the per-token path.
        return quant_ops.dense_any(y.reshape(b * w, -1), kern,
                                   bias).reshape(b, w, -1)

    for i in range(model.num_layers):
        p = params[f"block_{i}"]
        a = p["attn"]
        xln = ops.layer_norm(h, p["ln1_scale"], p["ln1_bias"])
        if kvh == nh:
            qkv = flat_dense(xln, a["qkv_kernel"], a["qkv_bias"])  # [B, W, 3E]
            q = qkv[..., :e].reshape(b, w, nh, hd)
            kk = qkv[..., e:2 * e].reshape(b, w, kvh, hd)
            v = qkv[..., 2 * e:].reshape(b, w, kvh, hd)
        else:  # GQA: split projections, kvh-head K/V (the smaller cache)
            q = flat_dense(xln, a["q_kernel"], a["q_bias"]).reshape(b, w, nh, hd)
            kv = flat_dense(xln, a["kv_kernel"],
                            a["kv_bias"]).reshape(b, w, 2, kvh, hd)
            kk, v = kv[:, :, 0], kv[:, :, 1]
        if model.rope:
            q = apply_rotary(q, safe_pos)
            kk = apply_rotary(kk, safe_pos)
        layer = cache[f"block_{i}"]
        quantized = "k_scale" in layer
        if quantized:
            kk, ks = quant_ops.quantize_rows(kk, layer["k"].dtype)
            v, vs = quant_ops.quantize_rows(v, layer["v"].dtype)
        # Bulk row scatter over (slot, position) pairs; out-of-range rows drop.
        k_cache = layer["k"].at[slot_idx, write_pos].set(
            kk.astype(layer["k"].dtype), mode="drop")
        v_cache = layer["v"].at[slot_idx, write_pos].set(
            v.astype(layer["v"].dtype), mode="drop")
        new_layer = {"k": k_cache, "v": v_cache}
        if quantized:
            ks_cache = layer["k_scale"].at[slot_idx, write_pos].set(
                ks, mode="drop")
            vs_cache = layer["v_scale"].at[slot_idx, write_pos].set(
                vs, mode="drop")
            new_layer["k_scale"] = ks_cache
            new_layer["v_scale"] = vs_cache
            k_read = quant_ops.dequantize_rows(k_cache, ks_cache)
            v_read = quant_ops.dequantize_rows(v_cache, vs_cache)
        else:
            k_read, v_read = k_cache, v_cache
        cache = {**cache, f"block_{i}": new_layer}
        qg = q.reshape(b, w, kvh, rep, hd)
        scores = jnp.einsum("bwgrd,bsgd->bwgrs", qg * scale,
                            k_read)                              # [B,W,G,R,S]
        scores = jnp.where(visible, scores, MASK_VALUE)
        weights = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bwgrs,bsgd->bwgrd", weights,
                          v_read).reshape(b, w, e)
        h = h + flat_dense(attn, a["out_kernel"], a["out_bias"])

        xln = ops.layer_norm(h, p["ln2_scale"], p["ln2_bias"])
        up = ops.gelu(flat_dense(xln, p["mlp_up_kernel"], p["mlp_up_bias"]))
        h = h + flat_dense(up, p["mlp_down_kernel"], p["mlp_down_bias"])

    h = ops.layer_norm(h, params["ln_f_scale"], params["ln_f_bias"])
    logits = flat_dense(h, params["head_kernel"], params["head_bias"])
    return cache, ops.log_softmax(logits.astype(jnp.float32))


def reset_slots(cache: dict, fresh: jax.Array) -> dict:
    """Zero the K/V rows of the slots where ``fresh`` (``[B]`` bool) is set — slot
    recycling for the serving engine. Correctness never depends on it (the per-slot
    ``pos <= t`` mask already hides rows beyond a slot's position), but wiping a
    recycled slot keeps its cache bit-identical to a freshly ``init_cache``'d one,
    so the decode-parity invariant is checkable slot-by-slot at any time. The
    wipe is rank-generic so a quantized cache's ``[B, S, KV_H]`` scale planes
    are wiped exactly like the ``[B, S, KV_H, Dh]`` K/V planes."""
    def wipe(x):
        mask = fresh.reshape(fresh.shape + (1,) * (x.ndim - 1))
        return jnp.where(mask, jnp.zeros((), x.dtype), x)
    return jax.tree_util.tree_map(wipe, cache)


# =============================================================================
# Paged KV cache (DESIGN.md §27): the serving cache as a fixed page pool
# =============================================================================
#
# The contiguous serving cache above prices every slot at worst-case context —
# ``[num_slots, S]`` planes whether a request uses 8 tokens or all S. The paged
# layout replaces those planes with per-layer PAGE POOLS
# ``[num_pages, page_size, KV_H, Dh]`` plus ONE page table ``[B, P_max]``
# (int32, ``P_max = ceil(S / page_size)``) carried as DATA into every jitted
# call: slot ``b``'s logical position ``p`` lives at
# ``pool[table[b, p // page_size], p % page_size]``. Slot count decouples from
# max context — the pool is sized for the tokens actually resident, and
# prefix-cache hits / park / resume become page refcount bumps in the host
# allocator (``serving/pagepool.py``) instead of whole-plane copies.
#
# The paged model functions below are ADAPTERS over the contiguous trio, not
# re-implementations: gather the table's view (``pool[table] → [B, S, ...]``),
# run the EXISTING function on that view, then scatter the rows it wrote back
# into the pool at their ``(page, offset)`` coordinates. Every arithmetic op —
# projections, quantize-on-write scales, masked einsums, softmax — is the same
# traced code, so greedy decode is token-IDENTICAL to the contiguous oracle by
# construction (pinned across the engine matrix in tests/test_paged_kv.py),
# and a math edit to the contiguous path cannot drift from the paged one.
# Masked garbage is the one place the layouts differ (a fresh slot's gathered
# view shows recycled-page junk where the contiguous plane shows zeros), and
# it is harmless by the same argument ``reset_slots`` documents: every masked
# score becomes ``MASK_VALUE`` exactly, its softmax weight underflows to 0.0,
# and ``0 · finite == 0`` — the pool never holds non-finite values (every page
# starts zeroed and only ever receives projected rows/scales). Paged mode
# therefore needs NO wipe-on-recycle at all.
#
# Unmapped table entries point at the allocator's reserved NULL page, so the
# fixed-shape programs' out-of-reservation writes (a parked slot's decode row,
# verify rows past a short reservation) land somewhere harmless instead of in
# a neighbour's page. The engine's reservation-at-admission invariant
# guarantees every position ``<= t`` of a LIVE slot is mapped, which is all
# the visibility mask ever reads.
#
# ``ops/paged_attention.py`` holds the TPU decode kernel (page-table-steered
# gather-attend with the dequant fused in, scalar-prefetch table); these
# adapters are its pure-XLA gather fallback and the tier-1 identity oracle.

# Axis semantics of the pool planes, by leaf name — the paged counterpart of
# KV_PLANE_AXES, mapped onto the serve mesh by serving/shard.py (pages are
# slot-owned -> slot-DP axis; KV heads -> TP axis, same as contiguous).
PAGE_PLANE_AXES: dict[str, tuple[str, ...]] = {
    "k": ("page", "offset", "kv_head", "head_dim"),
    "v": ("page", "offset", "kv_head", "head_dim"),
    "k_scale": ("page", "offset", "kv_head"),
    "v_scale": ("page", "offset", "kv_head"),
}


def pages_per_slot(seq_len: int, page_size: int) -> int:
    """P_max — the page-table width that can map a full-context slot."""
    if not 0 < page_size:
        raise ValueError(f"page_size must be positive, got {page_size}")
    return -(-seq_len // page_size)


def init_page_pool(model: TransformerLM, num_pages: int, *, page_size: int,
                   kv_dtype: str | None = None) -> dict:
    """Zeroed per-layer page pools ``[num_pages, page_size, KV_H, Dh]`` —
    ``init_cache``'s paged twin, same dtype/scale-plane rules (``kv_dtype``
    int8/fp8 adds ``k_scale``/``v_scale`` pools ``[num_pages, page_size,
    KV_H]`` f32). Total token capacity is ``num_pages * page_size`` split
    however the allocator hands out pages — the knob that decouples slot
    count from max context."""
    head_dim = model.embed_dim // model.num_heads
    kvh = model.num_kv_heads or model.num_heads
    shape = (num_pages, page_size, kvh, head_dim)
    dtype, scaled = quant_ops.resolve_kv_dtype(kv_dtype or "model", model.dtype)

    def layer():
        planes = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        if scaled:
            planes["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
            planes["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        return planes

    return {f"block_{i}": layer() for i in range(model.num_layers)}


def pool_page_size(pool: dict) -> int:
    """The pool's static page size, read off a K plane (one owner — callers
    never carry it separately and drift)."""
    return pool["block_0"]["k"].shape[1]


def _gather_view(pool: dict, table: jax.Array, seq_len: int) -> dict:
    """Materialize each slot's logical ``[S]`` cache view through the table:
    ``pool[table] → [B, P_max·ps, ...]`` truncated to ``[B, S, ...]``. The
    view is positionally identical to the contiguous plane at every mapped
    position; unmapped positions show null/recycled-page garbage the masks
    hide (module comment above)."""
    b, p_max = table.shape

    def leaf(x):
        ps = x.shape[1]
        v = x[table]                                   # [B, P, ps, ...]
        return v.reshape((b, p_max * ps) + x.shape[2:])[:, :seq_len]

    return jax.tree_util.tree_map(leaf, pool)


def paged_decode_step_slots(model: TransformerLM, params, pool: dict,
                            table: jax.Array, ids_t: jax.Array, t: jax.Array
                            ) -> tuple[dict, jax.Array]:
    """``decode_step_slots`` through a page table: ``pool`` per
    ``init_page_pool``, ``table: [B, P_max]`` int32 (data — the zero-retrace
    property extends to ANY page assignment), ``ids_t``/``t`` as contiguous.

    Gathers the table's view, runs the contiguous step on it (identical math,
    including quantize-on-write when scale pools are present), then scatters
    each slot's one written row back to ``(table[b, t//ps], t % ps)``. Slots
    whose table rows are null-mapped (inactive/parked) write their row into
    the null page — harmless by the reservation invariant."""
    b = ids_t.shape[0]
    s = model.seq_len
    ps = pool_page_size(pool)
    view = _gather_view(pool, table, s)
    new_view, log_probs = decode_step_slots(model, params, view, ids_t, t)

    safe_t = jnp.clip(t, 0, s - 1)      # decode's write clamps the same way
    pages = table[jnp.arange(b), safe_t // ps]                   # [B]
    offs = safe_t % ps

    def put(pool_leaf, view_leaf):
        rows = view_leaf[jnp.arange(b), safe_t]                  # [B, ...]
        return pool_leaf.at[pages, offs].set(rows)

    new_pool = jax.tree_util.tree_map(put, pool, new_view)
    return new_pool, log_probs


def paged_prefill_chunk(model: TransformerLM, params, pool: dict,
                        table: jax.Array, prompt: jax.Array, slot: jax.Array,
                        start: jax.Array, length: jax.Array, *,
                        chunk: int) -> dict:
    """``prefill_chunk`` through a page table — gathers only the ONE slot's
    view (``[1, S, ...]``, so per-chunk cost stays O(S) not O(B·S)), runs the
    contiguous chunk on it at batch index 0, and scatters the chunk's valid
    rows to their pages. No ``fresh`` wipe: paged slots never need one
    (module comment above)."""
    s = model.seq_len
    ps = pool_page_size(pool)
    p_max = table.shape[1]
    row_table = table[slot]                                      # [P_max]

    def leaf(x):
        v = x[row_table]                                         # [P, ps, ...]
        return v.reshape((p_max * ps,) + x.shape[2:])[:s][None]  # [1, S, ...]

    view = jax.tree_util.tree_map(leaf, pool)
    new_view = prefill_chunk(model, params, view, prompt[slot][None],
                             jnp.int32(0), start, length,
                             jnp.asarray(False), chunk=chunk)

    positions = start + jnp.arange(chunk, dtype=jnp.int32)       # [C]
    valid = (jnp.arange(chunk) < length) & (positions < s)
    safe_pos = jnp.clip(positions, 0, s - 1)
    page_of = row_table[safe_pos // ps]                          # [C]
    offs = safe_pos % ps

    def put(pool_leaf, view_leaf):
        rows = view_leaf[0, safe_pos]                            # [C, ...]
        pages = jnp.where(valid, page_of, pool_leaf.shape[0])    # OOB → drop
        return pool_leaf.at[pages, offs].set(rows, mode="drop")

    return jax.tree_util.tree_map(put, pool, new_view)


def paged_verify_chunk(model: TransformerLM, params, pool: dict,
                       table: jax.Array, ids: jax.Array, t: jax.Array,
                       draft: jax.Array, *, k: int
                       ) -> tuple[dict, jax.Array]:
    """``verify_chunk`` through a page table: full gather (verify reads every
    slot's cache, like decode), contiguous verify on the view, then a bulk
    ``[B, k+1]``-row scatter. Rows past ``seq_len`` drop; rows past a slot's
    reservation land in the null page — both rewritten-before-visible, same
    rollback argument as the contiguous docstring."""
    b = ids.shape[0]
    s = model.seq_len
    ps = pool_page_size(pool)
    w = k + 1
    view = _gather_view(pool, table, s)
    new_view, log_probs = verify_chunk(model, params, view, ids, t, draft, k=k)

    positions = t[:, None] + jnp.arange(w, dtype=jnp.int32)      # [B, W]
    safe_pos = jnp.clip(positions, 0, s - 1)
    in_range = positions < s
    page_of = jnp.take_along_axis(table, safe_pos // ps, axis=1)  # [B, W]
    offs = safe_pos % ps
    slot_idx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, w))

    def put(pool_leaf, view_leaf):
        rows = view_leaf[slot_idx, safe_pos]                     # [B, W, ...]
        pages = jnp.where(in_range, page_of, pool_leaf.shape[0])
        return pool_leaf.at[pages, offs].set(rows, mode="drop")

    new_pool = jax.tree_util.tree_map(put, pool, new_view)
    return new_pool, log_probs


def filter_logits(log_probs: jax.Array, *, top_k: int = 0,
                  top_p: float = 1.0) -> jax.Array:
    """Mask ``[..., V]`` logits outside the top-k set and/or the top-p nucleus.

    ``top_k = 0`` disables the k filter; ``top_p = 1.0`` disables the nucleus filter.
    The nucleus is the smallest prefix of the probability-sorted vocabulary whose
    mass reaches ``top_p`` (the argmax always survives). Filters compose — both masks
    apply when both are set. Input need not be normalized (temperature-scaled
    log-probs are fine); masked entries become ``MASK_VALUE`` so a downstream
    ``jax.random.categorical`` renormalizes over the survivors.
    """
    if top_k:
        kth = lax.top_k(log_probs, top_k)[0][..., -1:]
        log_probs = jnp.where(log_probs < kth, MASK_VALUE, log_probs)
    if top_p < 1.0:
        sorted_lp = jnp.sort(log_probs, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_lp, axis=-1)
        # Exclusive cumulative mass: position j is kept while the mass BEFORE it is
        # still < top_p, i.e. it is needed to reach the target mass. j=0 (the
        # argmax) is always kept.
        before = jnp.cumsum(probs, axis=-1) - probs
        kept = before < top_p
        # Value threshold = smallest kept sorted logit; ties at the threshold all
        # survive (harmless: they carry identical probability).
        thresh = jnp.min(jnp.where(kept, sorted_lp, jnp.inf), axis=-1,
                         keepdims=True)
        log_probs = jnp.where(log_probs < thresh, MASK_VALUE, log_probs)
    return log_probs


def generate(model: TransformerLM, params, rng: jax.Array, *, batch: int = 1,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
             prompt: jax.Array | None = None,
             prompt_len: int = 0) -> jax.Array:
    """Sample ``[batch, seq_len]`` token streams from BOS, autoregressively.

    ``temperature <= 0`` decodes greedily. ``top_k`` / ``top_p`` restrict sampling to
    the k most likely tokens / the smallest nucleus with ``top_p`` probability mass
    (applied AFTER temperature scaling, composing in that order — the common
    convention). The loop is ``ceil(S / DECODE_SEGMENT)`` ``lax.scan`` segments
    (wrap in ``jax.jit`` for repeated use); per-step work is the KV-cache
    ``decode_step`` reading a static prefix that grows per segment, so cost is
    O(S²·E) total instead of the O(S³·E) of re-running the full forward per
    position, and the dominant HBM term (the cache re-read) is O(t) amortized.

    ``prompt`` (``[batch, seq_len]`` token ids) with ``prompt_len = K`` conditions the
    sample: the first ``K`` output positions are teacher-forced to the prompt (their
    K/V still populate the cache), and positions ``K..S-1`` are sampled — e.g. digit
    COMPLETION from the top rows of a real image. ``prompt_len`` must be a Python int
    (it selects statically which scan steps force; the forced tokens themselves are
    traced data).
    """
    # Host (numpy) checkpoints decode too: numpy leaves can't be indexed by traced
    # token ids inside the scan.
    params = jax.tree_util.tree_map(jnp.asarray, params)
    if not 0 <= top_k <= model.vocab_size:
        raise ValueError(f"top_k {top_k} outside [0, {model.vocab_size}]")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p {top_p} outside (0, 1]")
    if prompt is None:
        prompt = jnp.zeros((batch, model.seq_len), jnp.int32)
        prompt_len = 0
    if not 0 <= prompt_len <= model.seq_len:
        raise ValueError(f"prompt_len {prompt_len} outside [0, {model.seq_len}]")
    if prompt.shape != (batch, model.seq_len):
        # Explicit: a [1, S] prompt with batch > 1 would silently broadcast one
        # forced prefix across the whole batch.
        raise ValueError(f"prompt shape {prompt.shape} != (batch, seq_len) = "
                         f"({batch}, {model.seq_len})")
    bos = jnp.full((batch,), model.vocab_size - 1, jnp.int32)

    def step(carry, scan_in, *, prefix_len):
        t, prompt_t = scan_in
        cache, ids_t, key = carry
        cache, log_probs = decode_step(model, params, cache, ids_t, t,
                                       prefix_len=prefix_len)
        # BOS is an input-only symbol (the tokenizer never produces it): mask its
        # logit so samples stay in the pixel vocabulary ids_to_images can invert.
        log_probs = log_probs.at[:, model.vocab_size - 1].set(MASK_VALUE)
        key, sub = jax.random.split(key)
        if temperature > 0:
            scaled = filter_logits(log_probs / temperature,
                                   top_k=top_k, top_p=top_p)
            nxt = jax.random.categorical(sub, scaled, axis=-1)
        else:
            nxt = jnp.argmax(log_probs, axis=-1)
        # Teacher-force the prompt region. The forced token conditions later steps
        # through the NEXT step's cache write (it becomes ids_t at t+1; decode_step
        # at t cached the PREVIOUS position's token).
        nxt = jnp.where(t < prompt_len, prompt_t, nxt).astype(jnp.int32)
        return (cache, nxt, key), nxt

    # Segmented scan: segment j's steps attend over a static prefix of
    # min((j+1)·DECODE_SEGMENT, S) cache rows instead of all S, so the dominant
    # decode HBM term (the per-step cache re-read) is O(t) amortized — ~2× less
    # traffic at S=784 — while every shape stays static (one compiled scan body
    # per segment, no dynamic control flow).
    positions = jnp.arange(model.seq_len, dtype=jnp.int32)
    prompt_cols = jnp.transpose(prompt.astype(jnp.int32))
    carry = (init_cache(model, batch), bos, rng)
    chunks = []
    for start in range(0, model.seq_len, DECODE_SEGMENT):
        stop = min(start + DECODE_SEGMENT, model.seq_len)
        carry, toks = lax.scan(
            functools.partial(step, prefix_len=stop), carry,
            (positions[start:stop], prompt_cols[start:stop]))
        chunks.append(toks)
    tokens = jnp.concatenate(chunks, axis=0)
    return jnp.transpose(tokens)          # [S, B] -> [B, S]
