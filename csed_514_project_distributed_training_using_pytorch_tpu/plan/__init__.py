"""Automatic parallelism planning: pick the mesh layout instead of knowing it.

The reference paper's result is a hand-made scaling curve; this repo grew six
parallel strategies a user composes by hand. ``plan/`` turns that choice into a
subsystem:

- ``costs.py``      — analytical per-step cost model (memory / FLOPs / per-axis
  collective bytes over ICI/DCN) for a model on an axis-shaped mesh;
- ``search.py``     — enumerate legal DP×FSDP×TP×PP factorizations of the
  device count, prune by per-chip HBM, rank by predicted step time;
- ``autotune.py``   — optional empirical re-rank: AOT-compile + short-trial the
  top-K candidates on the live devices;
- ``scenarios.py``  — per-trainer scenario builders + the trial harness;
- ``artifact.py``   — the serializable ``Plan`` JSON (inspect with
  ``tools/plan_report.py``, replay with ``--plan path.json``).

Trainer surface (``train/composed.py``, ``train/lm.py``)::

    --plan auto         # analytical pick
    --plan tune         # analytical top-K, re-ranked by measured step time
    --plan plan.json    # replay a saved/edited plan verbatim

``--plan`` omitted leaves the trainers bitwise-identical to before the planner
existed (pinned in ``tests/test_plan.py``).
"""

from __future__ import annotations

import dataclasses

import jax

from csed_514_project_distributed_training_using_pytorch_tpu.plan.artifact import (
    Plan,
)
from csed_514_project_distributed_training_using_pytorch_tpu.plan.costs import (
    Candidate, CostBreakdown, ModelStats, ServeCostBreakdown, ServeStats,
    Topology, predict, predict_serve,
)
from csed_514_project_distributed_training_using_pytorch_tpu.plan.search import (
    Ranked, Scenario, ServeRanked, ServeScenario, enumerate_candidates,
    enumerate_serve_candidates, search, search_serve,
)
from csed_514_project_distributed_training_using_pytorch_tpu.plan import (
    autotune, scenarios,
)

__all__ = [
    "Plan", "Candidate", "CostBreakdown", "ModelStats", "Topology", "Ranked",
    "Scenario", "predict", "enumerate_candidates", "search", "autotune",
    "scenarios", "resolve", "apply_plan", "AUTOTUNE_TOP_K",
    "ServeStats", "ServeCostBreakdown", "ServeScenario", "ServeRanked",
    "predict_serve", "enumerate_serve_candidates", "search_serve",
]

AUTOTUNE_TOP_K = 3


def _plan_from_ranked(scenario: Scenario, ranked: list[Ranked],
                      source: str) -> Plan:
    best = ranked[0]
    c = best.candidate
    return Plan(
        run_type=scenario.run_type, device_count=c.num_devices,
        mesh=c.mesh_spec(), axes=c.axes(), fsdp=c.fsdp,
        grad_accum=c.grad_accum, pipeline_microbatches=c.microbatches,
        source=source, predicted=best.costs.to_dict(),
        measured_step_s=best.measured_step_s,
        topology=scenario.topo.to_dict(), model=scenario.stats.to_dict(),
        global_batch=scenario.global_batch,
        candidates=[r.to_dict() for r in ranked])


def resolve(spec: str, scenario: Scenario, *, emit=None) -> Plan:
    """``--plan`` value → ``Plan``: ``"auto"`` searches the analytical model,
    ``"tune"`` additionally measures the top-K (degrading to ``auto`` on a
    multi-process fleet, where per-process wall clocks could rank differently
    on different hosts and desynchronize the SPMD mesh choice), anything else
    is a path to a saved artifact — validated against the live device count
    before the trainer builds a mesh from it."""
    if spec in ("auto", "tune"):
        ranked = search(scenario)
        source = spec
        if spec == "tune" and jax.process_count() > 1:
            from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
                metrics as M,
            )

            M.log("WARNING: --plan tune on a multi-process fleet would rank by "
                  "per-host wall clocks; degrading to the analytical 'auto' "
                  "ranking (identical on every process)")
            source = "auto"
        elif spec == "tune":
            ranked = autotune.refine(scenario, ranked, top_k=AUTOTUNE_TOP_K,
                                     emit=emit)
        return _plan_from_ranked(scenario, ranked, source)
    plan = Plan.load(spec)
    if plan.run_type != scenario.run_type:
        raise ValueError(
            f"plan {spec!r} was made for the {plan.run_type!r} trainer, not "
            f"{scenario.run_type!r} — regenerate with --plan auto")
    avail = scenario.topo.num_devices
    if plan.device_count > avail:
        raise ValueError(
            f"plan {spec!r} targets {plan.device_count} devices but only "
            f"{avail} are addressable — regenerate with --plan auto")
    return dataclasses.replace(plan, source="file")


def apply_plan(config, run_type: str, *, topo: Topology | None = None,
               emit=None):
    """Resolve ``config.plan`` and fold the pick back into the (frozen) trainer
    config. Returns ``(new_config, Plan)``; with ``config.plan`` empty the
    config object is returned untouched (the bitwise-identity contract).

    The plan artifact is saved to ``<results_dir>/plan_<run_type>.json``
    (process-0 gated, atomic) whenever it was computed here rather than loaded,
    so every ``--plan auto|tune`` run leaves a replayable record."""
    import os

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    if not config.plan:
        return config, None
    if run_type == "composed":
        scenario = scenarios.for_composed(config, topo)
    elif run_type == "lm":
        scenario = scenarios.for_lm(config, topo)
    else:
        raise ValueError(f"no planning scenario for run_type {run_type!r}")
    plan = resolve(config.plan, scenario, emit=emit)
    if plan.source != "file" and config.results_dir and M.is_logging_process():
        path = os.path.join(config.results_dir, f"plan_{run_type}.json")
        plan.save(path)
        M.log(f"Saved {path}")
    repl = {"mesh": plan.mesh, "grad_accum": plan.grad_accum}
    if run_type == "composed":
        repl["fsdp"] = plan.fsdp
        if plan.axes.get("stage", 1) > 1:
            repl["pipeline_microbatches"] = plan.pipeline_microbatches
    M.log(f"Plan ({plan.source}): mesh {plan.mesh}"
          + (", fsdp" if plan.fsdp else "")
          + f", grad_accum {plan.grad_accum}"
          + (f", microbatches {plan.pipeline_microbatches}"
             if plan.axes.get("stage", 1) > 1 else "")
          + f" — predicted step "
          + (f"{plan.predicted.get('step_s', 0) * 1e3:.3f} ms"
             if plan.predicted else "n/a")
          + (f", measured {plan.measured_step_s * 1e3:.3f} ms"
             if plan.measured_step_s else ""))
    return dataclasses.replace(config, **repl), plan
