"""Analytical per-step cost model: one parallel layout → predicted time + memory.

The reference picked its parallel layout by hand and measured the result (the
time-vs-machines curve is the paper's whole finding); our repo grew six
strategies a user composes by hand per model and chip count. This module is the
arithmetic that replaces that tribal knowledge: given a model's static stats, a
topology, and one candidate DP×FSDP×TP×PP factorization, it prices

- **memory** — param / optimizer / gradient / activation bytes per chip under the
  candidate's sharding (the HBM-feasibility gate ``plan/search.py`` prunes by);
- **compute** — train FLOPs per optimizer step over the chips' aggregate peak,
  inflated by the GPipe bubble ``(M+S-1)/M`` when a stage axis is present;
- **collectives** — per-axis bytes over per-link bandwidths: the once-per-step DP
  gradient ring all-reduce, Megatron TP's per-layer activation all-reduces, PP's
  stage-boundary sends — each routed over ICI or DCN by whether the axis spans
  granules (``Topology.num_slices``).

Everything is a closed-form estimate of a DELIBERATELY simple machine model
(no compute/comm overlap, ring collectives at ``2(n-1)/n`` efficiency, uniform
per-link bandwidth); DESIGN.md §13 states the assumptions and when to trust the
analytical ranking vs the ``plan/autotune.py`` empirical refinement. The model's
job is ranking candidates, not forecasting wall clocks — predicted-vs-measured
deltas are first-class output (``tools/plan_report.py``) precisely so the model
is falsifiable.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

# Nominal per-link, one-direction interconnect bandwidths (bytes/s) by
# device_kind substring — public-spec-order-of-magnitude values, NOT
# measurements (first match wins; more specific kinds precede their prefixes).
# They only ever rank layouts against each other; `tools/plan_report.py` renders
# predicted-vs-measured deltas so a wrong entry is visible, and `--plan tune`
# re-ranks by measurement.
ICI_BYTES_BY_KIND = [
    ("v6", 9.0e10), ("v5p", 9.0e10), ("v5", 4.5e10), ("v4", 4.5e10),
    ("v3", 7.0e10), ("v2", 4.0e10),
]
DEFAULT_ICI_BYTES = 1.0e10    # unknown kind / CPU test platform: deterministic
DEFAULT_DCN_BYTES = 3.125e9   # ~25 Gbit/s per chip across slices/hosts

# Per-pass host/dispatch overhead (seconds) charged to every extra microbatch
# (grad-accum pass or pipeline tick). Small by design: its role is to break
# ties AGAINST gratuitous microbatching when memory doesn't demand it, not to
# model real dispatch cost.
MICROBATCH_OVERHEAD_S = 50e-6


def ici_bytes_per_s(device_kind: str) -> float:
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        lookup_by_kind,
    )

    return lookup_by_kind(ICI_BYTES_BY_KIND, device_kind, DEFAULT_ICI_BYTES)


@dataclass(frozen=True)
class Topology:
    """The hardware facts one candidate is priced against. Constructed from the
    live runtime via ``detect()`` or stubbed outright in tests/synthetic
    scenarios — every field is plain data, nothing touches jax after
    construction."""

    num_devices: int
    device_kind: str = "cpu"
    hbm_bytes: float = 16 << 30        # usable accelerator memory per chip
    hbm_source: str = "nominal"        # env | runtime | spec | nominal
    peak_flops: float = 1e12           # per chip (bf16 peak on TPU)
    ici_bytes: float = DEFAULT_ICI_BYTES   # per-link one-way bytes/s
    dcn_bytes: float = DEFAULT_DCN_BYTES   # per-chip cross-granule bytes/s
    num_slices: int = 1                # DCN granules (slices, else hosts)

    @classmethod
    def detect(cls, devices=None) -> "Topology":
        """Snapshot the live platform (``parallel.mesh.topology_summary``) plus
        the committed per-kind bandwidth/peak tables."""
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
            topology_summary,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
            peak_flops,
        )

        t = topology_summary(devices)
        return cls(
            num_devices=t["device_count"],
            device_kind=t["device_kind"],
            hbm_bytes=float(t["hbm_bytes"]),
            hbm_source=t["hbm_source"],
            peak_flops=peak_flops(t["device_kind"]) or 1e12,
            ici_bytes=ici_bytes_per_s(t["device_kind"]),
            dcn_bytes=DEFAULT_DCN_BYTES,
            num_slices=t["num_granules"],
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class ModelStats:
    """Static per-model quantities the cost model consumes.

    ``flops_per_example`` is TRAIN FLOPs (fwd + backward ≈ 3× fwd).
    ``act_bytes_per_layer_per_example`` is the resident activation footprint of
    one layer for one example (the remat knob halves what must persist — callers
    bake that in); ``score_bytes_per_example`` the dense-attention ``[H, S, S]``
    score tile (0 when a flash/streaming core is used). ``shardable_fraction``
    is the fraction of parameter bytes Megatron TP actually splits (block
    kernels; embeddings/LN/head replicate)."""

    name: str
    param_bytes: float
    flops_per_example: float
    num_layers: int = 1
    num_heads: int = 1
    seq_len: int = 1
    embed_dim: int = 1
    dtype_bytes: int = 4
    act_bytes_per_layer_per_example: float = 0.0
    score_bytes_per_example: float = 0.0
    optimizer_mult: float = 1.0        # extra state as a multiple of params
                                       # (SGD velocity 1, AdamW 2; +1 with EMA)
    shardable_fraction: float = 1.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class Candidate:
    """One point of the DP×FSDP×TP×PP search space: mesh axis sizes plus the
    microbatch split. ``data·model·stage`` must equal the device count the
    search ran at; ``microbatches`` is the GPipe split (stage>1 only) and
    ``grad_accum`` the gradient-accumulation split (activation-memory knob)."""

    data: int = 1
    model: int = 1
    stage: int = 1
    fsdp: bool = False
    grad_accum: int = 1
    microbatches: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.model * self.stage

    def mesh_spec(self) -> str:
        """The trainer-facing ``--mesh`` string. The data axis always appears
        (every trainer accepts ``data=1``, and the LM trainer requires the axis
        to exist); model/stage axes of size 1 are elided."""
        parts = [("data", self.data)] + [
            (n, s) for n, s in (("model", self.model), ("stage", self.stage))
            if s > 1]
        return ",".join(f"{n}={s}" for n, s in parts)

    def axes(self) -> dict:
        return {"data": self.data, "model": self.model, "stage": self.stage}

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class CostBreakdown:
    """The priced candidate: per-phase seconds, per-chip bytes, feasibility."""

    compute_s: float
    bubble_s: float
    dp_comm_s: float
    tp_comm_s: float
    pp_comm_s: float
    overhead_s: float
    step_s: float                  # the ranking key: sum of the above
    param_bytes_per_chip: float
    opt_bytes_per_chip: float
    grad_bytes_per_chip: float
    act_bytes_per_chip: float
    total_bytes_per_chip: float
    hbm_budget_bytes: float
    fits: bool

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# Nominal per-chip HBM streaming bandwidth (bytes/s) by device_kind substring —
# the serving cost model's denominator (batched decode is memory-bound: every
# step re-reads the params and the resident KV planes). Same contract as the
# ICI table: ranking-only nominal values, falsified by measured tokens/s.
HBM_BYTES_BY_KIND = [
    ("v6", 1.6e12), ("v5p", 2.8e12), ("v5", 8.2e11), ("v4", 1.2e12),
    ("v3", 9.0e11), ("v2", 7.0e11),
]
DEFAULT_HBM_BYTES = 5.0e10    # unknown kind / CPU test platform: deterministic


def hbm_bytes_per_s(device_kind: str) -> float:
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        lookup_by_kind,
    )

    return lookup_by_kind(HBM_BYTES_BY_KIND, device_kind, DEFAULT_HBM_BYTES)


def _ring_time(nbytes: float, participants: int, link_bytes_per_s: float) -> float:
    """Ring all-reduce wall time for ``nbytes`` of payload per participant:
    ``2(n-1)/n`` traversals of the payload over one link's bandwidth (the
    standard bandwidth-optimal schedule; latency terms ignored)."""
    if participants <= 1 or nbytes <= 0:
        return 0.0
    return 2.0 * (participants - 1) / participants * nbytes / link_bytes_per_s


def _split_axis_over_dcn(axis_size: int, num_slices: int) -> tuple[int, int]:
    """Decompose an axis that spans granules into (dcn_factor, ici_factor) —
    the hybrid-mesh layout (``make_hybrid_mesh``): the LEADING factor strides
    slices, the remainder stays inside one. Axes that don't divide the granule
    count keep everything on the slower network (conservative)."""
    if num_slices <= 1:
        return 1, axis_size
    if axis_size % num_slices == 0:
        return num_slices, axis_size // num_slices
    return axis_size, 1


def predict(stats: ModelStats, topo: Topology, cand: Candidate, *,
            global_batch: int, hbm_fraction: float = 0.9) -> CostBreakdown:
    """Price one candidate layout: per-step seconds by phase + per-chip bytes.

    Machine-model assumptions (DESIGN.md §13): no compute/comm overlap (phases
    sum), ring collectives at ``2(n-1)/n``, the data axis is the one that spans
    DCN granules when granules exist (the hybrid-mesh recipe — model/stage
    crossing DCN is priced at DCN bandwidth as a deliberate penalty), gradients
    materialize one full shard alongside params, and TP shards activations and
    the dense score tile evenly."""
    d, m, s = cand.data, cand.model, cand.stage
    n = cand.num_devices

    # ---- memory (bytes per chip) -------------------------------------------
    # TP only splits the shardable fraction; PP/FSDP split everything they see.
    tp_sharded = (stats.param_bytes * stats.shardable_fraction / m
                  + stats.param_bytes * (1.0 - stats.shardable_fraction))
    param_pc = tp_sharded / (s * (d if cand.fsdp else 1))
    opt_pc = param_pc * stats.optimizer_mult
    grad_pc = param_pc                       # one transient grad shard
    micro = global_batch / (cand.grad_accum * cand.microbatches)
    # GPipe keeps EVERY microbatch's forward activations resident until its
    # backward — all M are in flight through the fill — so a stage split does
    # not shrink activation memory with M (only grad_accum does); modeling one
    # microbatch would let the bubble term steer the pick toward high-M
    # layouts the feasibility gate then under-counts 16×.
    inflight = cand.microbatches if s > 1 else 1
    micro_pc = micro * inflight / d          # examples resident per chip
    layers_pc = max(stats.num_layers / s, 1.0)
    act_pc = (micro_pc * layers_pc * stats.act_bytes_per_layer_per_example / m
              + micro_pc * stats.score_bytes_per_example / m)
    total_pc = param_pc + opt_pc + grad_pc + act_pc
    # The budget keeps ``1 - hbm_fraction`` headroom for what the model doesn't
    # count (compiler scratch, the replicated dataset, fragmentation).
    budget = topo.hbm_bytes * hbm_fraction

    # ---- compute ------------------------------------------------------------
    flops_step = stats.flops_per_example * global_batch
    compute_s = flops_step / (n * topo.peak_flops)
    bubble_s = 0.0
    if s > 1:
        # GPipe fill/drain: the stage pipeline runs M+S-1 ticks for M microbatch
        # ticks of useful work — charged per accumulation pass.
        bubble_s = compute_s * (s - 1) / cand.microbatches

    # ---- collectives --------------------------------------------------------
    # DP gradient all-reduce: once per step, one grad shard's bytes, split
    # hierarchically when the data axis spans DCN granules.
    grad_bytes = tp_sharded / s
    dcn_d, ici_d = _split_axis_over_dcn(d, topo.num_slices)
    dp_comm_s = (_ring_time(grad_bytes, ici_d, topo.ici_bytes)
                 + _ring_time(grad_bytes / max(ici_d, 1), dcn_d, topo.dcn_bytes))
    if cand.fsdp:
        # ZeRO adds a params all-gather per accumulation pass on top of the
        # grad reduce-scatter+all-gather (≙ the all-reduce above): same ring
        # volume again, times the extra passes.
        dp_comm_s *= 1.0 + 0.5 * cand.grad_accum

    # TP: Megatron inserts ~4 activation all-reduces per layer per pass
    # (fwd row-parallel + its backward, ×2 for attention + MLP); total volume
    # over the step covers the full batch regardless of the accum split. Any
    # model/stage axis is assumed inside one granule (ICI); if granules exist
    # and data can't absorb them, these axes pay DCN bandwidth.
    intra_bw = (topo.ici_bytes if topo.num_slices <= 1
                or _split_axis_over_dcn(d, topo.num_slices)[0] == topo.num_slices
                else topo.dcn_bytes)
    act_bytes_step = (global_batch / d) * stats.seq_len * stats.embed_dim \
        * stats.dtype_bytes
    tp_comm_s = (4 * stats.num_layers * _ring_time(act_bytes_step, m, intra_bw)
                 if m > 1 else 0.0)

    # PP: each microbatch's activations cross S-1 stage boundaries forward and
    # backward — point-to-point, one payload traversal each.
    pp_comm_s = (2 * (s - 1) * act_bytes_step / intra_bw if s > 1 else 0.0)

    overhead_s = MICROBATCH_OVERHEAD_S * (
        cand.grad_accum * cand.microbatches - 1)

    step_s = compute_s + bubble_s + dp_comm_s + tp_comm_s + pp_comm_s + overhead_s
    return CostBreakdown(
        compute_s=compute_s, bubble_s=bubble_s, dp_comm_s=dp_comm_s,
        tp_comm_s=tp_comm_s, pp_comm_s=pp_comm_s, overhead_s=overhead_s,
        step_s=step_s,
        param_bytes_per_chip=param_pc, opt_bytes_per_chip=opt_pc,
        grad_bytes_per_chip=grad_pc, act_bytes_per_chip=act_pc,
        total_bytes_per_chip=total_pc, hbm_budget_bytes=budget,
        fits=total_pc <= budget)


# =========================================================================================
# Serving: price a TP×(slot-DP) replica mesh (serving/shard.py) — the decode
# regime is the inverse of training: no optimizer/grad state, memory-BOUND
# steps (every decode step re-reads params + resident KV), and the objective
# is tokens/s and admissible slots under the HBM budget and a TTFT SLO.
# =========================================================================================


@dataclass(frozen=True)
class ServeStats:
    """Static per-model serving quantities (built exactly, via ``jax.eval_shape``
    over the model's init and ``models.lm.init_cache``, by
    ``plan.scenarios.for_serve`` — no hand formulas to drift).

    ``kv_bytes_per_slot`` is ONE slot's full cache planes across all layers
    (narrow K/V plus any scale planes — the int8 layout prices itself);
    ``prompt_bytes_per_slot`` the engine's per-slot host-prompt row.
    ``flops_per_token`` is the decode forward for one token (2·params plus the
    attention einsums); ``shardable_fraction`` the parameter bytes
    ``tensor_parallel.param_partition_specs`` actually splits over heads."""

    name: str
    param_bytes: float
    kv_bytes_per_slot: float
    prompt_bytes_per_slot: float = 0.0
    flops_per_token: float = 0.0
    num_layers: int = 1
    num_heads: int = 1
    num_kv_heads: int = 1
    seq_len: int = 1
    embed_dim: int = 1
    dtype_bytes: int = 4
    shardable_fraction: float = 1.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class ServeCostBreakdown:
    """One priced serve mesh: per-chip residency, the decode-step roofline,
    the prefill-derived TTFT estimate, and both feasibility gates."""

    decode_step_s: float           # one token for every slot of the replica
    decode_mem_s: float            # HBM-stream term (usually the binding one)
    decode_compute_s: float        # FLOPs term
    tp_comm_s: float               # per-step TP activation collectives
    ttft_s: float                  # prefill of one prompt_len prompt
    tokens_per_s: float            # num_slots / decode_step_s — the objective
    params_bytes_per_chip: float
    kv_bytes_per_chip: float
    total_bytes_per_chip: float
    hbm_budget_bytes: float
    slots_at_budget: int           # max admissible slots under the budget
    fits: bool                     # per-chip residency within the budget
    meets_ttft: bool               # TTFT estimate within the SLO (True if none)

    @property
    def feasible(self) -> bool:
        return self.fits and self.meets_ttft

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["feasible"] = self.feasible
        return d


def predict_serve(stats: ServeStats, topo: Topology, *, tp: int, dp: int,
                  num_slots: int, prompt_len: int,
                  ttft_slo_s: float | None = None,
                  hbm_fraction: float = 0.9,
                  kv_layout: str = "contiguous", page_size: int = 64,
                  context_tokens: int | None = None) -> ServeCostBreakdown:
    """Price one TP×(slot-DP) serve mesh.

    Residency follows ``serving/shard.py``'s byte-true accounting exactly:
    params replicate their unshardable fraction and split the shardable one
    over ``tp``; a dp group holds ``num_slots/dp`` slots whose KV planes split
    over ``tp`` (heads axis); the host-prompt rows shard over slots only. The
    decode step is a roofline — ``max(HBM stream, FLOPs)`` of one token for
    every resident slot — plus Megatron-style per-layer TP all-reduces of the
    step's activations. TTFT is the compute-bound prefill of one
    ``prompt_len`` prompt on one dp group (slot-DP doesn't speed up a single
    request — exactly why the disaggregated prefill tier exists).

    ``kv_layout="paged"`` prices page-pool residency instead of whole-context
    planes: a slot serving ``context_tokens`` (default: the full ``seq_len`` —
    the conservative pin) holds ``pages_for(context, page_size)`` pages, so
    both the per-slot HBM charge and the decode step's KV stream shrink to the
    page span actually reserved (the fused kernel's dead-page fetch elision
    makes the stream term real, not aspirational). The page-count formula is
    ``serving.pagepool.pages_for`` — the engine's own reservation math — and
    the contiguous default leaves every number bitwise unchanged."""
    if kv_layout not in ("contiguous", "paged"):
        raise ValueError(f"unknown kv_layout {kv_layout!r} "
                         f"(want 'contiguous' or 'paged')")
    kv_bytes_slot = stats.kv_bytes_per_slot
    if kv_layout == "paged":
        from csed_514_project_distributed_training_using_pytorch_tpu.serving.pagepool import (
            pages_for,
        )

        ctx = min(int(context_tokens or stats.seq_len), stats.seq_len)
        ps = max(1, min(int(page_size), stats.seq_len))
        kv_bytes_slot = (stats.kv_bytes_per_slot / max(stats.seq_len, 1)
                         * pages_for(ctx, ps) * ps)
    group_slots = max(num_slots // max(dp, 1), 1)
    params_pc = (stats.param_bytes * stats.shardable_fraction / tp
                 + stats.param_bytes * (1.0 - stats.shardable_fraction))
    kv_slot_pc = kv_bytes_slot / tp
    kv_pc = kv_slot_pc * group_slots
    prompt_pc = stats.prompt_bytes_per_slot * group_slots
    total_pc = params_pc + kv_pc + prompt_pc
    budget = topo.hbm_bytes * hbm_fraction
    slot_cost = max(kv_slot_pc + stats.prompt_bytes_per_slot, 1.0)
    slots_at_budget = max(dp, 1) * int(max(budget - params_pc, 0.0) // slot_cost)

    hbm_bw = hbm_bytes_per_s(topo.device_kind)
    # One decode step streams the param shard once (batched over the group's
    # slots) and each slot's resident KV once.
    decode_mem_s = (params_pc + kv_pc) / hbm_bw
    decode_compute_s = stats.flops_per_token * group_slots / (tp * topo.peak_flops)
    # Two all-reduces per layer per step (attention out-proj + MLP row-parallel)
    # over the step's [group_slots, embed] activations.
    step_act_bytes = group_slots * stats.embed_dim * stats.dtype_bytes
    tp_comm_s = (2 * stats.num_layers * _ring_time(step_act_bytes, tp,
                                                   topo.ici_bytes)
                 if tp > 1 else 0.0)
    decode_step_s = max(decode_mem_s, decode_compute_s) + tp_comm_s
    tokens_per_s = (num_slots / decode_step_s) if decode_step_s > 0 else 0.0

    # TTFT: prefill is compute-bound (the whole prompt's forward in chunks),
    # parallel over tp only, plus the same per-layer collectives over the
    # prompt's activations.
    prefill_act_bytes = prompt_len * stats.embed_dim * stats.dtype_bytes
    ttft_s = (stats.flops_per_token * prompt_len / (tp * topo.peak_flops)
              + (2 * stats.num_layers * _ring_time(prefill_act_bytes, tp,
                                                   topo.ici_bytes)
                 if tp > 1 else 0.0))
    return ServeCostBreakdown(
        decode_step_s=decode_step_s, decode_mem_s=decode_mem_s,
        decode_compute_s=decode_compute_s, tp_comm_s=tp_comm_s,
        ttft_s=ttft_s, tokens_per_s=tokens_per_s,
        params_bytes_per_chip=params_pc, kv_bytes_per_chip=kv_pc,
        total_bytes_per_chip=total_pc, hbm_budget_bytes=budget,
        slots_at_budget=slots_at_budget,
        fits=total_pc <= budget,
        meets_ttft=(ttft_slo_s is None or ttft_s <= ttft_slo_s))
