"""Scenario builders: one per trainer surface, plus the empirical trial harness.

A ``Scenario`` (``plan/search.py``) is the bridge between a trainer's config and
the abstract cost model: it pins the model's static stats (param bytes counted
EXACTLY via ``jax.eval_shape`` — no hand-maintained formulas to drift; the TP
shardable fraction comes from ``tensor_parallel.param_partition_specs`` itself,
so the planner and the trainer can never disagree about what TP splits), the
live topology, the batch, and which axes the trainer can legally execute.

The trial harness (``--plan tune``) builds, per candidate, the SAME scanned
epoch program shape the trainer runs — ``make_epoch_fn`` under the candidate's
TP/FSDP shardings on a real mesh — over a synthetic two-step index plan,
AOT-compiles it through ``utils.telemetry.aot_compile`` (compile seconds +
``cost_analysis`` FLOPs ride along), and times the steps closed by a
data-dependent host fetch of the final loss (the honest-sync protocol of
``utils/benchmarks.py``). Stage candidates return None (analytical estimate
retained): a pipeline trial would duplicate half the composed trainer for a
layout the cost model already prices conservatively via the bubble term.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.plan.costs import (
    Candidate, ModelStats, Topology,
)
from csed_514_project_distributed_training_using_pytorch_tpu.plan.search import (
    Scenario,
)

TRIAL_STEPS = 2          # steps per trial program (one scan)
TRIAL_REPS = 2           # timed invocations; the minimum is reported

# MNIST geometry the trainers are hard-wired to (data/mnist.py).
_IMAGE_SHAPE = (28, 28, 1)
_LM_SEQ_LEN = 28 * 28


def _param_bytes(model, sample, *init_extra) -> tuple[float, float]:
    """(total param bytes, TP-shardable bytes) from abstract init shapes —
    no FLOPs spent, and the shardable set comes from the one owner of the TP
    rules (``parallel.tensor_parallel.param_partition_specs``)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        tensor_parallel as tp,
    )

    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0), sample,
                            *init_extra)["params"]
    specs = tp.param_partition_specs(shapes)
    total = sharded = 0.0
    for leaf, spec in zip(jax.tree_util.tree_leaves(shapes),
                          jax.tree_util.tree_leaves(
                              specs, is_leaf=lambda x: isinstance(x, P))):
        nbytes = float(np.prod(leaf.shape)) * leaf.dtype.itemsize
        total += nbytes
        if any(e is not None for e in spec):
            sharded += nbytes
    return total, (sharded / total if total else 0.0)


def _optimizer_mult(optimizer: str, ema: bool) -> float:
    return (2.0 if optimizer == "adamw" else 1.0) + (1.0 if ema else 0.0)


def _transformer_stats(name, model, sample, *, seq_len, embed_dim, num_layers,
                       num_heads, mlp_ratio, dtype_bytes, remat, flash,
                       optimizer_mult) -> ModelStats:
    param_bytes, shardable = _param_bytes(model, sample)
    # Train FLOPs per example: the 6·P·S matmul rule plus the attention
    # score/value einsums (4·S²·E fwd), tripled for backward.
    fwd = 2.0 * param_bytes / 4 * seq_len + 4.0 * num_layers * seq_len ** 2 \
        * embed_dim
    # Resident activations per layer per example: the block's intermediate
    # streams (~attn qkv/out + the mlp_ratio-wide MLP) — an order-of-magnitude
    # constant, halved to block inputs under remat.
    act = seq_len * embed_dim * dtype_bytes * (2 if remat
                                               else 10 + 2 * mlp_ratio)
    score = 0.0 if flash else num_heads * seq_len ** 2 * 4.0
    return ModelStats(
        name=name, param_bytes=param_bytes, flops_per_example=3.0 * fwd,
        num_layers=num_layers, num_heads=num_heads, seq_len=seq_len,
        embed_dim=embed_dim, dtype_bytes=dtype_bytes,
        act_bytes_per_layer_per_example=act, score_bytes_per_example=score,
        optimizer_mult=optimizer_mult, shardable_fraction=shardable)


# --------------------------------------------------------------- trial harness


def _mesh_for(cand: Candidate):
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
        make_mesh,
    )

    names = ["data"] + [n for n, s in (("model", cand.model),
                                       ("stage", cand.stage)) if s > 1]
    sizes = [cand.data] + [s for s in (cand.model, cand.stage) if s > 1]
    return make_mesh(cand.num_devices, axis_names=tuple(names),
                     axis_shape=tuple(sizes))


def _time_epoch_program(cand: Candidate, mesh, state, epoch_body, xs, ys,
                        global_batch: int) -> dict | None:
    """AOT-compile the candidate's epoch program under its shardings and time
    ``TRIAL_STEPS`` scanned steps, closed by a host fetch of the loss vector
    (data-dependent on the final parameter update — the sync rule
    ``utils/benchmarks.py`` documents)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        data_parallel as dp,
        fsdp,
        tensor_parallel as tp,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    rep = dp.replicated(mesh)
    state_sh = (fsdp.hybrid_state_shardings(mesh, state) if cand.fsdp
                else tp.state_shardings(mesh, state))
    idx_sh = (NamedSharding(mesh, P(None, "data")) if cand.data > 1 else rep)
    jfn = jax.jit(epoch_body,
                  in_shardings=(state_sh, rep, rep, idx_sh, rep),
                  out_shardings=(state_sh, rep), donate_argnums=(0,))
    dstate = jax.device_put(state, state_sh)
    xs_d = dp.put_global(mesh, xs, P())
    ys_d = dp.put_global(mesh, ys, P())
    plan = dp.put_global(
        mesh, np.zeros((TRIAL_STEPS, global_batch), np.int32),
        P(None, "data") if cand.data > 1 else P())
    rng = jax.random.PRNGKey(0)
    compiled, aot = T.aot_compile(jfn, dstate, xs_d, ys_d, plan, rng)
    if compiled is None:
        return None
    # Warmup (fault-in, cache), then time TRIAL_REPS invocations threading the
    # donated state; the min absorbs host jitter on a 2-step program.
    dstate, losses = compiled(dstate, xs_d, ys_d, plan, rng)
    float(np.asarray(jax.device_get(losses)).mean())
    best = None
    for _ in range(TRIAL_REPS):
        t0 = time.perf_counter()
        dstate, losses = compiled(dstate, xs_d, ys_d, plan, rng)
        float(np.asarray(jax.device_get(losses)).mean())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    flops = aot["flops"] / TRIAL_STEPS if aot.get("flops") else None
    return {"step_s": best / TRIAL_STEPS,
            "compile_s": aot["lower_s"] + aot["compile_s"],
            "flops_per_step": flops}


def _classifier_trial(config):
    """Trial builder for the composed trainer's (non-stage) candidates."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.ops import optim
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state, make_epoch_fn,
    )

    def trial(cand: Candidate) -> dict | None:
        if cand.stage > 1:
            return None          # analytical estimate retained (module doc)
        mesh = _mesh_for(cand)
        model = TransformerClassifier(
            seq_len=config.seq_len, dropout_rate=0.0, causal=config.causal,
            dtype=jnp.bfloat16 if config.bf16 else jnp.float32,
            remat=config.remat, remat_policy=config.remat_policy)
        optimizer = optim.make_optimizer(config.optimizer,
                                         learning_rate=config.learning_rate,
                                         momentum=config.momentum,
                                         weight_decay=config.weight_decay)
        state = create_train_state(model, jax.random.PRNGKey(0),
                                   optimizer=optimizer,
                                   ema=config.ema_decay > 0)
        epoch_body = make_epoch_fn(model, learning_rate=config.learning_rate,
                                   momentum=config.momentum,
                                   grad_accum=cand.grad_accum,
                                   optimizer=optimizer,
                                   ema_decay=config.ema_decay)
        xs = np.zeros((config.batch_size,) + _IMAGE_SHAPE, np.float32)
        ys = np.zeros(config.batch_size, np.int32)
        return _time_epoch_program(cand, mesh, state, epoch_body, xs, ys,
                                   config.batch_size)

    return trial


def _lm_trial(config):
    """Trial builder for the LM trainer's candidates (data × model axes)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm as lm_mod,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.ops import optim
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state, make_epoch_from_step, make_train_step,
    )

    def trial(cand: Candidate) -> dict | None:
        if cand.stage > 1 or cand.fsdp:
            return None
        mesh = _mesh_for(cand)
        model = lm_mod.TransformerLM(
            vocab_size=config.num_levels + 1, seq_len=_LM_SEQ_LEN,
            embed_dim=config.embed_dim, num_layers=config.num_layers,
            num_heads=config.num_heads, dropout_rate=0.0,
            num_kv_heads=config.kv_heads or None, rope=config.rope,
            dtype=jnp.bfloat16 if config.bf16 else jnp.float32,
            remat=config.remat, remat_policy=config.remat_policy)
        optimizer = optim.make_optimizer(config.optimizer,
                                         learning_rate=config.learning_rate,
                                         momentum=config.momentum,
                                         weight_decay=config.weight_decay)
        state = create_train_state(model, jax.random.PRNGKey(0),
                                   sample_input_shape=(1, _LM_SEQ_LEN),
                                   optimizer=optimizer,
                                   ema=config.ema_decay > 0)

        def lm_loss(params, xs, ys, rng):
            del ys
            return lm_mod.next_token_loss(model, params, xs, rng,
                                          deterministic=True)

        step_fn = make_train_step(model, learning_rate=config.learning_rate,
                                  momentum=config.momentum,
                                  grad_accum=cand.grad_accum,
                                  optimizer=optimizer,
                                  ema_decay=config.ema_decay, loss_fn=lm_loss)
        epoch_body = make_epoch_from_step(step_fn)
        xs = np.zeros((config.batch_size, _LM_SEQ_LEN), np.int32)
        ys = np.zeros(config.batch_size, np.int32)
        return _time_epoch_program(cand, mesh, state, epoch_body, xs, ys,
                                   config.batch_size)

    return trial


# ------------------------------------------------------------------- builders


def for_composed(config, topo: Topology | None = None) -> Scenario:
    """Scenario for ``train/composed.py``: DP × FSDP × TP × PP over the fixed
    ``TransformerClassifier`` architecture. The stage axis is only offered when
    the config composes with it (the trainer rejects stage + remat/dropout/
    flash/zigzag/sharded-checkpoint up front — an emitted plan must pass those
    same guards)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )

    if topo is None:
        topo = Topology.detect()
    model = TransformerClassifier(seq_len=config.seq_len, dropout_rate=0.0)
    stats = _transformer_stats(
        "transformer_classifier", model,
        jnp.zeros((1,) + _IMAGE_SHAPE, jnp.float32),
        seq_len=config.seq_len, embed_dim=model.embed_dim,
        num_layers=model.num_layers, num_heads=model.num_heads,
        mlp_ratio=model.mlp_ratio,
        dtype_bytes=2 if config.bf16 else 4, remat=config.remat,
        flash=config.flash_attention,
        optimizer_mult=_optimizer_mult(config.optimizer,
                                       config.ema_decay > 0))
    axes = ["data", "model"]
    if not (config.remat or config.dropout_rate or config.zigzag_attention
            or config.flash_attention or config.sharded_checkpoint):
        axes.append("stage")
    return Scenario(run_type="composed", stats=stats, topo=topo,
                    global_batch=config.batch_size, axes=tuple(axes),
                    allow_fsdp=True, allow_grad_accum=True,
                    fixed_grad_accum=config.grad_accum,
                    test_batch=config.batch_size_test,
                    trial=_classifier_trial(config))


def for_lm(config, topo: Topology | None = None) -> Scenario:
    """Scenario for ``train/lm.py``: DP × TP over the configured
    ``TransformerLM`` (the LM trainer's mesh supports data/seq/model; the
    planner searches data/model — a seq axis is a context-length decision, not
    a throughput one)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm as lm_mod,
    )

    if topo is None:
        topo = Topology.detect()
    model = lm_mod.TransformerLM(
        vocab_size=config.num_levels + 1, seq_len=_LM_SEQ_LEN,
        embed_dim=config.embed_dim, num_layers=config.num_layers,
        num_heads=config.num_heads, dropout_rate=0.0,
        num_kv_heads=config.kv_heads or None)
    stats = _transformer_stats(
        "transformer_lm", model,
        jnp.zeros((1, _LM_SEQ_LEN), jnp.int32),
        seq_len=_LM_SEQ_LEN, embed_dim=config.embed_dim,
        num_layers=config.num_layers, num_heads=config.num_heads, mlp_ratio=4,
        dtype_bytes=2 if config.bf16 else 4, remat=config.remat, flash=False,
        optimizer_mult=_optimizer_mult(config.optimizer,
                                       config.ema_decay > 0))
    return Scenario(run_type="lm", stats=stats, topo=topo,
                    global_batch=config.batch_size, axes=("data", "model"),
                    allow_fsdp=False, allow_grad_accum=True,
                    fixed_grad_accum=config.grad_accum, trial=_lm_trial(config))


def for_cnn(global_batch: int, topo: Topology | None = None) -> Scenario:
    """Scenario for the reference CNN under plain DP — what ``bench_scaling.py
    --plan`` validates the cost model's predictions against (the paper's own
    time-vs-machines protocol)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import (
        Net,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        TRAIN_FLOPS_PER_EXAMPLE,
    )

    if topo is None:
        topo = Topology.detect()
    param_bytes, _ = _param_bytes(
        Net(), jnp.zeros((1,) + _IMAGE_SHAPE, jnp.float32))
    # Conv feature maps per example (f32): 24·24·10 + 12·12·10 + 8·8·20 + 4·4·20
    # + the dense tails — ~36 KB; one "layer" since the planner can't split it.
    stats = ModelStats(
        name="mnist_cnn", param_bytes=param_bytes,
        flops_per_example=float(TRAIN_FLOPS_PER_EXAMPLE), num_layers=1,
        act_bytes_per_layer_per_example=36e3, optimizer_mult=1.0,
        shardable_fraction=0.0)
    return Scenario(run_type="cnn", stats=stats, topo=topo,
                    global_batch=global_batch, axes=("data",),
                    allow_fsdp=False, allow_grad_accum=False)


def for_serve(model, *, num_slots: int, prompt_len: int,
              topo: Topology | None = None, ttft_slo_s: float | None = None,
              kv_dtype: str | None = None, hbm_fraction: float = 0.9,
              measure=None) -> "ServeScenario":
    """ServeScenario for a ``TransformerLM`` behind the continuous-batching
    engine: param bytes (and the TP-shardable fraction) counted exactly via
    ``jax.eval_shape`` over the model's init, KV bytes per slot counted
    exactly via ``jax.eval_shape`` over ``models.lm.init_cache`` for ONE slot
    under the requested ``kv_dtype`` — the int8 layout's scale planes price
    themselves, so the planner and the engine can never disagree about what a
    slot costs. ``measure`` (optional, ``(tp, dp) -> tokens/s | None``) hands
    the final ranking to measurement — see ``plan.search.search_serve``."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm as lm_mod,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.plan.costs import (
        ServeStats,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.plan.search import (
        ServeScenario,
    )

    if topo is None:
        topo = Topology.detect()
    param_bytes, shardable = _param_bytes(
        model, jnp.zeros((1, model.seq_len), jnp.int32))
    cache_shapes = jax.eval_shape(
        lambda: lm_mod.init_cache(model, 1, kv_dtype=kv_dtype))
    kv_bytes = float(sum(np.prod(leaf.shape) * leaf.dtype.itemsize
                         for leaf in jax.tree_util.tree_leaves(cache_shapes)))
    kvh = model.num_kv_heads or model.num_heads
    dtype_bytes = jnp.zeros((), model.dtype).dtype.itemsize
    # Decode forward per token: the 2·P matmul rule plus the attention
    # score/value einsums against the cached prefix (2·2·S·E per layer stack).
    flops_per_token = (2.0 * param_bytes / 4
                       + 4.0 * model.num_layers * model.seq_len
                       * model.embed_dim)
    stats = ServeStats(
        name="transformer_lm_serve", param_bytes=param_bytes,
        kv_bytes_per_slot=kv_bytes,
        prompt_bytes_per_slot=float(model.seq_len * 4),   # int32 prompt row
        flops_per_token=flops_per_token,
        num_layers=model.num_layers, num_heads=model.num_heads,
        num_kv_heads=kvh, seq_len=model.seq_len, embed_dim=model.embed_dim,
        dtype_bytes=dtype_bytes, shardable_fraction=shardable)
    return ServeScenario(stats=stats, topo=topo, num_slots=num_slots,
                         prompt_len=prompt_len, ttft_slo_s=ttft_slo_s,
                         hbm_fraction=hbm_fraction, measure=measure)
