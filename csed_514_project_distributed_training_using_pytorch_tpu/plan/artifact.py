"""The serializable ``Plan``: what the planner decided and why, as one JSON file.

A plan is a durable, inspectable artifact — not an in-memory decision: the
trainer that ran ``--plan auto`` writes it next to its checkpoints, a user
inspects it with ``tools/plan_report.py``, edits or pins it, and replays it
bit-for-bit with ``--plan path.json`` on a later run (or another machine with
the same chip count). The file carries the chosen mesh/microbatch split, the
predicted time/memory breakdown, the topology snapshot it was priced against,
and the ranked runner-up candidates, so predicted-vs-measured comparisons and
"why not X?" questions are answerable after the fact.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field

from csed_514_project_distributed_training_using_pytorch_tpu.plan.costs import (
    Candidate,
)

PLAN_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class Plan:
    """The planner's pick, in trainer-consumable and JSON-stable form."""

    run_type: str                       # 'composed' | 'lm' | 'cnn'
    device_count: int
    mesh: str                           # the --mesh spec string
    axes: dict                          # {'data': d, 'model': m, 'stage': s}
    fsdp: bool = False
    grad_accum: int = 1
    pipeline_microbatches: int = 1
    source: str = "auto"                # 'auto' | 'tune' | 'file'
    predicted: dict = field(default_factory=dict)   # CostBreakdown.to_dict()
    measured_step_s: float | None = None            # tune mode only
    topology: dict = field(default_factory=dict)    # Topology.to_dict()
    model: dict = field(default_factory=dict)       # ModelStats.to_dict()
    global_batch: int = 0
    candidates: list = field(default_factory=list)  # Ranked.to_dict() rows
    schema_version: int = PLAN_SCHEMA_VERSION

    @property
    def candidate(self) -> Candidate:
        return Candidate(data=int(self.axes.get("data", 1)),
                         model=int(self.axes.get("model", 1)),
                         stage=int(self.axes.get("stage", 1)),
                         fsdp=self.fsdp, grad_accum=self.grad_accum,
                         microbatches=self.pipeline_microbatches)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        if not isinstance(d, dict) or "mesh" not in d or "axes" not in d:
            raise ValueError("not a plan artifact: missing 'mesh'/'axes' keys")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            # Forward-compat: a NEWER writer may add fields; ignore but only
            # when the schema version says so, else it's probably not a plan.
            if int(d.get("schema_version", 0)) <= PLAN_SCHEMA_VERSION:
                raise ValueError(f"plan artifact has unknown keys {sorted(unknown)} "
                                 f"at schema_version <= {PLAN_SCHEMA_VERSION}")
        try:
            plan = cls(**{k: v for k, v in d.items() if k in known})
        except TypeError as e:
            # Hand-edited artifacts are a documented workflow: missing
            # required fields must surface as the corrupt-plan ValueError the
            # load contract promises, not a bare __init__ TypeError.
            raise ValueError(f"corrupt plan artifact: {e}") from e
        if plan.candidate.num_devices != plan.device_count:
            raise ValueError(
                f"corrupt plan: axes {plan.axes} product "
                f"{plan.candidate.num_devices} != device_count {plan.device_count}")
        return plan

    @classmethod
    def from_json(cls, text: str) -> "Plan":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        """Atomic write (the checkpoint writer's tmp+rename), so a reader never
        observes a torn artifact."""
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.checkpoint import (
            _atomic_write,
        )

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        _atomic_write(path, (self.to_json() + "\n").encode())

    @classmethod
    def load(cls, path: str) -> "Plan":
        with open(path) as fh:
            return cls.from_json(fh.read())
