"""Layout search: enumerate legal DP×FSDP×TP×PP factorizations, prune, rank.

The search space is small by construction — factor triples of the device count
times a few microbatch splits — so the "search" is exhaustive enumeration plus
a deterministic sort: no heuristics whose ranking could silently diverge from
the cost model it serves (pinned in ``tests/test_plan.py`` by comparing the
ranked output against brute-force evaluation of ``plan.costs.predict`` over the
same candidate set). What earns its keep here is the LEGALITY filter: every
divisibility and composition rule the trainers enforce at runtime
(``train/composed.py``'s guard block) is applied up front, so an emitted plan
never dies in the trainer's own validation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Callable

from csed_514_project_distributed_training_using_pytorch_tpu.plan.costs import (
    Candidate, CostBreakdown, ModelStats, ServeCostBreakdown, ServeStats,
    Topology, predict, predict_serve,
)

MAX_GRAD_ACCUM = 8       # accumulation splits tried when the scenario allows
MAX_MICROBATCHES = 16    # GPipe splits tried per stage candidate


@dataclass
class Scenario:
    """Everything one planning run needs: the model's stats, the topology, the
    batch, and which parts of the space the target trainer can execute
    (``axes``/``allow_fsdp`` mirror the trainer's own composition rules)."""

    run_type: str                       # 'composed' | 'lm' | 'cnn'
    stats: ModelStats
    topo: Topology
    global_batch: int
    axes: tuple = ("data", "model", "stage")
    allow_fsdp: bool = True
    allow_grad_accum: bool = False
    fixed_grad_accum: int = 1
    test_batch: int = 0      # eval batch a stage split must also divide
                             # (composed: batch_size_test % microbatches); 0 off
    hbm_fraction: float = 0.9
    # Optional empirical trial: candidate -> measured step seconds (None =
    # unmeasurable, e.g. a stage layout the trial harness doesn't build).
    # Installed by plan/scenarios.py; consumed by plan/autotune.py only.
    trial: Callable | None = field(default=None, repr=False)


@dataclass(frozen=True)
class Ranked:
    """One search result row: the candidate, its predicted costs, and (after
    ``plan.autotune.refine``) its measured step time + compile stats."""

    candidate: Candidate
    costs: CostBreakdown
    measured_step_s: float | None = None
    compile_s: float | None = None
    measured_flops_per_step: float | None = None

    @property
    def best_step_s(self) -> float:
        return (self.measured_step_s if self.measured_step_s is not None
                else self.costs.step_s)

    def to_dict(self) -> dict:
        return {"candidate": self.candidate.to_dict(),
                "costs": self.costs.to_dict(),
                "measured_step_s": self.measured_step_s,
                "compile_s": self.compile_s,
                "measured_flops_per_step": self.measured_flops_per_step}


def _factor_pairs(n: int):
    for a in range(1, n + 1):
        if n % a == 0:
            yield a, n // a


def _pow2_divisors(n: int, cap: int):
    d = 1
    while d <= min(n, cap):
        if n % d == 0:
            yield d
        d *= 2


def enumerate_candidates(scenario: Scenario) -> list[Candidate]:
    """Every LEGAL candidate for the scenario — the brute-force ground set.

    Legality mirrors the trainers' own guards: the global batch (and each
    accumulation microbatch) shards evenly over ``data``; ``model`` divides the
    attention heads and the embedding width (Megatron column/row splits);
    ``stage`` divides the layer stack, composes with data/model only, and
    carries a microbatch split the per-call batch divides by; ``fsdp`` never
    composes with a stage axis. Candidates are deduplicated and deterministic
    in order."""
    st, n = scenario.stats, scenario.topo.num_devices
    out: list[Candidate] = []
    accums = ([scenario.fixed_grad_accum] if not scenario.allow_grad_accum
              else sorted({scenario.fixed_grad_accum}
                          | set(_pow2_divisors(scenario.global_batch,
                                               MAX_GRAD_ACCUM))))
    for d, rest in _factor_pairs(n):
        if "data" not in scenario.axes and d > 1:
            continue
        if scenario.global_batch % d:
            continue
        for m, s in _factor_pairs(rest):
            if m > 1 and ("model" not in scenario.axes
                          or st.num_heads % m or st.embed_dim % m):
                continue
            if s > 1 and ("stage" not in scenario.axes
                          or st.num_layers % s):
                continue
            for accum in accums:
                step_batch = scenario.global_batch // accum
                if step_batch % d or (step_batch // d) == 0:
                    continue
                if s == 1:
                    out.append(Candidate(data=d, model=m, stage=s,
                                         grad_accum=accum))
                    if scenario.allow_fsdp and d > 1:
                        out.append(Candidate(data=d, model=m, stage=s,
                                             fsdp=True, grad_accum=accum))
                    continue
                for mb in _pow2_divisors(step_batch, MAX_MICROBATCHES):
                    if (step_batch // mb) % d:
                        continue
                    if scenario.test_batch and scenario.test_batch % mb:
                        # The composed trainer's eval engine pipelines the
                        # SAME microbatch split over the test batch — a plan
                        # that fails that guard must never be emitted.
                        continue
                    out.append(Candidate(data=d, model=m, stage=s,
                                         grad_accum=accum, microbatches=mb))
    return out


def _sort_key(row: Ranked):
    """Deterministic ranking: feasible first, then predicted step time, then a
    simplicity preference (fewer mesh axes, no FSDP, less microbatching, more
    data parallelism) so cost-model ties never flap between runs."""
    c = row.candidate
    axes_used = (c.model > 1) + (c.stage > 1)
    return (not row.costs.fits, row.costs.step_s, axes_used, c.fsdp,
            c.grad_accum * c.microbatches, -c.data, c.model, c.stage)


def search(scenario: Scenario, *, top: int = 10) -> list[Ranked]:
    """Enumerate, price, and rank the scenario's layouts; the head of the list
    is the planner's pick. Returns at most ``top`` rows, feasible-first; raises
    when NO candidate fits the memory budget (an infeasible plan must never be
    silently emitted — the error names the smallest observed footprint so the
    user can grow accum/devices or shrink the model)."""
    cands = enumerate_candidates(scenario)
    if not cands:
        raise ValueError(
            f"no legal parallel layout for {scenario.topo.num_devices} devices "
            f"at global batch {scenario.global_batch} (model "
            f"{scenario.stats.name!r})")
    rows = [Ranked(c, predict(scenario.stats, scenario.topo, c,
                              global_batch=scenario.global_batch,
                              hbm_fraction=scenario.hbm_fraction))
            for c in cands]
    rows.sort(key=_sort_key)
    if not rows[0].costs.fits:
        tightest = min(r.costs.total_bytes_per_chip for r in rows)
        raise ValueError(
            f"no layout fits the per-chip memory budget "
            f"({rows[0].costs.hbm_budget_bytes / 2**30:.2f} GiB usable): the "
            f"smallest candidate footprint is {tightest / 2**30:.2f} GiB — "
            f"add devices, enable grad accumulation, or shrink the model")
    return rows[:top]


# =========================================================================================
# Serving mesh search (the serve-plan half of DESIGN.md §25): enumerate the
# TP×(slot-DP) factorizations serving/shard.py can legally build, price them
# with plan.costs.predict_serve, and — when the scenario carries a measure
# hook — let MEASUREMENT pick the winner among the analytically-shortlisted
# candidates. The analytical model prunes; it never outranks a measurement.
# =========================================================================================


@dataclass
class ServeScenario:
    """One serve-planning run: the model's serving stats, the topology, the
    slot count, the workload shape (typical prompt length), and the SLO.
    ``measure`` is an optional empirical hook ``(tp, dp) -> tokens/s | None``
    (None = candidate unmeasurable); installed by the bench/loadgen caller,
    never by the scenario builder — measuring means serving real traffic."""

    stats: ServeStats
    topo: Topology
    num_slots: int
    prompt_len: int
    ttft_slo_s: float | None = None
    hbm_fraction: float = 0.9
    measure: Callable | None = field(default=None, repr=False)


@dataclass(frozen=True)
class ServeRanked:
    """One serve search row: the (tp, dp) mesh, its predicted costs, and —
    after the measure pass — the observed tokens/s."""

    tp: int
    dp: int
    costs: ServeCostBreakdown
    measured_tokens_per_s: float | None = None

    @property
    def best_tokens_per_s(self) -> float:
        return (self.measured_tokens_per_s
                if self.measured_tokens_per_s is not None
                else self.costs.tokens_per_s)

    def shard_spec(self) -> str:
        """The replica-facing ``--shard`` string (serving/tiers.py twin)."""
        return f"tp={self.tp},dp={self.dp}"

    def to_dict(self) -> dict:
        return {"tp": self.tp, "dp": self.dp,
                "shard_spec": self.shard_spec(),
                "costs": self.costs.to_dict(),
                "measured_tokens_per_s": self.measured_tokens_per_s}


def enumerate_serve_candidates(scenario: ServeScenario) -> list[tuple[int, int]]:
    """Every legal ``(tp, dp)`` pair for the device count: legality mirrors
    ``serving.shard.validate_engine_mesh`` exactly — ``tp`` divides both the
    query heads and the KV heads (head-sharded attention + cache planes),
    ``dp`` divides the slot count (whole slots per data group). Deterministic
    order: tp ascending."""
    st, n = scenario.stats, scenario.topo.num_devices
    out: list[tuple[int, int]] = []
    for tp, dp in _factor_pairs(n):
        if st.num_heads % tp or st.num_kv_heads % tp:
            continue
        if scenario.num_slots % dp:
            continue
        out.append((tp, dp))
    return out


def _serve_sort_key(row: ServeRanked):
    """Feasible first, highest throughput first, then simplicity (less TP —
    fewer collectives and a smaller blast radius) so model ties never flap."""
    return (not row.costs.feasible, -row.best_tokens_per_s, row.tp, row.dp)


def search_serve(scenario: ServeScenario, *, top: int = 10,
                 measure_top: int = 3) -> list[ServeRanked]:
    """Enumerate, price, rank — then, when the scenario carries a ``measure``
    hook, run it over the analytical top ``measure_top`` candidates and
    re-rank by measurement: the head of the returned list is the PICK, and it
    is always the measured-best among the measured set (the plan artifact's
    acceptance gate). Raises when no candidate is legal or none fits."""
    cands = enumerate_serve_candidates(scenario)
    if not cands:
        raise ValueError(
            f"no legal serve mesh for {scenario.topo.num_devices} devices "
            f"(heads {scenario.stats.num_heads}/{scenario.stats.num_kv_heads}, "
            f"slots {scenario.num_slots})")
    rows = [ServeRanked(tp, dp, predict_serve(
                scenario.stats, scenario.topo, tp=tp, dp=dp,
                num_slots=scenario.num_slots, prompt_len=scenario.prompt_len,
                ttft_slo_s=scenario.ttft_slo_s,
                hbm_fraction=scenario.hbm_fraction))
            for tp, dp in cands]
    rows.sort(key=_serve_sort_key)
    if not rows[0].costs.fits:
        tightest = min(r.costs.total_bytes_per_chip for r in rows)
        raise ValueError(
            f"no serve mesh fits the per-chip memory budget "
            f"({rows[0].costs.hbm_budget_bytes / 2**30:.2f} GiB usable): the "
            f"smallest candidate footprint is {tightest / 2**30:.2f} GiB — "
            f"add devices, shrink slots, or quantize the KV cache")
    rows = rows[:top]
    if scenario.measure is not None:
        measured = [dataclasses.replace(
                        r, measured_tokens_per_s=scenario.measure(r.tp, r.dp))
                    for r in rows[:measure_top]]
        # Measured rows outrank unmeasured ones outright; among measured,
        # observed tokens/s decides — the model only chose WHO got measured.
        measured.sort(key=lambda r: (r.measured_tokens_per_s is None,
                                     -(r.measured_tokens_per_s or 0.0),
                                     r.tp, r.dp))
        rows = measured + rows[measure_top:]
    return rows
