"""Empirical refinement: measure the top-K analytical candidates, re-rank by fact.

The analytical model (``plan/costs.py``) is built to RANK; its absolute numbers
inherit every nominal bandwidth in the tables. ``--plan tune`` closes the loop:
the top-K candidates from the analytical ranking are each AOT-compiled
(``jit(...).lower().compile()`` + ``cost_analysis()`` — the PR-1 telemetry path,
so compile seconds and compiled FLOPs ride along) and short-trialed for a few
steps on the live devices, and the final ranking sorts by MEASURED step time.
Costs are bounded by construction: K is small, trials are a handful of steps on
synthetic batches, and the compile cache is warm for whichever candidate the
real run then picks.

The trial harness itself lives with the scenario builders
(``plan/scenarios.py``) because what "one step of this trainer" means is
per-run-type; this module only orchestrates. Candidates the harness can't build
(stage layouts — the pipeline engine's trial would duplicate half the composed
trainer) keep their analytical estimate and remain in the ranking, flagged
unmeasured.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable

from csed_514_project_distributed_training_using_pytorch_tpu.plan.search import (
    Ranked, Scenario,
)


def refine(scenario: Scenario, ranked: list[Ranked], *, top_k: int = 3,
           emit: Callable | None = None) -> list[Ranked]:
    """Measure the first ``top_k`` rows with the scenario's trial harness and
    re-rank: measured rows by measured step seconds, unmeasured rows after them
    by their analytical estimate (a measured fact always outranks a prediction
    — an unmeasured stage candidate predicted faster than every measured row
    stays behind them rather than winning on an untested number).

    ``emit`` (optional) receives one ``plan.telemetry``-style dict per trialed
    candidate — the trainers pass ``TelemetryWriter.emit`` with
    ``utils.telemetry.autotune_event`` applied; tests pass a list appender."""
    if scenario.trial is None:
        return ranked
    out = []
    for rank, row in enumerate(ranked):
        if rank < top_k and row.costs.fits:
            trial = scenario.trial(row.candidate)
            if trial is not None:
                row = replace(row,
                              measured_step_s=trial.get("step_s"),
                              compile_s=trial.get("compile_s"),
                              measured_flops_per_step=trial.get("flops_per_step"))
            if emit is not None:
                from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
                    telemetry as T,
                )

                emit(T.autotune_event(
                    mesh=row.candidate.mesh_spec(), fsdp=row.candidate.fsdp,
                    grad_accum=row.candidate.grad_accum,
                    microbatches=row.candidate.microbatches, rank=rank,
                    predicted_step_s=row.costs.step_s,
                    measured_step_s=row.measured_step_s,
                    compile_s=row.compile_s,
                    flops_per_step=row.measured_flops_per_step))
        out.append(row)
    measured = [r for r in out if r.measured_step_s is not None]
    unmeasured = [r for r in out if r.measured_step_s is None]
    measured.sort(key=lambda r: r.measured_step_s)
    return measured + unmeasured
