"""Shared benchmark protocol: honest wall-clock for one training epoch on a mesh.

This is the measurement behind both headline artifacts of the reference — the single number
"time to train 1 epoch" and the time-vs-worker-count scaling curve (reference README.md:20,
``images/Time to train (1 epoch) vs. Number of machines.png``; the reference instruments it
as ``time.time() - t0`` around its epoch loop, ``src/train.py:10,99``).

Protocol details (SURVEY.md §7 hard part (c)):

- the whole epoch is ONE jit-compiled scanned program over the mesh (no per-step Python);
- one untimed warmup epoch pays for compilation and data fault-in;
- each timed epoch is closed by a device→host fetch of a scalar that is data-dependent on
  the epoch's final loss AND on the final step's parameter update (a leaf of the returned
  state), so the last backward/all-reduce/SGD cannot still be in flight at t1. The fetch —
  not ``block_until_ready`` — is the sync point on purpose: on tunnelled/experimental
  PJRT backends (this build image's axon TPU) ``block_until_ready`` can resolve at
  enqueue-ack rather than device completion and under-reports by orders of magnitude
  (measured: 1.6 ms for a 937-step epoch); a transfer of a value data-dependent on the
  whole epoch cannot lie.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import Dataset
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.distributed import (
    epoch_index_plan,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state, make_epoch_fn,
)


# The reference-parity training configuration both bench entry points measure under
# (reference src/train.py:12-16; global batch stays fixed as devices grow, :133).
GLOBAL_BATCH = 64
LEARNING_RATE = 0.01
MOMENTUM = 0.5

# Per-example model FLOPs, forward pass, computed statically from the flagship
# architecture (models/cnn.py; SURVEY.md §3.4): conv as 2·H_out·W_out·C_out·(K·K·C_in)
# MACs, dense as 2·in·out.
FWD_FLOPS_PER_EXAMPLE = (
    2 * 24 * 24 * 10 * (5 * 5 * 1)      # conv1: 288,000
    + 2 * 8 * 8 * 20 * (5 * 5 * 10)     # conv2: 640,000
    + 2 * 320 * 50                      # fc1:    32,000
    + 2 * 50 * 10                       # fc2:     1,000
)
TRAIN_FLOPS_PER_EXAMPLE = 3 * FWD_FLOPS_PER_EXAMPLE   # fwd + ~2× for backward

# bf16 peak per chip by device_kind substring (public spec sheets). The model computes in
# f32, so an MFU against bf16 peak is a conservative lower bound. Ordered: first match
# wins, so more specific kinds come before their prefixes.
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12), ("v4", 275e12),
    ("v3", 123e12), ("v2", 45e12),
]

# Published per-chip HBM bandwidth — the roofline batched KV-cache decode is judged
# against (decode is bandwidth-bound: every step re-reads the cache + weights).
PEAK_HBM_BYTES_BY_KIND = [
    ("v6", 1640e9), ("v5p", 2765e9), ("v5", 819e9), ("v4", 1228e9),
    ("v3", 900e9), ("v2", 700e9),
]

# Published per-chip HBM CAPACITY (spec sheets) — what a chip the process can't
# introspect yet is judged by (``parallel.mesh.device_memory_budget``'s fallback
# when the runtime reports no limit).
HBM_CAPACITY_BY_KIND = [
    ("v6", 32 << 30), ("v5p", 95 << 30), ("v5", 16 << 30), ("v4", 32 << 30),
    ("v3", 16 << 30), ("v2", 8 << 30),
]


def lookup_by_kind(table, device_kind: str, default=None):
    """First-match substring lookup over a device-kind spec table — the ONE
    matcher behind every per-kind table here (peak FLOPs, HBM bandwidth/
    capacity) and the planner's interconnect table (``plan.costs``). Tables are
    ordered most-specific-first ('v5p' before 'v5'); adding a chip generation
    means adding rows, never another matcher."""
    kind = device_kind.lower()
    return next((val for key, val in table if key in kind), default)


def peak_hbm_bytes(device_kind: str) -> float | None:
    """Peak HBM bytes/s for a TPU ``device_kind`` string, or None if unknown."""
    return lookup_by_kind(PEAK_HBM_BYTES_BY_KIND, device_kind)


def chained_diff_time(chain, *, n1=2, grow=8, max_n=4096, min_delta=0.25,
                      reps=3, warmup=1):
    """Per-iteration time of a chained computation via the two-point difference
    ``(t(N2) − t(N1)) / (N2 − N1)`` — the honest protocol for tunnelled PJRT
    backends, whose fixed ~70 ms dispatch+host-sync latency swamps a
    one-dispatch-per-rep measurement of sub-100 ms ops (it cancels exactly in the
    difference). ``chain(n)`` returns a zero-arg callable that runs the n-long
    chained program AND blocks on a data-dependent fetch. N2 grows geometrically
    (``grow``× per probe, capped at ``max_n``) until the chained work adds
    ``min_delta`` seconds over N1, so per-dispatch jitter (~ms) cannot dominate the
    difference. Returns ``(per_iter_seconds, (n1, t1), (n2, t2), converged)`` —
    ``converged`` is False when ``max_n`` was exhausted before the chain ever added
    ``min_delta`` seconds, i.e. the two-point difference is still jitter-dominated
    and callers should mark the row as such in their artifacts (r4 advisor
    finding). One owner for the protocol — a fix lands in every bench at once
    (bench_attention, bench_lm)."""
    def timed(run):
        for _ in range(warmup):
            run()
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    t1 = timed(chain(n1))
    n2, t2 = n1, t1
    while n2 < max_n:
        n2 = min(n2 * grow, max_n)
        t2 = timed(chain(n2))
        if t2 - t1 >= min_delta:
            break
    return (max((t2 - t1) / (n2 - n1), 1e-9), (n1, t1), (n2, t2),
            t2 - t1 >= min_delta)


def timed_state_run(run, state):
    """Time ONE compiled ``state -> (state, losses)`` program with the honest-sync
    protocol the microbenches share: the clock stops only after a device→host fetch
    of a scalar data-dependent on the last loss AND a parameter leaf (on tunnelled
    PJRT backends ``block_until_ready`` can resolve at enqueue-ack, under-reporting).
    Returns ``(state, seconds, last_loss)``. One owner for the probe — a sync-protocol
    fix lands in every bench at once."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    state, losses = run(state)
    probe = losses[-1] + jax.tree_util.tree_leaves(state.params)[0].astype(
        jnp.float32).ravel()[0]
    jax.device_get(probe)
    return state, time.perf_counter() - t0, float(jax.device_get(losses[-1]))


def enable_compile_cache(default_dir: str) -> None:
    """Enable jax's persistent compilation cache (best-effort; never a failure mode).

    Shared by the bench entry points (bench.py, bench_transformer.py): once any
    hardware window has primed the cache, a later successful chip claim costs seconds
    instead of a full XLA compile that can eat most of a bench attempt's deadline.
    ``JAX_COMPILATION_CACHE_DIR`` overrides ``default_dir``."""
    import os
    import sys

    import jax as _jax

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or default_dir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as exc:
        print(f"benchmarks: compilation cache disabled: {exc}", file=sys.stderr)


def peak_flops(device_kind: str) -> float | None:
    """bf16 peak FLOP/s for a TPU ``device_kind`` string, or None if unknown."""
    return lookup_by_kind(PEAK_FLOPS_BY_KIND, device_kind)


@dataclass(frozen=True)
class EpochBenchResult:
    """One mesh-size measurement of the reference's headline metric."""

    devices: int
    epoch_seconds: list[float]      # every timed epoch, in order
    median_seconds: float
    steps_per_epoch: int
    final_train_loss: float
    final_state: object             # TrainState after warmup + timed epochs (for eval)


def time_epochs(mesh: Mesh, train_ds: Dataset, *, global_batch: int = 64,
                learning_rate: float = 0.01, momentum: float = 0.5,
                seed: int = 1, sampler_seed: int = 42,
                timed_epochs: int = 3, unroll: int = 1,
                pregather: bool = False) -> EpochBenchResult:
    """Measure full-epoch wall-clock on ``mesh`` under the protocol above.

    Hyperparameter defaults are the reference's single-trainer values
    (``src/train.py:12-16``); the global batch stays fixed as devices grow — the reference's
    weak per-worker scaling regime (``src/train_dist.py:133``).
    """
    world = mesh.shape["data"]
    if global_batch % world:
        raise ValueError(f"global batch {global_batch} not divisible by device count "
                         f"{world} — the reported protocol would be wrong")

    model = Net()
    state = jax.device_put(create_train_state(model, jax.random.PRNGKey(seed)),
                           dp.replicated(mesh))
    rng = jax.random.PRNGKey(seed + 1)

    train_x = dp.put_global(mesh, train_ds.images, P())
    train_y = dp.put_global(mesh, train_ds.labels, P())
    epoch_fn = dp.compile_epoch(
        make_epoch_fn(model, learning_rate=learning_rate, momentum=momentum,
                      unroll=unroll, pregather=pregather), mesh)
    samplers = [ShardedSampler(len(train_ds), num_replicas=world, rank=r,
                               seed=sampler_seed) for r in range(world)]

    def one_epoch(state, epoch):
        plan = epoch_index_plan(samplers, epoch, global_batch // world)
        plan_d = dp.put_global(mesh, plan, P(None, "data"))
        state, losses = epoch_fn(state, train_x, train_y, plan_d, rng)
        # The honest sync point: fetch a scalar data-dependent on BOTH the final step's
        # forward (losses[-1]) and its backward/all-reduce/SGD update (a parameter leaf of
        # the returned state) — losses[-1] alone would let the last update stay in flight
        # at t1 (advisor finding r1).
        probe = losses[-1] + jax.tree_util.tree_leaves(state.params)[0].ravel()[0]
        jax.device_get(probe)
        final_loss = float(jax.device_get(losses[-1]))
        return state, final_loss, plan.shape[0]

    state, final_loss, steps = one_epoch(state, 0)       # warmup: compile + fault-in

    times = []
    for epoch in range(1, timed_epochs + 1):
        t0 = time.perf_counter()
        state, final_loss, steps = one_epoch(state, epoch)
        times.append(time.perf_counter() - t0)

    return EpochBenchResult(
        devices=world,
        epoch_seconds=times,
        median_seconds=float(np.median(times)),
        steps_per_epoch=steps,
        final_train_loss=final_loss,
        final_state=state,
    )
