"""Support subsystems: config, checkpointing, metrics/plots, profiling, determinism checks."""
