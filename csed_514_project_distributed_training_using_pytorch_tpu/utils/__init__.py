"""Support subsystems: config, checkpointing, metrics/plots, profiling, telemetry,
determinism checks."""
