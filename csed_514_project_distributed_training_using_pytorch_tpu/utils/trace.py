"""Distributed request tracing: spans from loadgen to decode tick, one schema.

The serve path is four processes deep — loadgen → ``serving/router.py`` →
``serving/replica.py`` (TCP) → ``serving/server.py``/``engine.py`` — and the
per-process JSONL telemetry can report TTFT percentiles but not *where one
request's milliseconds went* (router queue? affinity spill-over? prefill budget
stall? a redispatch hop after a crash?). This module is the backend-free
tracing plane that answers that:

- every request gets a ``trace_id`` at origin (loadgen, ``Server.submit`` or
  ``Router.submit``) and the id rides the router's newline-JSON TCP protocol
  into the replica's engine — spans emitted by four different processes join
  into one tree by id alone;
- each process emits **spans** — ``{"event": "span", "trace_id", "name",
  "proc", "ts", "dur_s", ...attrs}`` — through its own :class:`Tracer` (a
  ``utils.jsonl.JsonlWriter``, the jax-free writer: the router must never
  initialize a backend). Span names are a fixed vocabulary: ``client``
  (loadgen submit → future resolved), ``queue_wait`` (router or replica
  arrival → dispatch/admission), ``route`` (the routing decision, with
  affinity/spill-over attrs), ``dispatch`` (send → completion line, per hop),
  ``redispatch`` (a drained hop: hop number + cause crash/preempt/hang),
  ``hedge`` (a point marker: the router speculatively re-dispatched a
  still-pending request to a second replica — the copy's own ``dispatch``
  window closes later as ``ok`` or ``hedge_lost``),
  ``prefill`` (per chunk, with ``cache_hit_len``), ``decode`` (decode-ready →
  done, with the first-token split), ``draft``/``verify`` (speculative
  decoding's children of the decode window — per verify tick: host drafting
  wall, then the batched verify program, with proposed/accepted counts),
  ``resolve`` (completion → future resolution);
- **clock anchoring**: timestamps are ``time.monotonic()`` stamps shifted by a
  per-process anchor ``time.time() - time.monotonic()`` captured once at
  Tracer construction. Durations keep monotonic fidelity (immune to NTP
  steps); absolute positions are wall-clock comparable across processes on the
  same host (the fleet's deployment unit), so cross-process spans order
  correctly without any handshake. The residual error is wall-vs-monotonic
  drift over a process lifetime — microseconds over the minutes a serving run
  lasts, far under the millisecond spans being ordered.

Each process writes its own file (``<trace_dir>/router.jsonl``,
``replica<i>.jsonl``, ``server.jsonl``, ``loadgen.jsonl``) — no cross-process
file locking, restarts append (history survives), and a crashed replica tears
at most its own final line, which the shared guarded reader
(``utils.jsonl.read_jsonl``) tolerates. Assembly, critical-path accounting and
the Chrome trace-event export live here too so ``tools/trace_report.py`` and
``tools/serve_loadgen.py --summary-json`` render from one implementation.
"""

from __future__ import annotations

import itertools
import math
import os
import time

from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
    JsonlWriter,
    percentiles,
    read_jsonl,
)

_counter = itertools.count()


def new_trace_id() -> str:
    """A process-unique id: pid + per-process counter + a coarse time salt (two
    processes can share a pid across restarts; same-second reuse does not)."""
    return f"{os.getpid():x}-{int(time.time()):x}-{next(_counter):x}"


class Tracer:
    """Span emitter for ONE process. ``path`` empty disables everything (every
    call is a no-op — tracing off costs a truthiness check); ``proc`` names this
    process's track (``"router"``, ``"replica0"``, ``"server"``, ``"loadgen"``).

    All public stamps are ``time.monotonic()`` values — the same clock every
    serving component already uses for deadlines — converted to anchored
    wall-comparable seconds only at emission.
    """

    def __init__(self, path: str, *, proc: str):
        self.proc = proc
        self._writer = JsonlWriter(path)
        # The per-process anchor: monotonic -> wall, captured once. See the
        # module docstring for the ordering argument.
        self._anchor = time.time() - time.monotonic()

    @property
    def enabled(self) -> bool:
        return self._writer.enabled

    def anchored(self, mono_s: float) -> float:
        """A monotonic stamp as wall-comparable absolute seconds."""
        return self._anchor + mono_s

    def span(self, name: str, trace_id: str | None, t0: float,
             t1: float | None = None, **attrs) -> None:
        """Emit one span: ``[t0, t1]`` monotonic stamps (``t1`` None = a point
        span, dur 0). Silently a no-op when disabled or the request carries no
        trace id (an untraced request through a traced server)."""
        if not self.enabled or trace_id is None:
            return
        dur = 0.0 if t1 is None else max(0.0, t1 - t0)
        ev = {"event": "span", "trace_id": trace_id, "name": name,
              "proc": self.proc, "ts": round(self.anchored(t0), 6),
              "dur_s": round(dur, 6)}
        for k, v in attrs.items():
            if v is not None:
                ev[k] = (round(self.anchored(v), 6) if k.endswith("_ts")
                         else v)
        self._writer.emit(ev)

    def close(self) -> None:
        self._writer.close()


# --------------------------------------------------------------------- reading


def span_files(paths) -> list[str]:
    """Expand files-or-directories into the JSONL files under them (sorted —
    deterministic assembly order)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(os.path.join(p, f) for f in os.listdir(p)
                              if f.endswith(".jsonl")))
        else:
            out.append(p)
    return out


def read_spans(paths) -> tuple[list[dict], list[dict]]:
    """Load spans (and every non-span event, for reconciliation) from files or
    directories. Returns ``(spans, other_events)``; both use the shared guarded
    reader, so a crashed process's torn final line never blocks assembly."""
    spans, other = [], []
    for path in span_files(paths):
        for row in read_jsonl(path):
            (spans if row.get("event") == "span" else other).append(row)
    return spans, other


def assemble(spans) -> dict[str, list[dict]]:
    """Group spans by ``trace_id``, each trace sorted by anchored start time."""
    traces: dict[str, list[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(s)
    for tid in traces:
        traces[tid].sort(key=lambda s: (s.get("ts") or 0.0, s.get("dur_s") or 0))
    return traces


# The terminal span names: a trace holding none of these never resolved — its
# spans are ORPHANS (a future stranded, or a trace file lost). trace_report
# counts them; tests pin the count at zero.
TERMINAL_SPANS = ("resolve", "client")

# Fleet-lifecycle spans (the router's scale_up/scale_down/reload timeline
# annotations plus straggler eject/probe markers, all sharing one synthetic
# trace id): real spans on the Chrome timeline, but NOT requests —
# per-request accounting (summarize_traces, orphan counting) excludes them,
# or every elastic run would report one eternal "orphan" that is actually
# the fleet's own history.
LIFECYCLE_SPANS = ("scale", "reload", "eject")

# Critical-path segments, in pipeline order. ``dispatch`` spans OVERLAP the
# replica-side work they contain, so the breakdown uses the replica's own
# spans for the covered interior and charges only the remainder to overhead.
# ``draft``/``verify`` are the speculative-decoding children of the decode
# window (per verify tick: host drafting wall, then the batched verify
# program) — carved OUT of decode_first/decode_tail below so the segments
# stay exclusive and still sum to e2e. ``preempt_park`` is the decode stint a
# priority-preempted slot served before its eviction and ``resume`` the
# parked wait until re-admission (DESIGN.md §22) — the final ``decode`` span
# covers only the post-resume stint, so the three never overlap; the padding
# between park and resume that neither captures lands in ``overhead`` like
# any other scheduling gap.
SEGMENTS = ("router_queue_wait", "route", "failed_dispatch", "prefill_tier",
            "handoff", "replica_queue_wait",
            "prefill", "preempt_park", "resume", "draft", "verify",
            "decode_first", "decode_tail", "resolve", "overhead")


def trace_breakdown(spans: list[dict]) -> dict:
    """One trace's critical-path accounting: exclusive per-segment seconds that
    sum (with ``overhead`` absorbing scheduling/transport gaps) to the trace's
    end-to-end span. Exclusivity across hops: a losing (drained) dispatch is
    charged in FULL as ``failed_dispatch``, so replica-side spans that started
    inside its window — the dead replica's queue_wait/prefill/decode history, a
    hung zombie's late decode — stay visible in the span tree but are NOT
    summed into their segments (they would double-charge the same interval).
    Also surfaces redispatch hops, the span-derived TTFT, and the request ids
    seen at each tier (router vs replica — they differ: each tier numbers
    requests independently; the trace id is the join key)."""
    by_name: dict[str, list[dict]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    drained_windows = [(d["ts"], d["ts"] + (d.get("dur_s") or 0.0))
                       for d in by_name.get("dispatch", ())
                       if d.get("outcome") == "drained"]
    # Hedge-loser windows are SHADOWS, not failures: the losing copy ran
    # concurrently with the winner, so its wall clock is already covered by
    # the winning hop — its replica-side spans are excluded from the segment
    # sums (they would double-charge the interval), but the window itself is
    # NOT charged anywhere (unlike a drained hop, where the failed interval
    # was the only thing happening). Shadow exclusion is scoped to the LOSING
    # replica's own track: the winner's spans cover the same wall clock by
    # design and must keep counting.
    shadow_windows = [(d["ts"], d["ts"] + (d.get("dur_s") or 0.0),
                       f"replica{d.get('replica')}")
                      for d in by_name.get("dispatch", ())
                      if d.get("outcome") == "hedge_lost"]
    # Disaggregated prefill (DESIGN.md §25): the router's ``prefill_tier``
    # span covers the whole prefill-replica stint (dispatch → prefill_done),
    # so the prefill replica's own interior spans (its queue_wait/prefill)
    # are excluded from their segments — the tier window already charges
    # that wall, exclusively. The decode replica's spans start after the
    # window closes, so the decode-tier wall stays in decode_first/tail.
    tier_windows = [(d["ts"], d["ts"] + (d.get("dur_s") or 0.0))
                    for d in by_name.get("prefill_tier", ())]

    def losing(s):
        # Only replica-side spans can be "inside" a losing hop; the router's
        # own spans legitimately touch window boundaries (a route span at the
        # dispatch instant, the replay's queue_wait at the drain instant).
        # 2e-6 absorbs the independent 6-decimal rounding of ts and dur_s; the
        # winning hop's replica spans start a transport hop AFTER the drain.
        if s.get("proc") == "router":
            return False
        if any(a - 2e-6 <= s["ts"] <= b + 2e-6 for a, b in drained_windows):
            return True
        if any(a - 2e-6 <= s["ts"] <= b + 2e-6 for a, b in tier_windows):
            return True
        return any(a - 2e-6 <= s["ts"] <= b + 2e-6
                   for a, b, proc in shadow_windows
                   if s.get("proc") == proc)

    def total(name, pred=lambda s: True):
        return sum(s.get("dur_s") or 0.0 for s in by_name.get(name, ())
                   if pred(s) and not losing(s))

    start = min(s["ts"] for s in spans)
    end = max(s["ts"] + (s.get("dur_s") or 0.0) for s in spans)
    seg = dict.fromkeys(SEGMENTS, 0.0)
    seg["router_queue_wait"] = total("queue_wait",
                                     lambda s: s.get("proc") == "router")
    seg["replica_queue_wait"] = total("queue_wait",
                                      lambda s: s.get("proc") != "router")
    seg["route"] = total("route")
    seg["failed_dispatch"] = sum(b - a for a, b in drained_windows)
    # The handoff span lies INSIDE the prefill_tier window (the router closes
    # both at prefill_done): charge the shipping wall to its own segment and
    # carve the same seconds out of the tier window, so the sum stays e2e.
    seg["handoff"] = total("handoff")
    seg["prefill_tier"] = max(0.0, total("prefill_tier") - seg["handoff"])
    seg["prefill"] = total("prefill")
    # Priority preemption (DESIGN.md §22): the evicted decode stint and the
    # parked wait are their own segments — a preempted best-effort request's
    # e2e must show WHERE the squeeze landed, not smear it into overhead.
    seg["preempt_park"] = total("preempt_park")
    seg["resume"] = total("resume")
    decodes = [d for d in by_name.get("decode", ()) if not losing(d)]
    for d in decodes:
        first = d.get("first_token_s")
        dur = d.get("dur_s") or 0.0
        seg["decode_first"] += dur if first is None else min(first, dur)
        seg["decode_tail"] += 0.0 if first is None else max(0.0, dur - first)
    # Speculative decoding's draft/verify spans lie INSIDE the decode window:
    # charge them to their own segments and carve the same seconds out of the
    # decode split (tail first — drafting happens throughout, but the tail is
    # where the bulk of the window lives), so the sum stays exactly e2e.
    seg["draft"] = total("draft")
    seg["verify"] = total("verify")
    carve = seg["draft"] + seg["verify"]
    take = min(seg["decode_tail"], carve)
    seg["decode_tail"] -= take
    seg["decode_first"] = max(0.0, seg["decode_first"] - (carve - take))
    seg["resolve"] = total("resolve")
    e2e = end - start
    seg["overhead"] = max(0.0, e2e - sum(seg.values()))

    redispatches = sorted(by_name.get("redispatch", ()),
                          key=lambda s: s["ts"])
    # Span-derived TTFT: origin (trace start) -> the first token of the attempt
    # that actually resolved (the LAST decode span — a drained hop's decode
    # span, when it exists at all, precedes the replay's).
    ttft = None
    if decodes:
        d = max(decodes, key=lambda s: s["ts"])
        if d.get("first_token_ts") is not None:
            ttft = max(0.0, d["first_token_ts"] - start)
    return {
        "start": start, "end": end, "e2e_s": e2e, "segments": seg,
        "ttft_s": ttft,
        "hops": 1 + len(redispatches),
        "hedges": len(by_name.get("hedge", ())),
        "redispatch_causes": [s.get("cause") for s in redispatches],
        "resolved": any(s["name"] in TERMINAL_SPANS for s in spans),
        "request_ids": {s.get("proc"): s.get("request_id") for s in spans
                        if s.get("request_id") is not None},
        "finish": next((s.get("finish") for s in reversed(spans)
                        if s.get("finish") is not None), None),
    }


def lifecycle_timeline(spans) -> list[dict]:
    """The fleet-lifecycle spans (scale/reload), in time order — the scale
    timeline ``tools/trace_report.py`` renders alongside per-request trees."""
    return sorted((s for s in spans if s.get("name") in LIFECYCLE_SPANS),
                  key=lambda s: s.get("ts") or 0.0)


def summarize_traces(spans) -> dict:
    """Fleet-level reduction of a span set: per-segment p50/p95 over all traces,
    span-derived TTFT percentiles, hop/orphan accounting, and the per-trace
    breakdowns (sorted slowest-first) for the slowest-N report. Fleet-lifecycle
    spans (``LIFECYCLE_SPANS``) are excluded — they are timeline annotations,
    not requests."""
    spans = [s for s in spans if s.get("name") not in LIFECYCLE_SPANS]
    traces = assemble(spans)
    downs = {tid: trace_breakdown(t) for tid, t in traces.items()}
    orphans = [tid for tid, d in downs.items() if not d["resolved"]]
    seg_pcts = {}
    for name in SEGMENTS:
        vals = [d["segments"][name] for d in downs.values()]
        pcts = percentiles(vals, qs=(50, 95))
        if pcts and any(v > 0 for v in vals):
            seg_pcts[name] = {**pcts, "mean": sum(vals) / len(vals)}
    ttfts = [d["ttft_s"] for d in downs.values() if d["ttft_s"] is not None]
    return {
        "traces": len(traces),
        "spans": len(list(spans)),
        "orphans": len(orphans),
        "orphan_ids": orphans,
        "redispatched": sum(d["hops"] > 1 for d in downs.values()),
        "hedged": sum(d.get("hedges", 0) > 0 for d in downs.values()),
        "segments": seg_pcts,
        "ttft_s": percentiles(ttfts, qs=(50, 95)),
        "e2e_s": percentiles([d["e2e_s"] for d in downs.values()], qs=(50, 95)),
        "by_trace": dict(sorted(downs.items(),
                                key=lambda kv: -kv[1]["e2e_s"])),
    }


def reconcile_ttft(summary: dict, events) -> dict | None:
    """Span-derived TTFT percentiles against the serve/route events' own —
    the cross-check that the tracing plane measures the same reality the
    latency telemetry reports. Returns p50/p95 for both sides plus the ratio;
    None when either side is empty. Route events win over serve events when
    both exist (fleet runs: the replica-local serve ids don't match the
    router's; route events are the client-facing truth)."""
    routes = [e for e in events if e.get("event") == "route"]
    serves = routes or [e for e in events if e.get("event") == "serve"]
    ev_ttft = percentiles([e.get("ttft_s") for e in serves], qs=(50, 95))
    span_ttft = summary.get("ttft_s")
    if not ev_ttft or not span_ttft:
        return None
    out = {"span": span_ttft, "events": ev_ttft, "source":
           "route" if routes else "serve"}
    for q in ("p50", "p95"):
        a, b = span_ttft.get(q), ev_ttft.get(q)
        out[f"{q}_ratio"] = (a / b if a and b else None)
    return out


# ------------------------------------------------------------- chrome export


def chrome_trace(spans) -> dict:
    """The span set as Chrome trace-event JSON (``chrome://tracing`` /
    Perfetto's legacy loader): one ``pid`` track per process (router, each
    replica, loadgen/server) named via ``process_name`` metadata, one ``tid``
    lane per trace within each track (requests overlap freely — a lane per
    request keeps concurrent spans from nesting into nonsense), ``ph: "X"``
    complete events with microsecond ``ts``/``dur`` and the span attrs under
    ``args`` (``trace_id`` included, so Perfetto's search finds a request by
    id)."""
    spans = sorted(spans, key=lambda s: (s.get("ts") or 0.0))
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(s["ts"] for s in spans)
    pids: dict[str, int] = {}
    lanes: dict[str, int] = {}
    events = []
    for s in spans:
        pid = pids.setdefault(s.get("proc") or "?", len(pids) + 1)
        tid = lanes.setdefault(s["trace_id"], len(lanes) + 1)
        args = {k: v for k, v in s.items()
                if k not in ("event", "name", "proc", "ts", "dur_s", "t_s")}
        events.append({
            "name": s["name"], "cat": "serve", "ph": "X",
            "pid": pid, "tid": tid,
            "ts": round((s["ts"] - base) * 1e6, 1),
            "dur": max(round((s.get("dur_s") or 0.0) * 1e6, 1), 1.0),
            "args": args,
        })
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": proc}} for proc, pid in sorted(pids.items())]
    # Sort index pins track order: router first, then replicas, then clients.
    order = {"router": 0, "loadgen": 90, "server": 91}
    meta += [{"name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
              "args": {"sort_index": order.get(proc, 10)}}
             for proc, pid in sorted(pids.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def validate_chrome(doc: dict) -> list[str]:
    """Schema check for the export (the CI trace-smoke gate): every ``X`` event
    carries numeric pid/tid/ts/dur, every pid resolves to a ``process_name``
    metadata record, and every event references a trace (a span that lost its
    ``trace_id`` would render as an unattributable box). Returns the problems
    (empty = valid)."""
    problems = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    named = {e.get("pid") for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    for i, e in enumerate(events):
        if e.get("ph") != "X":
            continue
        for key in ("pid", "tid", "ts", "dur"):
            v = e.get(key)
            if not isinstance(v, (int, float)) or not math.isfinite(v) or v < 0:
                problems.append(f"event {i} ({e.get('name')}): bad {key}={v!r}")
        if e.get("pid") not in named:
            problems.append(f"event {i} ({e.get('name')}): pid {e.get('pid')} "
                            f"has no process_name record")
        if not e.get("args", {}).get("trace_id"):
            problems.append(f"event {i} ({e.get('name')}): no trace_id arg")
    return problems
