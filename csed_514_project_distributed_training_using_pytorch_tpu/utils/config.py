"""Configuration system.

The reference configures runs through hardcoded module constants (reference
``src/train.py:12-21``, ``src/train_dist.py:124-139``), one CLI flag (``--local_rank``,
``src/train_dist.py:121``), and cluster env vars set inside the program
(``MASTER_ADDR``/``MASTER_PORT``, ``src/train_dist.py:144-145``). Here the same knob set lives
in two frozen dataclasses with CLI overrides; cluster topology is *not* a knob — it comes from
the runtime (``jax.distributed`` slice metadata / device mesh), which deletes the reference's
hand-edited ``run1.py``/``run2.py`` launcher pattern entirely.

Defaults reproduce the reference values exactly (cited per field).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass


@dataclass(frozen=True)
class SingleProcessConfig:
    """Knobs of the single-process trainer (reference ``src/train.py:12-21``)."""

    n_epochs: int = 3                 # src/train.py:12
    batch_size_train: int = 64        # src/train.py:13
    batch_size_test: int = 1000       # src/train.py:14
    learning_rate: float = 0.01       # src/train.py:15
    momentum: float = 0.5             # src/train.py:16
    optimizer: str = "sgd"            # 'sgd' (reference parity, src/train.py:60-61) or
                                      # 'adamw' (beyond-parity; torch.optim.AdamW
                                      # semantics, ops/optim.py — momentum is then unused)
    weight_decay: float = 0.0         # AdamW decoupled weight decay (adamw only)
    lr_schedule: str = "constant"     # learning-rate schedule: 'constant' or 'cosine'
                                      # (half-period decay over the whole run); applied
                                      # inside the compiled step from state.step. This
                                      # trainer's resume trains n_epochs MORE, so the
                                      # cosine horizon anchors at the restored step
                                      # (the resumed run decays over its own span)
    warmup_steps: int = 0             # linear warmup ramp over the first N updates
    clip_grad_norm: float = 0.0       # clip gradients to this global norm before the
                                      # update (torch clip_grad_norm_ semantics); 0 off
    label_smoothing: float = 0.0      # torch CrossEntropyLoss(label_smoothing=s)
                                      # semantics: smoothed target (1-s)*onehot + s/C
    ema_decay: float = 0.0            # maintain an EMA of the params in the compiled
                                      # step (torch swa_utils semantics); eval and the
                                      # final export use the EMA weights; 0 disables
    async_checkpoint: bool = False    # write checkpoints on a background thread
                                      # (serialization+IO off the hot loop; atomic,
                                      # coalescing overwrites; flushed at exit)
    log_interval: int = 10            # src/train.py:17
    seed: int = 1                     # src/train.py:19 (torch.manual_seed(random_seed))
    data_dir: str = "files"           # src/train.py:26 ({CURR_PATH}/files/; one dir, not the
                                      # reference's hardcoded /home/abhishek test path, §2d.2)
    download_data: bool = False       # fetch the MNIST IDX archives into data_dir first
                                      # (≙ torchvision download=True, src/train.py:26-31;
                                      # off by default — this build env has no egress)
    results_dir: str = "results"      # src/train.py:84-85 checkpoint target
    images_dir: str = "images"        # src/train.py:57,117 plot target
    profile: bool = False             # optional jax.profiler capture (reference has none, §5)
    profile_dir: str = "results/profile"
    telemetry: str = ""               # write structured run telemetry (manifest /
                                      # compile / epoch / health / mfu JSONL events,
                                      # utils/telemetry.py) to this path; "" off.
                                      # Render with tools/telemetry_report.py
    health_stats: bool = False        # accumulate grad-norm/param-norm/loss-range
                                      # health stats INSIDE the compiled epoch scan
                                      # (zero extra host syncs; bitwise-identical
                                      # training — train/step.py::HealthStats) and
                                      # emit them as telemetry 'health' events
    resume_from: str = ""             # checkpoint path to resume from (the restore path the
                                      # reference lacks, SURVEY.md §5 "checkpoint/resume")
    model: str = "cnn"                # model family: 'cnn' (the reference's Net) or
                                      # 'transformer' (the beyond-parity attention family,
                                      # models/transformer.py); same data/trainer surface
    bf16: bool = False                # bfloat16 activations (f32 master weights + f32
                                      # softmax/loss statistics — the MXU-native dtype)
    remat: bool = False               # jax.checkpoint each transformer block on backward
                                      # (O(1)-blocks activation memory; transformer only)
    remat_policy: str = ""            # what remat saves: 'recompute-all' (default) or
                                      # 'save-dots' (keep MXU outputs, replay VPU work)
    causal: bool = False              # decoder-style (causal) attention
                                      # (transformer only)
    attention_window: int = 0         # sliding-window (local) attention width
                                      # (transformer only; 0 = full attention; see
                                      # ops.full_attention's window semantics)
    kv_heads: int = 0                 # grouped-query attention: number of K/V heads
                                      # (transformer only; 0 = MHA; must divide
                                      # num_heads — 1 = multi-query attention)
    rope: bool = False                # rotary position embeddings on q/k
                                      # (transformer only; composes with every core)
    use_pallas_kernels: bool = False  # fused Pallas loss/optimizer kernels
                                      # (ops/pallas_kernels.py; single-device step path)
    heartbeat_dir: str = ""           # write a per-process liveness file (step +
                                      # timestamp, atomic) each epoch for the fleet
                                      # supervisor's hang detection
                                      # (resilience/heartbeat.py); "" off
    handle_preemption: bool = False   # SIGTERM/SIGINT request a cooperative stop at
                                      # the next epoch boundary: final checkpoint +
                                      # telemetry flush, then exit 75 ("preempted",
                                      # resumable — resilience/preemption.py)
    keep_checkpoints: int = 0         # ALSO keep the last N per-epoch checkpoints
                                      # under results_dir/checkpoints/ with a
                                      # checksummed manifest + GC — the versioned
                                      # store the supervisor's newest-HEALTHY
                                      # resume scan reads (utils/checkpoint.py);
                                      # 0 off
    guard: bool = False               # numerical immune system: a fixed-shape
                                      # anomaly verdict (non-finite loss/grads,
                                      # grad-norm z-score) computed INSIDE the
                                      # compiled step; a poisoned step applies
                                      # the IDENTITY update instead of garbage
                                      # (train/step.py::GuardSpec). Off = zero
                                      # added ops, bitwise-pinned
    guard_zscore: float = 8.0         # spike threshold: grad norm above
                                      # ema_mean + z*max(ema_std, 0.5*ema_mean)
                                      # is an anomaly (guard only)
    anomaly_exit: int = 0             # exit 65 ("poisoned", EX_DATAERR) at the
                                      # epoch boundary once >= N anomalies were
                                      # detected — the supervisor then rolls
                                      # back to the newest HEALTHY checkpoint
                                      # and restarts with --skip-steps; 0 =
                                      # never exit, keep skipping silently
    skip_steps: str = ""              # half-open step windows "a:b[,c:d]" that
                                      # take the identity update on replay (the
                                      # supervisor's rollback-and-skip handoff;
                                      # deterministic because data order is a
                                      # pure function of seed+step)
    use_host_pipeline: bool = False   # feed batches through the native C++ threaded
                                      # prefetcher (the DataLoader num_workers=4 analog,
                                      # src/train_dist.py:43-45) instead of the device-
                                      # resident scan fast path; same math, same order
    scan_unroll: int = 1              # epoch-scan body unroll factor (semantics-preserving
                                      # codegen knob; amortizes per-step control overhead)
    grad_accum: int = 1               # accumulate gradients over N equal microbatches per
                                      # optimizer step (N× less activation memory; update
                                      # exactly equals the full-batch step — pinned)
    pregather: bool = False           # gather each scan segment's batches once up front
                                      # instead of per step (semantics-preserving; trades
                                      # HBM for per-step gather latency)
    max_train_examples: int = 0       # 0 = full split; >0 truncates (dev/CI shortening —
    max_test_examples: int = 0        # no reference analog; the reference always trains full)


@dataclass(frozen=True)
class DistributedConfig:
    """Knobs of the distributed trainer (reference ``src/train_dist.py:124-139``)."""

    epochs: int = 6                   # src/train_dist.py:139
    global_batch_size: int = 64       # src/train_dist.py:125 (per-worker = global/world, :133)
    batch_size_test: int = 1000       # src/train_dist.py:126
    learning_rate: float = 0.02       # src/train_dist.py:127
    momentum: float = 0.5             # src/train_dist.py:128
    optimizer: str = "sgd"            # 'sgd' (reference parity) or 'adamw'
                                      # (see SingleProcessConfig.optimizer)
    weight_decay: float = 0.0         # AdamW decoupled weight decay (adamw only)
    lr_schedule: str = "constant"     # 'constant' or 'cosine' (see
                                      # SingleProcessConfig.lr_schedule)
    warmup_steps: int = 0             # linear warmup ramp over the first N updates
    clip_grad_norm: float = 0.0       # global-norm gradient clipping; 0 disables
    label_smoothing: float = 0.0      # torch label-smoothing semantics
    ema_decay: float = 0.0            # params EMA in the compiled step (torch
                                      # swa_utils semantics); eval uses EMA weights
    async_checkpoint: bool = False    # background-thread checkpoint writes
    log_interval: int = 10            # src/train_dist.py:129
    seed: int = 1                     # src/train_dist.py:135 (model/init seed)
    sampler_seed: int = 42            # src/train_dist.py:37 (DistributedSampler seed)
    data_dir: str = "files"
    download_data: bool = False       # ≙ torchvision download=True (src/train_dist.py:22-30);
                                      # atomic install makes concurrent fetches by
                                      # co-hosted processes safe (last replace wins)
    results_dir: str = "results"
    images_dir: str = "images"
    shard_eval: bool = False          # False reproduces the reference's every-rank-evaluates-
                                      # the-full-test-set behavior (src/train_dist.py:21-24,
                                      # §2d.7); True shards eval + psums the sums.
    fsdp: bool = False                # ZeRO/FSDP (r5): shard params + optimizer
                                      # state over the SAME data axis the batch is
                                      # sharded on (parallel/fsdp.py) — per-device
                                      # weight+optimizer memory divides by the
                                      # worker count; trajectory identical to
                                      # plain DP (pinned in tests)
    resume_from: str = ""             # full-TrainState checkpoint to resume from (the
                                      # restore path the reference lacks; the distributed
                                      # trainer writes one per epoch to
                                      # results_dir/model_dist.ckpt)
    model: str = "cnn"                # model family: 'cnn' or 'transformer' (see
                                      # SingleProcessConfig.model)
    bf16: bool = False                # bfloat16 activations (see SingleProcessConfig.bf16)
    remat: bool = False               # jax.checkpoint transformer blocks (see
                                      # SingleProcessConfig.remat)
    remat_policy: str = ""            # see SingleProcessConfig.remat_policy
    causal: bool = False              # decoder-style attention (see
                                      # SingleProcessConfig.causal)
    attention_window: int = 0         # sliding-window attention width (see
                                      # SingleProcessConfig.attention_window)
    kv_heads: int = 0                 # grouped-query attention K/V head count (see
                                      # SingleProcessConfig.kv_heads)
    rope: bool = False                # rotary position embeddings (see
                                      # SingleProcessConfig.rope)
    heartbeat_dir: str = ""           # per-process liveness files for the fleet
                                      # supervisor (see SingleProcessConfig); "" off
    handle_preemption: bool = False   # cooperative SIGTERM stop at the next epoch
                                      # boundary, exit 75 (see SingleProcessConfig)
    keep_checkpoints: int = 0         # keep-last-N versioned checkpoint store with
                                      # manifest under results_dir/checkpoints/
                                      # (see SingleProcessConfig); 0 off
    guard: bool = False               # in-step anomaly verdict + guarded identity
                                      # update (see SingleProcessConfig.guard)
    guard_zscore: float = 8.0         # spike threshold (see SingleProcessConfig)
    anomaly_exit: int = 0             # exit 65 "poisoned" once >= N anomalies
                                      # (see SingleProcessConfig); 0 off
    skip_steps: str = ""              # identity-update replay windows "a:b[,c:d]"
                                      # (see SingleProcessConfig.skip_steps)
    host_local_feed: bool = False     # multi-host input pipeline: each process gathers and
                                      # feeds ONLY its addressable devices' shard of every
                                      # batch (SURVEY.md §7 hard part (d)) instead of the
                                      # device-resident replicated dataset + on-device
                                      # gather fast path; same plan, same math
    scan_unroll: int = 1              # epoch-scan body unroll factor (semantics-preserving)
    pregather: bool = False           # whole-epoch up-front batch gather (semantics-
                                      # preserving; trades HBM for per-step gather latency)
    grad_accum: int = 1               # gradient accumulation microbatches per step (see
                                      # SingleProcessConfig.grad_accum)
    profile: bool = False
    profile_dir: str = "results/profile"
    telemetry: str = ""               # structured run-telemetry JSONL path (see
                                      # SingleProcessConfig.telemetry); "" off
    health_stats: bool = False        # in-scan training-health accumulators (see
                                      # SingleProcessConfig.health_stats)
    max_train_examples: int = 0       # 0 = full split; >0 truncates (dev/CI shortening —
    max_test_examples: int = 0        # no reference analog; the reference always trains full)


@dataclass(frozen=True)
class ComposedConfig:
    """Knobs of the composed-parallelism trainer (``train/composed.py`` — beyond-parity:
    the reference has no TP/SP mode to mirror, so defaults are small-demo-sized)."""

    mesh: str = "data=2,seq=2,model=2"  # named axes: data (DP), seq (ring attention),
                                        # model (Megatron TP); product = device count
    plan: str = ""                      # automatic parallelism planning (plan/):
                                        # 'auto' picks mesh/fsdp/microbatch split
                                        # from the analytical cost model, 'tune'
                                        # re-ranks the top candidates by measured
                                        # step time, a path replays a saved plan
                                        # JSON; overrides --mesh/--fsdp/
                                        # --grad-accum/--pipeline-microbatches.
                                        # "" (default) changes nothing
    seq_len: int = 16                   # tokens per image (a seq mesh axis must divide
                                        # it; indivisible 784/seq_len zero-pads the
                                        # pixel stream — see TransformerClassifier)
    flash_attention: bool = False       # route attention through the Pallas flash
                                        # kernels: ring-of-flash when a seq axis > 1 is
                                        # present, single-chip flash otherwise. Needs
                                        # seq_len % (seq_axis_size * 128) == 0.
    pipeline_microbatches: int = 4      # GPipe microbatches per step under a stage
                                        # axis (bubble fraction (S-1)/(M+S-1));
                                        # batch_size must divide by it, and the
                                        # microbatch by the data axis
    pipeline_schedule: str = "gpipe"    # backward formulation under a stage axis:
                                        # 'gpipe' (autodiff through the scan) or
                                        # '1f1b' (custom-VJP reverse ring, stage-
                                        # input-only residuals + in-tick remat —
                                        # parallel/pipeline.py docstring)
    bf16: bool = False                  # bfloat16 activations (f32 master weights;
                                        # see SingleProcessConfig.bf16)
    remat_policy: str = ""              # see SingleProcessConfig.remat_policy
    remat: bool = False                 # jax.checkpoint each block on backward (not
                                        # with a stage axis — the pipeline engine
                                        # applies blocks itself)
    grad_accum: int = 1                 # gradient accumulation microbatches per step
                                        # (see SingleProcessConfig.grad_accum)
    causal: bool = False                # decoder-style (causal) attention over the
                                        # token sequence instead of bidirectional
    attention_window: int = 0           # sliding-window attention width (dense or
                                        # single-chip flash cores only — the ring/
                                        # ulysses SP schedules do not window; 0 off)
    kv_heads: int = 0                   # grouped-query attention K/V head count
                                        # (0 = MHA; must divide the model's 4 heads)
    rope: bool = False                  # rotary position embeddings on q/k
    moe_top_k: int = 1                  # MoE router: 1 = Switch top-1, 2 = GShard
                                        # top-2 (expert axis only)
    zigzag_attention: bool = False      # load-balanced zig-zag causal ring schedule
                                        # (parallel.zigzag_ring_attention); requires
                                        # --causal and seq_len % (2*seq_axis) == 0
    seq_impl: str = "ring"              # sequence-parallel schedule under a seq axis:
                                        # 'ring' (K/V ppermute rotation) or 'ulysses'
                                        # (head-scatter all-to-all,
                                        # parallel.ulysses_attention — needs
                                        # heads % (model_axis*seq_axis) == 0; composes
                                        # with --flash-attention, not
                                        # --zigzag-attention)
    resume_from: str = ""               # full-TrainState checkpoint to resume from;
                                        # checkpoints are layout-standard, so a run
                                        # resumes from ANY mesh's checkpoint (incl.
                                        # across stage layouts via the bridge)
    profile: bool = False               # jax.profiler capture around the epoch loop
    profile_dir: str = "results/profile"
    telemetry: str = ""                 # structured run-telemetry JSONL path (see
                                        # SingleProcessConfig.telemetry); "" off
    health_stats: bool = False          # in-scan training-health accumulators (see
                                        # SingleProcessConfig.health_stats)
    epochs: int = 2
    batch_size: int = 64
    batch_size_test: int = 1000
    learning_rate: float = 0.05
    momentum: float = 0.5
    optimizer: str = "sgd"              # 'sgd' or 'adamw' (see
                                        # SingleProcessConfig.optimizer); composes with
                                        # every mesh incl. stage (moments bridge
                                        # through the stacked layout)
    weight_decay: float = 0.0           # AdamW decoupled weight decay (adamw only)
    lr_schedule: str = "constant"       # 'constant' or 'cosine' (see
                                        # SingleProcessConfig.lr_schedule)
    warmup_steps: int = 0               # linear warmup ramp over the first N updates
    clip_grad_norm: float = 0.0         # global-norm gradient clipping; 0 disables
    label_smoothing: float = 0.0        # torch label-smoothing semantics
    ema_decay: float = 0.0              # params EMA in the compiled step (torch
                                        # swa_utils semantics); eval uses EMA weights
    async_checkpoint: bool = False      # background-thread checkpoint writes
    fsdp: bool = False                  # ZeRO x TP hybrid (r5): params + optimizer
                                        # state additionally shard over the data
                                        # axis on each leaf's largest free dim
                                        # (parallel/fsdp.py::hybrid_state_shardings)
                                        # — memory divides by data x model size;
                                        # trajectory identical (pinned in tests);
                                        # rejected with a stage axis
    dcn_data: int = 0                   # multi-slice: the data axis's leading
                                        # factor spans this many slices/granules
                                        # over DCN (0 = flat single-network mesh);
                                        # all other axes stay on ICI
    sharded_checkpoint: bool = False    # ALSO write a per-process distributed
                                        # checkpoint each epoch (<ckpt>.sharded/:
                                        # every process saves only the shards it
                                        # addresses, no gather); --resume-from
                                        # accepts the directory (not with stage=)
    heartbeat_dir: str = ""             # per-process liveness files for the fleet
                                        # supervisor (see SingleProcessConfig)
    handle_preemption: bool = False     # cooperative SIGTERM stop at the next epoch
                                        # boundary, exit 75 (see SingleProcessConfig)
    keep_checkpoints: int = 0           # keep-last-N versioned checkpoint store with
                                        # manifest (see SingleProcessConfig); 0 off
    guard: bool = False                 # in-step anomaly verdict + guarded identity
                                        # update (see SingleProcessConfig.guard)
    guard_zscore: float = 8.0           # spike threshold (see SingleProcessConfig)
    anomaly_exit: int = 0               # exit 65 "poisoned" once >= N anomalies
                                        # (see SingleProcessConfig); 0 off
    skip_steps: str = ""                # identity-update replay windows "a:b[,c:d]"
                                        # (see SingleProcessConfig.skip_steps)
    dropout_rate: float = 0.0           # 0 keeps composed runs comparable across meshes
    seed: int = 1
    data_dir: str = "files"
    download_data: bool = False
    results_dir: str = "results"
    max_train_examples: int = 0
    max_test_examples: int = 0


@dataclass(frozen=True)
class LMConfig:
    """Knobs of the autoregressive pixel-LM trainer (``train/lm.py`` — beyond-parity:
    the reference has no language model or generation path to mirror)."""

    epochs: int = 2
    batch_size: int = 64                # global batch, sharded over the data axis
    num_levels: int = 16                # gray-level vocabulary (BOS id = num_levels)
    embed_dim: int = 64
    num_layers: int = 2
    num_heads: int = 4
    dropout_rate: float = 0.0
    attention_window: int = 0           # sliding-window (local) causal attention
                                        # width over the pixel stream (0 = full)
    kv_heads: int = 0                   # grouped-query attention: K/V head count
                                        # (0 = MHA; divides num_heads; shrinks the
                                        # decode KV cache num_heads/kv_heads x)
    mesh: str = ""                      # optional named mesh, e.g. "data=2,seq=4"
                                        # or "data=2,model=2": data shards the
                                        # batch (DP), seq runs ring attention over
                                        # the pixel stream (context parallelism —
                                        # the LM is causal, so a seq axis trains
                                        # decoder-style long context), model
                                        # Megatron-shards the block kernels (TP,
                                        # r5; composes with data and seq).
                                        # Empty = all devices on one data axis.
    plan: str = ""                      # automatic parallelism planning (plan/):
                                        # 'auto' | 'tune' | a saved plan JSON
                                        # path; overrides --mesh/--grad-accum
                                        # (data x model search). "" off
    zigzag_attention: bool = False      # use the load-balanced zig-zag causal ring
                                        # schedule on the seq axis (uniform per-hop
                                        # work; needs seq_len % (2*seq_axis) == 0)
    rope: bool = False                  # rotary position embeddings (replaces the
                                        # learned pos_embed; decode rotates its
                                        # position by the same formula)
    learning_rate: float = 1e-3
    momentum: float = 0.5               # sgd only (adamw is the LM default)
    optimizer: str = "adamw"
    weight_decay: float = 0.01
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    clip_grad_norm: float = 1.0         # LM training convention; 0 disables
    label_smoothing: float = 0.0        # torch label-smoothing semantics
    ema_decay: float = 0.0              # params EMA in the compiled step (torch
                                        # swa_utils semantics); eval/generation use
                                        # the EMA weights
    async_checkpoint: bool = False      # background-thread checkpoint writes
    grad_accum: int = 1
    bf16: bool = False
    remat: bool = False
    remat_policy: str = ""              # see SingleProcessConfig.remat_policy
    eval_batch: int = 500               # test-perplexity scan batch (must divide split)
    generate: int = 6                   # sample this many digits after training (0 off)
    temperature: float = 1.0            # sampling temperature (<= 0 decodes greedily)
    top_k: int = 0                      # sample only the k most likely tokens (0 off)
    top_p: float = 1.0                  # nucleus sampling mass cutoff (1.0 off)
    seed: int = 1
    data_dir: str = "files"
    download_data: bool = False
    corpus: str = ""                    # sharded token-corpus directory
                                        # (tools/build_corpus.py output): train on
                                        # its streaming shards instead of MNIST
                                        # pixel streams; seq_len/vocab come from
                                        # corpus.json, the resume cursor from the
                                        # checkpoint manifest (DESIGN.md §26)
    data_throttle_s: float = 0.0        # per-batch streaming-loader brake (debug/
                                        # bench: proves goodput's data_wait is
                                        # actually measured); 0 off
    results_dir: str = "results"
    images_dir: str = "images"
    resume_from: str = ""               # per-epoch checkpoint to resume from
    heartbeat_dir: str = ""             # per-process liveness files for the fleet
                                        # supervisor (see SingleProcessConfig)
    handle_preemption: bool = False     # cooperative SIGTERM stop at the next epoch
                                        # boundary, exit 75 (see SingleProcessConfig)
    keep_checkpoints: int = 0           # keep-last-N versioned checkpoint store with
                                        # manifest (see SingleProcessConfig); 0 off
    guard: bool = False                 # in-step anomaly verdict + guarded identity
                                        # update (see SingleProcessConfig.guard)
    guard_zscore: float = 8.0           # spike threshold (see SingleProcessConfig)
    anomaly_exit: int = 0               # exit 65 "poisoned" once >= N anomalies
                                        # (see SingleProcessConfig); 0 off
    skip_steps: str = ""                # identity-update replay windows "a:b[,c:d]"
                                        # (see SingleProcessConfig.skip_steps)
    telemetry: str = ""                 # structured run-telemetry JSONL path (see
                                        # SingleProcessConfig.telemetry); "" off
    health_stats: bool = False          # in-scan training-health accumulators (see
                                        # SingleProcessConfig.health_stats)
    max_train_examples: int = 0
    max_test_examples: int = 0


def _add_args(parser: argparse.ArgumentParser, cfg) -> None:
    for f in dataclasses.fields(cfg):
        arg = "--" + f.name.replace("_", "-")
        if f.type == "bool" or isinstance(f.default, bool):
            parser.add_argument(arg, action=argparse.BooleanOptionalAction,
                                default=f.default)
        else:
            parser.add_argument(arg, type=type(f.default), default=f.default)


def parse_config(cls, argv: list[str] | None = None):
    """Build a config of type ``cls`` from CLI args (every field is a ``--flag``)."""
    parser = argparse.ArgumentParser(description=cls.__doc__)
    _add_args(parser, cls)
    ns = parser.parse_args(argv)
    return cls(**{f.name: getattr(ns, f.name) for f in dataclasses.fields(cls)})
