"""Metric history + stdout reporting.

Covers the reference's observability surface (SURVEY.md §5 "metrics/logging"): the four
module-level loss/counter lists (reference ``src/train.py:64-67``, ``src/train_dist.py:150-153``),
the every-``log_interval`` train progress line (``src/train.py:77-80``), the post-eval test
summary with average loss / correct / accuracy%% / elapsed seconds (``src/train.py:100-104``),
and the distributed per-epoch summary (``src/train_dist.py:113-114``). Elapsed time is
wall-clock since trainer start — the very number behind the reference's
time-vs-machines scaling plot (BASELINE.md), so it is measured identically here (but around
``block_until_ready``'d device work, SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field

import jax


@dataclass
class MetricsHistory:
    """Loss trajectories for the loss-curve plot (≙ reference src/train.py:64-67)."""

    train_losses: list = field(default_factory=list)
    train_counter: list = field(default_factory=list)   # examples seen at each train point
    test_losses: list = field(default_factory=list)
    test_counter: list = field(default_factory=list)    # examples seen at each eval point

    def record_train(self, examples_seen: int, loss: float) -> None:
        self.train_counter.append(int(examples_seen))
        self.train_losses.append(float(loss))

    def record_test(self, examples_seen: int, loss: float) -> None:
        self.test_counter.append(int(examples_seen))
        self.test_losses.append(float(loss))


def save_metrics_jsonl(history: MetricsHistory, path: str) -> str | None:
    """Machine-readable companion to the loss-curve PNGs: one JSON line per recorded
    metric point (``{"kind": "train"|"test", "examples_seen": N, "loss": L}``),
    process-0 gated and written atomically (tmp + rename) like the checkpoints.
    The stdout lines remain the reference-parity surface; this is the structured
    artifact tooling can consume without parsing them."""
    if not is_logging_process():
        return None
    import json
    import math

    # One atomic-write implementation for the whole codebase (perms/cleanup parity
    # with the checkpoints); lazy import keeps module import order trivial.
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.checkpoint import (
        _atomic_write,
    )

    def finite(l):
        # Strict JSONL: bare NaN/Infinity tokens are invalid JSON and break
        # consumers (jq, JSON.parse); a diverged run records null instead.
        return l if math.isfinite(l) else None

    rows = ([{"kind": "train", "examples_seen": e, "loss": finite(l)}
             for e, l in zip(history.train_counter, history.train_losses)]
            + [{"kind": "test", "examples_seen": e, "loss": finite(l)}
               for e, l in zip(history.test_counter, history.test_losses)])
    payload = "".join(json.dumps(row, allow_nan=False) + "\n" for row in rows)
    _atomic_write(path, payload.encode())
    return path


def load_metrics_jsonl(path: str) -> list[dict]:
    """Read-side inverse of ``save_metrics_jsonl``: one dict per non-blank line.

    This is the ONE JSONL reader — loss-curve metrics, the training telemetry
    stream, and the serving logs (``utils/telemetry.py``) all share it, so
    ``tools/telemetry_report.py`` consumes every file kind through the same code
    path. Two deliberate tolerances keep that sharing honest:

    - **unknown event types pass through untouched** — the reader never filters or
      interprets the ``event``/``kind`` keys, so a serve log, a training log, or a
      future event type all load as plain dicts and consumers pick what they know;
    - **a torn FINAL line is skipped** — the stream-mode writer
      (``TelemetryWriter(path, stream=True)``) appends per event, so a killed
      serving process can leave a partial trailing line; everything before it
      still loads. A malformed line anywhere EARLIER is still an error (atomic
      writers can't produce one — that file is corrupt, not torn). The guard
      itself has ONE owner — ``utils.jsonl.read_jsonl`` — shared with the
      trace reader, so router/trace files get the identical tolerance.
    """
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
        read_jsonl,
    )

    return read_jsonl(path)


class Stopwatch:
    """Wall-clock since construction (≙ ``t0 = time.time()`` reference src/train.py:10)."""

    def __init__(self):
        self.t0 = time.time()

    def elapsed(self) -> float:
        return time.time() - self.t0


def is_logging_process() -> bool:
    """Metric emission is process-0-gated — unlike the reference, where every rank prints and
    plots duplicate output (SURVEY.md §5)."""
    return jax.process_index() == 0


def log(msg: str) -> None:
    if is_logging_process():
        print(msg, flush=True)


class ProgressBar:
    """Live per-batch progress display — the reference's tqdm bars
    (``src/train_dist.py:76,96``) as a first-party, dependency-free analog.

    TPU-first constraints shape it: the compiled-epoch fast paths never see it (a
    per-batch host sync would throttle the chip — the reference's per-step
    ``.item()`` sync, SURVEY.md §3.2, is exactly what the scanned paths delete), so
    only the HOST-FED loops (``--use-host-pipeline``, ``--host-local-feed``) drive
    it, where a per-step dispatch already exists. Rendering is rate-limited
    (``min_interval_s``), process-0 gated, and tty-gated — piped/CI output gets
    nothing, so logs and tests stay byte-stable.
    """

    def __init__(self, total: int, desc: str = "", *, stream=None,
                 min_interval_s: float = 0.1, width: int = 24):
        self.total = max(1, int(total))
        self.desc = desc
        self.n = 0
        self._stream = sys.stderr if stream is None else stream
        self._min_interval = min_interval_s
        self._width = width
        self._last_render = 0.0
        self._t0 = time.time()
        self._enabled = (is_logging_process()
                         and bool(getattr(self._stream, "isatty", lambda: False)()))
        self._open_line = False
        self._last_len = 0

    def update(self, n: int = 1, loss: float | None = None) -> None:
        self.n += n
        if not self._enabled:
            return
        now = time.time()
        if self.n < self.total and now - self._last_render < self._min_interval:
            return
        self._last_render = now
        filled = self._width * self.n // self.total
        bar = "#" * filled + "-" * (self._width - filled)
        rate = self.n / max(now - self._t0, 1e-9)
        extra = f" loss={loss:.4f}" if loss is not None else ""
        line = (f"{self.desc}[{bar}] {self.n}/{self.total} "
                f"{rate:.1f}it/s{extra}")
        # Pad to the previous render's length: a shrinking line (rate settling,
        # loss dropping off) must not leave stale tail characters on the tty.
        pad = " " * max(0, self._last_len - len(line))
        self._last_len = len(line)
        self._stream.write(f"\r{line}{pad}")
        self._stream.flush()
        self._open_line = True

    def close(self) -> None:
        """Finish the in-place line so the next log starts clean."""
        if self._enabled and self._open_line:
            self._stream.write("\n")
            self._stream.flush()
            self._open_line = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def train_progress_line(epoch: int, examples_seen: int, dataset_size: int,
                        loss: float) -> str:
    """Per-log-interval progress (≙ reference src/train.py:78-80 format)."""
    pct = 100.0 * examples_seen / dataset_size
    return (f"Train Epoch: {epoch} [{examples_seen}/{dataset_size} ({pct:.0f}%)]"
            f"\tLoss: {loss:.6f}")


def test_summary_line(avg_loss: float, correct: int, total: int,
                      elapsed_s: float) -> str:
    """Post-eval summary (≙ reference src/train.py:100-104: avg loss = summed NLL / dataset
    size, argmax accuracy, elapsed seconds)."""
    pct = 100.0 * correct / total
    return (f"\nTest set: Avg. loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({pct:.0f}%), "
            f"Time elapsed: {elapsed_s:.2f}s\n")


def dist_epoch_summary_line(epoch: int, train_loss: float, val_loss: float,
                            accuracy: float, elapsed_s: float) -> str:
    """Distributed per-epoch summary (≙ reference src/train_dist.py:113-114)."""
    return (f"Epoch {epoch}: train_loss: {train_loss:.4f}, val_loss: {val_loss:.4f}, "
            f"accuracy: {accuracy:.4f}, time_elapsed: {elapsed_s:.2f}s")
