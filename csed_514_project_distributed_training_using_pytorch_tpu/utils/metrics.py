"""Metric history + stdout reporting.

Covers the reference's observability surface (SURVEY.md §5 "metrics/logging"): the four
module-level loss/counter lists (reference ``src/train.py:64-67``, ``src/train_dist.py:150-153``),
the every-``log_interval`` train progress line (``src/train.py:77-80``), the post-eval test
summary with average loss / correct / accuracy%% / elapsed seconds (``src/train.py:100-104``),
and the distributed per-epoch summary (``src/train_dist.py:113-114``). Elapsed time is
wall-clock since trainer start — the very number behind the reference's
time-vs-machines scaling plot (BASELINE.md), so it is measured identically here (but around
``block_until_ready``'d device work, SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax


@dataclass
class MetricsHistory:
    """Loss trajectories for the loss-curve plot (≙ reference src/train.py:64-67)."""

    train_losses: list = field(default_factory=list)
    train_counter: list = field(default_factory=list)   # examples seen at each train point
    test_losses: list = field(default_factory=list)
    test_counter: list = field(default_factory=list)    # examples seen at each eval point

    def record_train(self, examples_seen: int, loss: float) -> None:
        self.train_counter.append(int(examples_seen))
        self.train_losses.append(float(loss))

    def record_test(self, examples_seen: int, loss: float) -> None:
        self.test_counter.append(int(examples_seen))
        self.test_losses.append(float(loss))


def save_metrics_jsonl(history: MetricsHistory, path: str) -> str | None:
    """Machine-readable companion to the loss-curve PNGs: one JSON line per recorded
    metric point (``{"kind": "train"|"test", "examples_seen": N, "loss": L}``),
    process-0 gated and written atomically (tmp + rename) like the checkpoints.
    The stdout lines remain the reference-parity surface; this is the structured
    artifact tooling can consume without parsing them."""
    if not is_logging_process():
        return None
    import json
    import math

    # One atomic-write implementation for the whole codebase (perms/cleanup parity
    # with the checkpoints); lazy import keeps module import order trivial.
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.checkpoint import (
        _atomic_write,
    )

    def finite(l):
        # Strict JSONL: bare NaN/Infinity tokens are invalid JSON and break
        # consumers (jq, JSON.parse); a diverged run records null instead.
        return l if math.isfinite(l) else None

    rows = ([{"kind": "train", "examples_seen": e, "loss": finite(l)}
             for e, l in zip(history.train_counter, history.train_losses)]
            + [{"kind": "test", "examples_seen": e, "loss": finite(l)}
               for e, l in zip(history.test_counter, history.test_losses)])
    payload = "".join(json.dumps(row, allow_nan=False) + "\n" for row in rows)
    _atomic_write(path, payload.encode())
    return path


class Stopwatch:
    """Wall-clock since construction (≙ ``t0 = time.time()`` reference src/train.py:10)."""

    def __init__(self):
        self.t0 = time.time()

    def elapsed(self) -> float:
        return time.time() - self.t0


def is_logging_process() -> bool:
    """Metric emission is process-0-gated — unlike the reference, where every rank prints and
    plots duplicate output (SURVEY.md §5)."""
    return jax.process_index() == 0


def log(msg: str) -> None:
    if is_logging_process():
        print(msg, flush=True)


def train_progress_line(epoch: int, examples_seen: int, dataset_size: int,
                        loss: float) -> str:
    """Per-log-interval progress (≙ reference src/train.py:78-80 format)."""
    pct = 100.0 * examples_seen / dataset_size
    return (f"Train Epoch: {epoch} [{examples_seen}/{dataset_size} ({pct:.0f}%)]"
            f"\tLoss: {loss:.6f}")


def test_summary_line(avg_loss: float, correct: int, total: int,
                      elapsed_s: float) -> str:
    """Post-eval summary (≙ reference src/train.py:100-104: avg loss = summed NLL / dataset
    size, argmax accuracy, elapsed seconds)."""
    pct = 100.0 * correct / total
    return (f"\nTest set: Avg. loss: {avg_loss:.4f}, "
            f"Accuracy: {correct}/{total} ({pct:.0f}%), "
            f"Time elapsed: {elapsed_s:.2f}s\n")


def dist_epoch_summary_line(epoch: int, train_loss: float, val_loss: float,
                            accuracy: float, elapsed_s: float) -> str:
    """Distributed per-epoch summary (≙ reference src/train_dist.py:113-114)."""
    return (f"Epoch {epoch}: train_loss: {train_loss:.4f}, val_loss: {val_loss:.4f}, "
            f"accuracy: {accuracy:.4f}, time_elapsed: {elapsed_s:.2f}s")
