"""Plot artifacts: sample grid + loss curves (matplotlib, gated).

Reproduces the reference's three figure artifacts (SURVEY.md §2a #5, #7, #11): the 6-digit
sample grid (reference ``src/train.py:43-57`` → images/train_images.png), the single-process
train/test loss curve (``src/train.py:111-117`` → images/train_test_curve.png), and the
distributed curve (``src/train_dist.py:49-56`` → images/train_test_curve_dist.png). All
plotting is process-0 gated and degrades to a no-op if matplotlib is unavailable.
"""

from __future__ import annotations

import os

import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
    MNIST_MEAN,
    MNIST_STD,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (
    MetricsHistory,
    is_logging_process,
)

try:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    HAVE_MATPLOTLIB = True
except ImportError:  # plotting is optional — training never depends on it
    HAVE_MATPLOTLIB = False


def _ensure_dir(path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)


def _save_grid(images: np.ndarray, titles: list, path: str,
               **imshow_kw) -> str | None:
    """Shared digit-grid body: 3 columns, as many rows as the image count needs."""
    if not (HAVE_MATPLOTLIB and is_logging_process()):
        return None
    _ensure_dir(path)
    n = len(titles)
    rows = -(-n // 3)
    fig = plt.figure()
    for i in range(n):
        plt.subplot(rows, 3, i + 1)
        plt.tight_layout()
        plt.imshow(np.asarray(images[i, :, :, 0]), cmap="gray",
                   interpolation="none", **imshow_kw)
        plt.title(titles[i])
        plt.xticks([])
        plt.yticks([])
    fig.savefig(path)
    plt.close(fig)
    return path


def save_sample_grid(images: np.ndarray, labels: np.ndarray, path: str,
                     n: int = 6) -> str | None:
    """Grid of ``n`` example digits with their labels (≙ reference src/train.py:43-57).

    ``images`` are normalized NHWC; de-normalized for display.
    """
    imgs = np.asarray(images[:n]) * MNIST_STD + MNIST_MEAN
    return _save_grid(imgs, [f"Ground Truth: {int(l)}" for l in labels[:n]], path)


def save_generated_grid(images_raw: np.ndarray, path: str,
                        n: int = 6) -> str | None:
    """Grid of ``n`` model-generated digits (raw [0, 1] intensity NHWC — the pixel
    LM's ``ids_to_images`` output; no ground-truth labels exist for samples)."""
    n = min(n, len(images_raw))
    return _save_grid(np.asarray(images_raw[:n]), [f"Sample {i}" for i in range(n)],
                      path, vmin=0.0, vmax=1.0)


def save_loss_curves(history: MetricsHistory, path: str) -> str | None:
    """Train-loss trajectory + test-loss points vs examples seen
    (≙ reference src/train.py:111-117 and src/train_dist.py:49-56)."""
    if not (HAVE_MATPLOTLIB and is_logging_process()):
        return None
    _ensure_dir(path)
    fig = plt.figure()
    plt.plot(history.train_counter, history.train_losses, color="blue")
    plt.scatter(history.test_counter, history.test_losses, color="red")
    plt.legend(["Train Loss", "Test Loss"], loc="upper right")
    plt.xlabel("number of training examples seen")
    plt.ylabel("negative log likelihood loss")
    fig.savefig(path)
    plt.close(fig)
    return path


def save_batch_sweep_curve(global_batches: list[int], examples_per_s: list[float],
                           path: str) -> str | None:
    """Training throughput vs global batch size at fixed device count — the
    BASELINE.json configs[3] sweep (256/1024/4096) artifact."""
    if not (HAVE_MATPLOTLIB and is_logging_process()):
        return None
    _ensure_dir(path)
    fig = plt.figure()
    plt.plot(global_batches, examples_per_s, marker="o")
    plt.xscale("log", base=2)
    plt.xlabel("Global batch size")
    plt.ylabel("Training throughput (examples/s)")
    plt.title("Throughput vs. global batch size (fixed device count)")
    fig.savefig(path)
    plt.close(fig)
    return path


def save_attention_curve(rows: list[dict], path: str) -> str | None:
    """Flash-vs-dense attention fwd+bwd time vs sequence length (the long-context
    microbench artifact, ``bench_attention.py``). ``rows`` are the tool's JSON rows;
    a missing ``dense_fwdbwd_s`` (the O(S²) memory wall) truncates the dense line —
    that truncation is the point of the chart."""
    if not (HAVE_MATPLOTLIB and is_logging_process()):
        return None
    _ensure_dir(path)
    # 'is not None', not truthiness: a legitimate 0.0-second timing must plot.
    flash_pts = [(r["seq_len"], r["flash_fwdbwd_s"]) for r in rows
                 if r.get("flash_fwdbwd_s") is not None]
    dense_pts = [(r["seq_len"], r["dense_fwdbwd_s"]) for r in rows
                 if r.get("dense_fwdbwd_s") is not None]
    fig = plt.figure()
    plt.plot([s for s, _ in flash_pts], [f for _, f in flash_pts],
             marker="o", label="flash (Pallas, O(S·D) HBM)")
    if dense_pts:
        plt.plot([s for s, _ in dense_pts], [d for _, d in dense_pts],
                 marker="s", label="dense (XLA, O(S²) HBM)")
    plt.xscale("log", base=2)
    plt.xlabel("Sequence length (tokens)")
    plt.ylabel("Attention fwd+bwd time (s)")
    plt.title("Flash vs dense attention vs sequence length")
    plt.legend()
    fig.savefig(path)
    plt.close(fig)
    return path


def save_scaling_curve(worker_counts: list[int], epoch_seconds: list[float],
                       path: str) -> str | None:
    """Time-to-train-one-epoch vs number of workers — the reference's headline result
    (README.md:20, 'Time to train (1 epoch) vs. Number of machines.png')."""
    if not (HAVE_MATPLOTLIB and is_logging_process()):
        return None
    _ensure_dir(path)
    fig = plt.figure()
    plt.plot(worker_counts, epoch_seconds, marker="o")
    plt.xlabel("Number of devices")
    plt.ylabel("Time to train 1 epoch (s)")
    plt.title("Time to train (1 epoch) vs. Number of devices")
    fig.savefig(path)
    plt.close(fig)
    return path
