"""Backend-free JSONL emission + the shared percentile estimator.

Two pieces of telemetry plumbing live here because their consumers must never
initialize a jax backend (the supervisor doctrine: a process that supervises
accelerator-owning children must never claim a device itself):

- :class:`JsonlWriter` — append-per-emit, flushed-per-line JSONL. The full
  ``utils.telemetry.TelemetryWriter`` is process-0 gated via
  ``jax.process_index()``, which initializes a jax backend on first use; the
  fleet-side processes (``resilience/supervisor.py``, ``serving/router.py``)
  therefore use this writer instead. Same line schema, same shared reader
  (``utils.metrics.load_metrics_jsonl``), same report CLI.
- :func:`percentiles` — nearest-rank percentiles, the one estimator every
  serving summary and the report CLI agree on. ``utils.telemetry`` re-exports
  it; the backend-free router imports it from here directly.
- :func:`read_jsonl` — the ONE guarded line-parse: every JSONL reader in the
  repo (``utils.metrics.load_metrics_jsonl``, the trace reader in
  ``utils/trace.py``) funnels through it, so the torn-final-line tolerance —
  an append-per-emit writer killed mid-line (a crashed replica, a killed
  router) tears at most the trailing line — is defined exactly once. A
  malformed line anywhere EARLIER is still an error: append-only writers
  cannot produce one mid-file, so that file is corrupt, not torn.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time


def _finite(x):
    """Strict-JSONL rule (same as ``metrics.save_metrics_jsonl``): non-finite → None."""
    if x is None:
        return None
    x = float(x)
    return x if math.isfinite(x) else None


def read_jsonl(path: str) -> list[dict]:
    """Parse a JSONL file, one dict per non-blank line, tolerating a TORN FINAL
    line (skipped) and raising on a malformed line anywhere earlier. This is the
    single owner of that guard — ``utils.metrics.load_metrics_jsonl`` and the
    span reader in ``utils/trace.py`` both delegate here, so a router/trace file
    left mid-line by a crashed process always loads the same way."""
    rows = []
    with open(path) as f:
        lines = [l.strip() for l in f]
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break
            raise
    return rows


def percentiles(xs, qs=(50, 95, 99)) -> dict | None:
    """Nearest-rank percentiles of the non-None values, as ``{"p50": ..., ...}`` —
    the serving events' latency-summary convention (shared with the report CLI so
    both sides agree on the estimator). None when no values survive."""
    xs = sorted(x for x in xs if x is not None)
    if not xs:
        return None
    return {f"p{q}": _finite(xs[max(0, math.ceil(q / 100 * len(xs)) - 1)])
            for q in qs}


class JsonlWriter:
    """Append-per-emit JSONL, flushed per line — fleet-side telemetry.

    Append (never truncate): a preempted/restarted run re-runs with the same
    telemetry path later, and its event history must survive into the resumed
    run's report. ``path`` empty disables everything (emit is a no-op)."""

    def __init__(self, path: str):
        self.path = path or ""
        self._fh = None
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._fh = open(self.path, "a")
        self._t0 = time.time()
        # The router emits from N replica io threads plus its dispatch/monitor
        # threads concurrently; interleaved write() fragments would corrupt the
        # JSONL, so every emit is serialized.
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def emit(self, event: dict) -> None:
        event.setdefault("t_s", round(time.time() - self._t0, 6))
        line = json.dumps(event) + "\n"
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line)
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
