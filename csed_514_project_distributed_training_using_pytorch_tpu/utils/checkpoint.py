"""Checkpointing: pytree save AND the restore path the reference lacks.

The reference has three write-only checkpoint sites and no load code anywhere (SURVEY.md §5):
periodic ``torch.save`` of model+optimizer state every ``log_interval`` batches, overwriting
in place (reference ``src/train.py:84-85``), and a rank-0-only final model save
(``src/train_dist.py:163-164``, with the DDP unwrap at ``:116`` giving clean keys — moot here,
since there is no wrapper object to unwrap). This module reproduces both policies over a
single msgpack-serialized pytree (flax serialization — the ``torch.save`` zip+pickle analog,
but deterministic and pickle-free), gates writes to process 0, makes them atomic
(tmp + rename), and adds ``restore_train_state`` / ``load_params`` so training can actually
resume.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time

import jax
from flax import serialization

from csed_514_project_distributed_training_using_pytorch_tpu.train.step import TrainState


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file exists but its bytes do not decode (or do not match their
    recorded checksum) — the torn-write signature, as opposed to a missing file or a
    structurally different (wrong-format) pytree. The supervisor's newest-valid scan
    and humans both need the distinction: a torn write means "fall back one
    checkpoint", not "your code is loading the wrong thing"."""


def _atomic_write(path: str, data: bytes) -> None:
    if os.environ.get("RESILIENCE_FAULTS"):
        # Fault-injection hook (resilience/faults.py): an armed `torn` fault truncates
        # matching payloads, simulating the non-atomic write this tmp+rename dance
        # exists to prevent. Env-gated: the unarmed path costs one dict lookup.
        from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
            faults,
        )
        data = faults.mangle_write(path, data)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _decode_msgpack(path: str):
    """Read + msgpack-decode ``path``, wrapping raw decoder errors in a crisp
    :class:`CheckpointCorrupt` that names the file."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        return serialization.msgpack_restore(data)
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is corrupt — {len(data)} bytes failed to decode "
            f"({type(e).__name__}: {e}); likely a torn/partial write, not a format "
            f"mismatch") from e


def _state_dict_for_save(state: TrainState) -> dict:
    """Serialization form: absent optional fields are OMITTED (not stored as None),
    so EMA-off (and guard-off) checkpoints stay byte-identical to the format
    that predates each optional field — and raw msgpack consumers never see a
    None leaf."""
    d = state._asdict()
    for opt in ("ema", "guard"):
        if d.get(opt) is None:
            d.pop(opt, None)
    return d


def save_train_state(path: str, state: TrainState) -> None:
    """Full model+optimizer checkpoint (≙ the reference's model.pth + optimizer.pth pair,
    src/train.py:84-85, as one file). Process-0 gated; no-op elsewhere."""
    if jax.process_index() != 0:
        return
    state = jax.device_get(state)
    _atomic_write(path, serialization.to_bytes(_state_dict_for_save(state)))


def restore_train_state(path: str, reference_state: TrainState) -> TrainState:
    """The resume path the reference is missing. ``reference_state`` supplies the pytree
    structure/shapes (e.g. a freshly-initialized state).

    The optional ``ema`` field reconciles across the ``--ema-decay`` flag: a
    checkpoint written without EMA restores into an EMA-enabled reference by seeding
    the EMA tree from the checkpoint's params (exactly what the first
    ``AveragedModel`` update would do); a checkpoint carrying EMA restores into a
    plain reference by dropping the tree. The optional ``guard`` field (the
    ``--guard`` anomaly detector, ``train/step.py::GuardState``) reconciles the
    same way: a pre-guard checkpoint restores into a guarded reference keeping
    the reference's (fresh) detector state; a guarded checkpoint restores into
    a plain reference by dropping it.

    Raises :class:`CheckpointCorrupt` (naming the path) when the bytes do not decode
    — a truncated file surfaces as a torn write, not a raw msgpack stack trace."""
    raw = _decode_msgpack(path)
    ref = reference_state._asdict()
    if ref.get("ema") is not None and raw.get("ema") is None:
        raw["ema"] = raw["params"]
    elif ref.get("ema") is None:
        raw.pop("ema", None)
    raw.setdefault("ema", None)
    if ref.get("guard") is not None and raw.get("guard") is None:
        raw["guard"] = serialization.to_state_dict(ref["guard"])
    elif ref.get("guard") is None:
        raw.pop("guard", None)
    raw.setdefault("guard", None)
    restored = serialization.from_state_dict(ref, raw)
    return TrainState(**restored)


def restore_for_resume(path: str, reference_state: TrainState, *,
                       process_index: int, process_count: int,
                       steps_per_epoch: int, tele=None, shardings=None):
    """Shared resume prologue of the distributed and composed trainers: process-0
    restore, full-state broadcast to the fleet (the resume analog of DDP's initial
    param broadcast — checkpoints are process-0-gated writes, so on a fleet without a
    shared filesystem only process 0 can read one back), and start-epoch derivation.

    Returns ``(state, start_epoch, warning)`` where ``warning`` is a log-worthy
    message when the checkpoint's step count is not a whole number of THIS config's
    epochs — the tell-tale of a mid-epoch checkpoint or a checkpoint written under a
    different batch size (the step counter is the only progress metadata stored).

    ``path`` may also be a ``save_train_state_sharded`` DIRECTORY: every process
    then re-assembles it from the shard files directly (deterministic, shared-FS
    contract) — no process-0 gating and no broadcast needed.

    ``tele`` (a ``TelemetryWriter``) records the restore as a ``checkpoint`` event
    (op=restore, kind, bytes, wall seconds); emission is process-0 gated by the
    writer itself.

    ``shardings`` (a ``TrainState``-shaped sharding pytree for the CURRENT
    mesh) places the restored state straight onto the mesh — the
    rollback-on-a-reshaped-fleet path: a checkpoint written under one layout
    restores bitwise onto any other (the sharded interchange contract above;
    pinned in ``tests/test_anomaly.py``)."""
    t0 = time.perf_counter()
    state = reference_state
    if os.path.isdir(path):
        result = _derive_resume_epoch(
            restore_train_state_sharded(path, reference_state,
                                        shardings=shardings), steps_per_epoch)
        _emit_restore_event(tele, path, "sharded", t0, result[0])
        return result
    if process_index == 0:
        state = restore_train_state(path, state)
    if process_count > 1:
        import numpy as np
        from jax.experimental import multihost_utils
        state = jax.tree_util.tree_map(
            np.asarray, multihost_utils.broadcast_one_to_all(state))
    if shardings is not None:
        state = jax.device_put(state, shardings)
    result = _derive_resume_epoch(state, steps_per_epoch)
    _emit_restore_event(tele, path, "full", t0, result[0])
    return result


def _path_bytes(path: str) -> int | None:
    try:
        if os.path.isdir(path):
            return sum(os.path.getsize(os.path.join(path, f))
                       for f in os.listdir(path))
        return os.path.getsize(path)
    except OSError:
        return None


def _emit_checkpoint_event(tele, **kw) -> None:
    """The one owner of the enabled-gate + lazy-import emit dance every save and
    restore site shares (the lazy import keeps checkpoint->telemetry one-way at
    module-load time)."""
    if tele is None or not tele.enabled:
        return
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )
    tele.emit(T.checkpoint_event(**kw))


def _emit_restore_event(tele, path: str, kind: str, t0: float, state) -> None:
    _emit_checkpoint_event(tele, op="restore", path=path, kind=kind,
                           nbytes=_path_bytes(path),
                           wall_s=time.perf_counter() - t0,
                           step=int(state.step))


def _derive_resume_epoch(state: TrainState, steps_per_epoch: int):
    spe = max(steps_per_epoch, 1)
    start_epoch = int(state.step) // spe
    warning = None
    if int(state.step) % spe:
        warning = (f"checkpoint step {int(state.step)} is not a multiple of "
                   f"steps_per_epoch {spe} — a mid-epoch checkpoint, or one written "
                   f"under a different batch size; resuming at epoch {start_epoch} "
                   f"replays the partial epoch")
    return state, start_epoch, warning


# =========================================================================================
# Sharded (per-process) distributed checkpoints
# =========================================================================================


def _flatten_state_dict(tree):
    """Nested state dict → flat ``{"a/b/c": leaf}`` (msgpack-friendly key paths).
    ``None`` subtrees survive as leaves (flax's flatten_dict drops/levels them
    differently per version, and the format needs them recorded explicitly)."""
    from flax import traverse_util

    return traverse_util.flatten_dict(tree, sep="/",
                                      is_leaf=lambda _, v: not isinstance(v, dict))


def _unflatten_state_dict(flat):
    from flax import traverse_util

    return traverse_util.unflatten_dict(flat, sep="/")


def save_train_state_sharded(dir_path: str, state: TrainState) -> None:
    """Distributed checkpoint: EVERY process writes exactly the shards it addresses
    (first replica only), in parallel — no process gathers the full state, so the
    host-memory and IO cost per process is its own shard set, not the model size.
    This is the multi-host-scalable alternative to the process-0 full-state
    ``save_train_state`` (which must all-gather sharded leaves to host 0 first).

    Layout: ``dir_path/meta.msgpack`` (process 0: global shapes/dtypes + step) and one
    ``shards_p{i}.msgpack`` per process, each mapping flat leaf paths to a list of
    ``{start, data}`` blocks (global offsets + the local block). All writes are
    atomic; restore re-assembles from whatever layout the state was sharded in, so
    sharded checkpoints interchange across mesh layouts like full-state ones."""
    import numpy as np

    flat = _flatten_state_dict(serialization.to_state_dict(state._asdict()))
    shards: dict[str, list] = {}
    meta: dict[str, dict] = {}
    for key, leaf in flat.items():
        if leaf is None:                    # optional subtree absent (e.g. no EMA)
            meta[key] = {"none": True}
        elif isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            meta[key] = {"shape": list(leaf.shape), "dtype": leaf.dtype.name}
            blocks = []
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:     # exactly one owner per global block
                    continue
                starts = [0 if s.start is None else int(s.start) for s in sh.index]
                blocks.append({"start": np.asarray(starts, np.int64),
                               "data": np.asarray(sh.data)})
            if blocks:
                shards[key] = blocks
        else:                               # host leaf (numpy/python): process 0 owns it
            arr = np.asarray(leaf)
            meta[key] = {"shape": list(arr.shape), "dtype": arr.dtype.name}
            if jax.process_index() == 0:
                shards[key] = [{"start": np.zeros(arr.ndim, np.int64), "data": arr}]
    os.makedirs(dir_path, exist_ok=True)
    if jax.process_index() == 0:
        _atomic_write(os.path.join(dir_path, "meta.msgpack"),
                      serialization.msgpack_serialize(
                          {"meta": meta, "process_count": jax.process_count()}))
        # Drop stale shard files a previous larger-fleet run may have left in an
        # overwritten checkpoint dir — restore reads exactly process_count files.
        import glob
        for old in glob.glob(os.path.join(dir_path, "shards_p*.msgpack")):
            idx = os.path.basename(old)[len("shards_p"):-len(".msgpack")]
            if idx.isdigit() and int(idx) >= jax.process_count():
                os.remove(old)
    _atomic_write(os.path.join(dir_path, f"shards_p{jax.process_index()}.msgpack"),
                  serialization.msgpack_serialize(shards))


def _box_subtract(box: tuple, cut: tuple) -> list:
    """Axis-aligned box difference ``box \\ cut`` as a list of disjoint boxes.

    Boxes are tuples of per-dimension ``(lo, hi)`` half-open ranges (a 0-d box —
    the empty tuple — is a scalar and is removed by any cut). The standard guillotine
    split: clip ``cut`` to ``box``; if they are disjoint the box survives whole,
    otherwise slice off the below/above-the-cut slabs dimension by dimension,
    shrinking toward the intersection, which is the (discarded) covered part."""
    inter = [(max(lo, clo), min(hi, chi))
             for (lo, hi), (clo, chi) in zip(box, cut)]
    if any(lo >= hi for lo, hi in inter):
        return [box]
    pieces = []
    cur = list(box)
    for d, (ilo, ihi) in enumerate(inter):
        lo, hi = cur[d]
        if lo < ilo:
            pieces.append(tuple(cur[:d]) + ((lo, ilo),) + tuple(cur[d + 1:]))
        if ihi < hi:
            pieces.append(tuple(cur[:d]) + ((ihi, hi),) + tuple(cur[d + 1:]))
        cur[d] = (ilo, ihi)
    return pieces


def restore_train_state_sharded(dir_path: str, reference_state: TrainState,
                                *, shardings=None) -> TrainState:
    """Re-assemble a ``save_train_state_sharded`` checkpoint (any source layout) into
    host arrays shaped by ``reference_state``, optionally ``jax.device_put`` onto
    ``shardings`` (a ``TrainState``-shaped sharding pytree for the CURRENT mesh).
    Needs every writing process's ``shards_p*.msgpack`` visible (shared filesystem,
    the usual distributed-checkpoint contract); the file set is pinned by the
    recorded ``process_count``, so stale files from an older, larger fleet in an
    overwritten directory are never merged in. The optional ``ema`` field reconciles
    across ``--ema-decay`` exactly like ``restore_train_state``."""
    import numpy as np

    raw_meta = _decode_msgpack(os.path.join(dir_path, "meta.msgpack"))
    meta, process_count = raw_meta["meta"], int(raw_meta["process_count"])
    none_keys = {key for key, m in meta.items() if m.get("none")}
    meta = {key: m for key, m in meta.items() if key not in none_keys}
    full = {key: np.zeros(m["shape"], np.dtype(m["dtype"]))
            for key, m in meta.items()}
    files = [os.path.join(dir_path, f"shards_p{i}.msgpack")
             for i in range(process_count)]
    missing = [f for f in files if not os.path.exists(f)]
    if missing:
        raise FileNotFoundError(
            f"sharded checkpoint {dir_path} was written by {process_count} "
            f"process(es) but {len(missing)} shard file(s) are absent "
            f"(e.g. {os.path.basename(missing[0])}) — shared filesystem required")
    # Exact per-REGION coverage via box subtraction, not a volumetric count:
    # overlapping blocks (a writer bug, a hand-edited checkpoint) must not
    # double-count and mask a genuinely missing region that would silently restore
    # zeros — and unlike the earlier per-element bool masks this costs O(#blocks)
    # boxes, not one host byte per parameter element on top of the full restore
    # buffers (r4 advisor finding: ~25% extra peak memory at large checkpoints).
    # Zero-size keys start fully covered; each block subtracts its slab from the
    # remaining-uncovered set (subtracting an already-covered region is a no-op,
    # which is what makes overlap exact).
    uncovered = {key: ([] if 0 in m["shape"]
                       else [tuple((0, n) for n in m["shape"])])
                 for key, m in meta.items()}
    for path in files:
        shards = _decode_msgpack(path)
        for key, blocks in shards.items():
            for blk in blocks:
                start, data = blk["start"], blk["data"]
                idx = tuple(slice(int(s), int(s) + n)
                            for s, n in zip(start, data.shape))
                full[key][idx] = data
                cut = tuple((int(s), int(s) + n)
                            for s, n in zip(start, data.shape))
                uncovered[key] = [piece for box in uncovered[key]
                                  for piece in _box_subtract(box, cut)]
    short = [k for k, boxes in uncovered.items() if boxes]
    if short:
        raise ValueError(
            f"sharded checkpoint {dir_path} is missing blocks for {short[:3]}"
            f"{'...' if len(short) > 3 else ''} — were all processes' shard files "
            f"written and visible?")
    # EMA reconciliation across the --ema-decay flag (mirrors restore_train_state):
    # a pre-EMA checkpoint seeds the reference's EMA tree from its params; an EMA
    # checkpoint restoring into a plain reference drops the tree.
    if reference_state.ema is not None and "ema" in none_keys:
        for k in [k for k in full if k.startswith("params/")]:
            full["ema/" + k[len("params/"):]] = full[k]
        none_keys.discard("ema")
    elif reference_state.ema is None:
        for k in [k for k in full if k.startswith("ema/")]:
            del full[k]
        none_keys.add("ema")
    # Guard reconciliation across the --guard flag: a pre-guard checkpoint
    # (guard recorded as absent OR predating the field entirely) keeps the
    # reference's fresh detector scalars; a guarded checkpoint restoring into
    # a plain reference drops them.
    if reference_state.guard is not None and not any(
            k.startswith("guard/") for k in full):
        for k, leaf in _flatten_state_dict(
                {"guard": serialization.to_state_dict(
                    reference_state.guard)}).items():
            full[k] = np.asarray(leaf)
        none_keys.discard("guard")
    elif reference_state.guard is None:
        for k in [k for k in full if k.startswith("guard/")]:
            del full[k]
        none_keys.add("guard")
    for key in none_keys:
        full[key] = None
    restored = serialization.from_state_dict(reference_state._asdict(),
                                             _unflatten_state_dict(full))
    state = TrainState(**restored)
    if shardings is not None:
        state = jax.device_put(state, shardings)
    return state


# =========================================================================================
# Versioned checkpoint store: manifest + retention + newest-valid selection
# =========================================================================================
#
# The overwrite-in-place policy above reproduces the reference; it is also exactly one
# torn write away from having NO resume artifact. The versioned store is the supervisor's
# (resilience/supervisor.py) substrate: per-epoch files named by step, a checksummed
# manifest, keep-last-N GC, and a newest-VALID scan that skips the torn write the crash
# it is recovering from may have produced.

MANIFEST_NAME = "manifest.json"
_VERSIONED_PREFIX, _VERSIONED_SUFFIX = "ckpt_", ".msgpack"


def versioned_name(step: int) -> str:
    return f"{_VERSIONED_PREFIX}{int(step):08d}{_VERSIONED_SUFFIX}"


def load_manifest(dir_path: str) -> dict:
    """The store's manifest (``{"version": 1, "entries": [...]}``; each entry:
    ``file``/``step``/``sha256``/``bytes``/``unix_time``). Missing or unreadable →
    empty manifest (the scan then falls back to decode-validation)."""
    try:
        with open(os.path.join(dir_path, MANIFEST_NAME)) as f:
            man = json.load(f)
        if isinstance(man.get("entries"), list):
            return man
    except (OSError, ValueError):
        pass
    return {"version": 1, "entries": []}


def save_versioned(dir_path: str, state: TrainState, *, keep: int = 3,
                   tele=None, health: dict | None = None,
                   cursor: dict | None = None) -> str | None:
    """Write ``state`` as ``ckpt_{step:08d}.msgpack`` into the versioned store:
    atomic file write, then an atomic manifest update (file, step, sha256, bytes),
    then GC of everything beyond the newest ``keep`` steps. Process-0 gated (returns
    None elsewhere and for ``keep``-0 stores). The checksum is computed from the
    in-memory payload BEFORE the write — a torn write therefore mismatches its own
    manifest entry and is skipped by :func:`newest_valid_checkpoint`, which is the
    entire point of recording it.

    ``health`` stamps the manifest entry with the run's integrity verdict at
    save time (``--guard`` trainers pass ``{"clean": bool, "anomalies": N,
    "skipped": N, "step": N, "fingerprint": F}`` — clean meaning no anomaly
    was detected since the PREVIOUS versioned save). The stamp is what
    :func:`newest_healthy_checkpoint` prefers over blind newest-valid; old
    manifests without it remain loadable and keep their merely-valid standing
    (back-compat pinned in tests).

    ``cursor`` keys the trainer's DATA position into the same manifest entry —
    for the streaming loader (``data/stream.py``) the shard/intra-shard-offset/
    plan-CRC triple, for the in-memory trainers the ``(seed, epoch, step)``
    anchor of the ``(seed, epoch)``-pure permutation. The invariant (DESIGN.md
    §26): a checkpoint and the position of the batch stream that produced it
    are ONE durable artifact, so preemption-resume replays the exact remaining
    stream bitwise instead of guessing an epoch boundary from the step count.
    Read back with :func:`cursor_for`.

    Synchronous BY DESIGN, even next to ``--async-checkpoint``: this store is the
    supervisor's resume substrate and the preemption contract's "checkpoint already
    durable at the boundary" — a write-behind versioned save would make the
    cooperative-stop exit racy against its own artifact. The cost is one extra
    serialize+hash per epoch on top of the overwrite checkpoint."""
    if jax.process_index() != 0:
        return None
    keep = max(int(keep), 1)
    t0 = time.perf_counter()
    state = jax.device_get(state)
    data = serialization.to_bytes(_state_dict_for_save(state))
    step = int(state.step)
    name = versioned_name(step)
    path = os.path.join(dir_path, name)
    _atomic_write(path, data)
    manifest = load_manifest(dir_path)
    entries = [e for e in manifest["entries"] if e.get("file") != name]
    entry = {"file": name, "step": step,
             "sha256": hashlib.sha256(data).hexdigest(),
             "bytes": len(data), "unix_time": time.time()}
    if health is not None:
        entry["health"] = dict(health)
    if cursor is not None:
        entry["cursor"] = dict(cursor)
    entries.append(entry)
    entries.sort(key=lambda e: e["step"])
    dropped, entries = entries[:-keep], entries[-keep:]
    _atomic_write(os.path.join(dir_path, MANIFEST_NAME),
                  json.dumps({"version": 1, "entries": entries},
                             indent=1).encode())
    for e in dropped:                     # GC strictly after the manifest stops
        try:                              # naming them — a reader never sees a
            os.remove(os.path.join(dir_path, e["file"]))   # manifest-listed hole
        except OSError:
            pass
    _emit_checkpoint_event(tele, op="save", path=path, kind="full",
                           nbytes=len(data), wall_s=time.perf_counter() - t0,
                           step=step)
    return path


def manifest_entry_for(path: str) -> dict | None:
    """The manifest entry of one versioned-store file (by its directory +
    basename), or None when the file is outside any store / not listed —
    overwrite checkpoints and hand-copied files resolve to None, never
    raise."""
    name = os.path.basename(path)
    for entry in load_manifest(os.path.dirname(path) or ".")["entries"]:
        if entry.get("file") == name:
            return entry
    return None


def cursor_for(path: str) -> dict | None:
    """The data cursor ``save_versioned(cursor=...)`` stamped next to this
    checkpoint, or None (pre-cursor manifests, non-store files). The resume
    prologue of every trainer consults this so the batch stream restarts where
    the checkpoint's stream actually stopped (DESIGN.md §26)."""
    entry = manifest_entry_for(path)
    return dict(entry["cursor"]) if entry and entry.get("cursor") else None


def check_cursor_resume(path: str, *, seed: int, step: int,
                        start_epoch: int | None = None) -> str | None:
    """Cross-check a resume target's manifest cursor against what the trainer
    is about to do; returns a log-worthy warning on mismatch, None when
    consistent or when no ``kind: "epoch"`` cursor exists (stream cursors are
    the :class:`data.stream.StreamLoader`'s to verify — it RAISES, because a
    streaming mismatch silently feeds different bytes; here the permutation is
    re-derived from ``(seed, epoch)`` regardless, so a mismatch means the
    RESUMING CONFIG disagrees with the saving one and deserves a warning, not
    a refusal)."""
    cursor = cursor_for(path)
    if not cursor or cursor.get("kind") != "epoch":
        return None
    problems = []
    if int(cursor.get("seed", seed)) != int(seed):
        problems.append(f"cursor seed {cursor.get('seed')} != config seed {seed} "
                        f"(the resumed epochs will reshuffle)")
    if int(cursor.get("step", step)) != int(step):
        problems.append(f"cursor step {cursor.get('step')} != checkpoint step "
                        f"{step} (manifest drifted from its file)")
    if (start_epoch is not None and cursor.get("epoch") is not None
            and int(cursor["epoch"]) != int(start_epoch)):
        problems.append(f"cursor epoch {cursor['epoch']} != derived start epoch "
                        f"{start_epoch} (a different batch size than the saving "
                        f"run?)")
    if not problems:
        return None
    return ("resume cursor mismatch for " + os.path.basename(path) + ": "
            + "; ".join(problems))


def newest_valid_checkpoint(dir_path: str) -> str | None:
    """Newest-first scan of a versioned store, returning the first checkpoint whose
    bytes verify — against the manifest's sha256 when the store has one, by msgpack
    decode-validation otherwise (a hand-assembled directory of ``ckpt_*.msgpack``
    still resolves). Torn/missing files are skipped, not raised: the caller is a
    restart path and wants the best surviving artifact, or None."""
    if not os.path.isdir(dir_path):
        return None
    entries = sorted(load_manifest(dir_path)["entries"],
                     key=lambda e: e["step"], reverse=True)
    if entries:
        for e in entries:
            if _entry_verifies(dir_path, e):
                return os.path.join(dir_path, e["file"])
        return None
    candidates = sorted((f for f in os.listdir(dir_path)
                         if f.startswith(_VERSIONED_PREFIX)
                         and f.endswith(_VERSIONED_SUFFIX)), reverse=True)
    for name in candidates:
        path = os.path.join(dir_path, name)
        try:
            _decode_msgpack(path)
            return path
        except (CheckpointCorrupt, OSError):
            continue
    return None


def _entry_verifies(dir_path: str, entry: dict) -> bool:
    path = os.path.join(dir_path, entry.get("file", ""))
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    return hashlib.sha256(data).hexdigest() == entry.get("sha256")


def newest_healthy_checkpoint(dir_path: str, *,
                              before_step: int | None = None) -> str | None:
    """The resume scan every supervised rollback goes through: the newest
    checkpoint that is NOT health-stamped-unclean — stamped-clean and legacy
    unstamped entries (old manifests stay loadable, and a guard-off run's
    newer progress must not be discarded in favor of an older stamp) rank
    purely by step; only entries a ``--guard`` run explicitly stamped
    ``clean: false`` are skipped. When nothing else survives, fall back to
    :func:`newest_valid_checkpoint` (an unclean checkpoint beats no resume at
    all, and the caller's skip window makes even that safe to replay from).

    ``before_step`` additionally excludes entries at or past that step — the
    DESYNC rollback path: a cross-replica fingerprint mismatch at step S
    indicts the step-S state, whose checkpoint is already durable and (the
    per-process anomaly counters cannot see divergence) clean-STAMPED, so the
    supervisor must roll back strictly before it.

    This supersedes blind newest-valid in resume paths: ``_newest_valid``'s
    old behavior trusted the newest decodable checkpoint even when the run
    that wrote it was already diverging — the exact state a rollback must NOT
    land on (regression-pinned in ``tests/test_anomaly.py``). Checksums are
    verified against the manifest exactly like :func:`newest_valid_checkpoint`
    (torn writes are skipped, never raised)."""
    if not os.path.isdir(dir_path):
        return None
    entries = sorted(load_manifest(dir_path)["entries"],
                     key=lambda e: e["step"], reverse=True)
    if not entries:
        return newest_valid_checkpoint(dir_path)    # manifest-less fallback
    for e in entries:
        if before_step is not None and e.get("step", 0) >= before_step:
            continue                                # indicted by the mismatch
        if (e.get("health") or {}).get("clean") is False:
            continue                                # a known-diverging save
        if _entry_verifies(dir_path, e):
            return os.path.join(dir_path, e["file"])
    return newest_valid_checkpoint(dir_path)


class AsyncCheckpointer:
    """Write-behind checkpointing: serialization + disk IO run on a background
    thread so the train loop only pays the device→host fetch (which a synchronous
    ``save_train_state`` pays anyway — the copy must happen before the next donated
    step invalidates the buffers).

    Semantics match the reference's overwrite-in-place policy (reference
    ``src/train.py:84-85``): writes to the SAME path coalesce — while one write is in
    flight, newer states replace the queued one instead of piling up (an epoch can
    outpace the disk; only the newest state matters when the file is an overwrite
    target). Distinct paths never coalesce. Writes stay atomic (tmp + rename) and
    process-0 gated; ``flush()`` drains the queue and re-raises the first background
    error. Usable as a context manager (``with AsyncCheckpointer() as ck: ...`` —
    exit flushes).

    ``tele`` (a ``TelemetryWriter``) makes each completed background write emit a
    ``checkpoint`` event carrying bytes, write seconds, and how many queued states
    the write coalesced away — the async-policy number nothing else can observe.
    Emission happens on the worker thread; the writer is thread-safe."""

    def __init__(self, tele=None):
        self._pending: dict[str, object] = {}        # path -> newest host state
        self._coalesced: dict[str, int] = {}         # path -> overwrites since last write
        self._lock = threading.Lock()
        self._work = queue.Queue()                   # paths with pending data
        self._error: BaseException | None = None
        self._thread: threading.Thread | None = None
        self._tele = tele

    def _worker(self) -> None:
        while True:
            path = self._work.get()
            if path is None:
                return
            with self._lock:
                state = self._pending.pop(path, None)
                coalesced = self._coalesced.pop(path, 0)
            if state is None:                        # coalesced away
                continue
            try:
                t0 = time.perf_counter()
                data = serialization.to_bytes(state)
                _atomic_write(path, data)
                _emit_checkpoint_event(
                    self._tele, op="save", path=path, kind="full",
                    nbytes=len(data), wall_s=time.perf_counter() - t0,
                    step=int(state["step"]), background=True,
                    coalesced=coalesced)
            except BaseException as e:               # surfaced on flush()
                with self._lock:
                    if self._error is None:
                        self._error = e

    def save_train_state(self, path: str, state: TrainState) -> None:
        """Drop-in for module-level ``save_train_state``, minus the disk wait."""
        if jax.process_index() != 0:
            return
        state_h = jax.device_get(state)              # the only on-thread cost
        if self._thread is None:
            self._thread = threading.Thread(target=self._worker, daemon=True,
                                            name="async-checkpoint")
            self._thread.start()
        with self._lock:
            coalesced = path in self._pending
            if coalesced:
                self._coalesced[path] = self._coalesced.get(path, 0) + 1
            self._pending[path] = _state_dict_for_save(state_h)
        if not coalesced:
            self._work.put(path)

    def flush(self) -> None:
        """Block until every queued write is durable; re-raise background errors."""
        if self._thread is not None:
            self._work.put(None)
            self._thread.join()
            self._thread = None
        err, self._error = self._error, None
        if err is not None:
            raise err

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.flush()
        return False


class SyncSaver:
    """Synchronous saver with the AsyncCheckpointer's call surface (save + flush),
    so the trainers hold ONE saver object either way — plus per-save ``checkpoint``
    telemetry (bytes + wall seconds) the bare module function cannot emit."""

    def __init__(self, tele=None):
        self._tele = tele

    def save_train_state(self, path: str, state: TrainState) -> None:
        t0 = time.perf_counter()
        save_train_state(path, state)
        if self._tele is not None and self._tele.enabled:
            _emit_checkpoint_event(self._tele, op="save", path=path, kind="full",
                                   nbytes=_path_bytes(path),
                                   wall_s=time.perf_counter() - t0,
                                   step=int(state.step))

    def flush(self) -> None:
        """Writes are already durable — parity no-op with the async surface."""


def make_saver(async_: bool = False, tele=None):
    """The trainers' one saver factory: write-behind or synchronous, both emitting
    ``checkpoint`` telemetry events through ``tele`` and both flush()-able."""
    return AsyncCheckpointer(tele=tele) if async_ else SyncSaver(tele=tele)


def save_params(path: str, params) -> None:
    """Final params-only export (≙ rank-0 ``torch.save(model.state_dict(), 'model.pt')``,
    reference src/train_dist.py:163-164). Process-0 gated."""
    if jax.process_index() != 0:
        return
    _atomic_write(path, serialization.to_bytes(jax.device_get(params)))


def load_params(path: str, reference_params):
    with open(path, "rb") as f:
        return serialization.from_bytes(reference_params, f.read())


def load_params_or_state(path: str, reference_params):
    """Load model params from ``path``, accepting either a full TrainState
    msgpack (train.lm's ``model_lm.ckpt``) or a params-only export
    (:func:`save_params`). The one loader behind every serving/bench surface
    that takes a ``--checkpoint`` — new checkpoint layouts are taught here,
    not per caller."""
    reference_params = jax.device_get(reference_params)
    raw = _decode_msgpack(path)
    if isinstance(raw, dict) and "params" in raw:
        return serialization.from_state_dict(reference_params, raw["params"])
    return serialization.from_state_dict(reference_params, raw)
