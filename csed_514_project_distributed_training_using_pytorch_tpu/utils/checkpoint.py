"""Checkpointing: pytree save AND the restore path the reference lacks.

The reference has three write-only checkpoint sites and no load code anywhere (SURVEY.md §5):
periodic ``torch.save`` of model+optimizer state every ``log_interval`` batches, overwriting
in place (reference ``src/train.py:84-85``), and a rank-0-only final model save
(``src/train_dist.py:163-164``, with the DDP unwrap at ``:116`` giving clean keys — moot here,
since there is no wrapper object to unwrap). This module reproduces both policies over a
single msgpack-serialized pytree (flax serialization — the ``torch.save`` zip+pickle analog,
but deterministic and pickle-free), gates writes to process 0, makes them atomic
(tmp + rename), and adds ``restore_train_state`` / ``load_params`` so training can actually
resume.
"""

from __future__ import annotations

import os

import jax
from flax import serialization

from csed_514_project_distributed_training_using_pytorch_tpu.train.step import TrainState


def _atomic_write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def save_train_state(path: str, state: TrainState) -> None:
    """Full model+optimizer checkpoint (≙ the reference's model.pth + optimizer.pth pair,
    src/train.py:84-85, as one file). Process-0 gated; no-op elsewhere."""
    if jax.process_index() != 0:
        return
    state = jax.device_get(state)
    _atomic_write(path, serialization.to_bytes(state._asdict()))


def restore_train_state(path: str, reference_state: TrainState) -> TrainState:
    """The resume path the reference is missing. ``reference_state`` supplies the pytree
    structure/shapes (e.g. a freshly-initialized state)."""
    with open(path, "rb") as f:
        restored = serialization.from_bytes(reference_state._asdict(), f.read())
    return TrainState(**restored)


def restore_for_resume(path: str, reference_state: TrainState, *,
                       process_index: int, process_count: int,
                       steps_per_epoch: int):
    """Shared resume prologue of the distributed and composed trainers: process-0
    restore, full-state broadcast to the fleet (the resume analog of DDP's initial
    param broadcast — checkpoints are process-0-gated writes, so on a fleet without a
    shared filesystem only process 0 can read one back), and start-epoch derivation.

    Returns ``(state, start_epoch, warning)`` where ``warning`` is a log-worthy
    message when the checkpoint's step count is not a whole number of THIS config's
    epochs — the tell-tale of a mid-epoch checkpoint or a checkpoint written under a
    different batch size (the step counter is the only progress metadata stored)."""
    state = reference_state
    if process_index == 0:
        state = restore_train_state(path, state)
    if process_count > 1:
        import numpy as np
        from jax.experimental import multihost_utils
        state = jax.tree_util.tree_map(
            np.asarray, multihost_utils.broadcast_one_to_all(state))
    spe = max(steps_per_epoch, 1)
    start_epoch = int(state.step) // spe
    warning = None
    if int(state.step) % spe:
        warning = (f"checkpoint step {int(state.step)} is not a multiple of "
                   f"steps_per_epoch {spe} — a mid-epoch checkpoint, or one written "
                   f"under a different batch size; resuming at epoch {start_epoch} "
                   f"replays the partial epoch")
    return state, start_epoch, warning


def save_params(path: str, params) -> None:
    """Final params-only export (≙ rank-0 ``torch.save(model.state_dict(), 'model.pt')``,
    reference src/train_dist.py:163-164). Process-0 gated."""
    if jax.process_index() != 0:
        return
    _atomic_write(path, serialization.to_bytes(jax.device_get(params)))


def load_params(path: str, reference_params):
    with open(path, "rb") as f:
        return serialization.from_bytes(reference_params, f.read())
