"""Checkpointing: pytree save AND the restore path the reference lacks.

The reference has three write-only checkpoint sites and no load code anywhere (SURVEY.md §5):
periodic ``torch.save`` of model+optimizer state every ``log_interval`` batches, overwriting
in place (reference ``src/train.py:84-85``), and a rank-0-only final model save
(``src/train_dist.py:163-164``, with the DDP unwrap at ``:116`` giving clean keys — moot here,
since there is no wrapper object to unwrap). This module reproduces both policies over a
single msgpack-serialized pytree (flax serialization — the ``torch.save`` zip+pickle analog,
but deterministic and pickle-free), gates writes to process 0, makes them atomic
(tmp + rename), and adds ``restore_train_state`` / ``load_params`` so training can actually
resume.
"""

from __future__ import annotations

import os

import jax
from flax import serialization

from csed_514_project_distributed_training_using_pytorch_tpu.train.step import TrainState


def _atomic_write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def save_train_state(path: str, state: TrainState) -> None:
    """Full model+optimizer checkpoint (≙ the reference's model.pth + optimizer.pth pair,
    src/train.py:84-85, as one file). Process-0 gated; no-op elsewhere."""
    if jax.process_index() != 0:
        return
    state = jax.device_get(state)
    _atomic_write(path, serialization.to_bytes(state._asdict()))


def restore_train_state(path: str, reference_state: TrainState) -> TrainState:
    """The resume path the reference is missing. ``reference_state`` supplies the pytree
    structure/shapes (e.g. a freshly-initialized state)."""
    with open(path, "rb") as f:
        restored = serialization.from_bytes(reference_state._asdict(), f.read())
    return TrainState(**restored)


def save_params(path: str, params) -> None:
    """Final params-only export (≙ rank-0 ``torch.save(model.state_dict(), 'model.pt')``,
    reference src/train_dist.py:163-164). Process-0 gated."""
    if jax.process_index() != 0:
        return
    _atomic_write(path, serialization.to_bytes(jax.device_get(params)))


def load_params(path: str, reference_params):
    with open(path, "rb") as f:
        return serialization.from_bytes(reference_params, f.read())
