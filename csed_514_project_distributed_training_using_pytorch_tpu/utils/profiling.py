"""Optional tracing/profiling.

The reference's only instrument is coarse wall-clock (``t0 = time.time()``, reference
``src/train.py:10,99``; SURVEY.md §5 "tracing/profiling") — kept, in ``utils.metrics.Stopwatch``,
because it *is* the baseline metric. This module adds what the reference lacks: an opt-in
``jax.profiler`` device trace (TPU timeline incl. ICI collectives, viewable in
TensorBoard/Perfetto) behind a flag, costing nothing when disabled.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def maybe_profile(enabled: bool, log_dir: str):
    """Capture a jax.profiler trace of the enclosed block when ``enabled``."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the device trace (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield
