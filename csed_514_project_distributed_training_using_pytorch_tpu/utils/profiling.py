"""Optional tracing/profiling.

The reference's only instrument is coarse wall-clock (``t0 = time.time()``, reference
``src/train.py:10,99``; SURVEY.md §5 "tracing/profiling") — kept, in ``utils.metrics.Stopwatch``,
because it *is* the baseline metric. This module adds what the reference lacks: an opt-in
``jax.profiler`` device trace (TPU timeline incl. ICI collectives, viewable in
TensorBoard/Perfetto) behind a flag, costing nothing when disabled. The structured
(always-parseable, per-run) counterpart is ``utils/telemetry.py`` — the trace is for
timeline forensics, telemetry for the numbers.
"""

from __future__ import annotations

import contextlib
import os

import jax

from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics


@contextlib.contextmanager
def maybe_profile(enabled: bool, log_dir: str):
    """Capture a jax.profiler trace of the enclosed block when ``enabled``.

    Process-0 gated INTERNALLY (one trace per fleet, not one per host — every rank
    tracing would multiply IO and clobber nothing useful), creates ``log_dir`` if
    missing, and logs the trace path so a run's stdout says where its timeline went.
    """
    if not enabled or not metrics.is_logging_process():
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        metrics.log(f"Saved profiler trace to {log_dir}")


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the device trace (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield
