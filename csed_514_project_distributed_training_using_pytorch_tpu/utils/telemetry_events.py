"""THE telemetry event-kind registry: every JSONL event kind this repo emits.

One owner for the schema surface that PR 8's drift footer checks against. Before
this module existed, ``tools/telemetry_report.py::KNOWN_EVENTS`` was a hand-kept
frozenset that had to be updated every time a writer grew a new ``"event"`` kind
— the exact schema-drift failure mode the footer exists to surface, one hop
earlier. Now:

- every emitter's kind must appear here (enforced statically by the
  ``telemetry-schema`` checker in ``tools/graftlint`` — an ``{"event": "..."}``
  literal anywhere in the package or tools with a kind not in this registry is
  a lint error, so a writer cannot drift from the report tools at commit time);
- ``tools/telemetry_report.py::KNOWN_EVENTS`` is DERIVED from this module, so
  the footer can never disagree with the emitters' sanctioned vocabulary.

Kinds map to a one-line producer note (kept next to the kind so adding an event
forces writing down who emits it). The full field-level schemas live with the
producers — ``utils/telemetry.py`` event helpers, ``serving/router.py``,
``resilience/supervisor.py``, ``utils/trace.py`` — this registry pins only the
``"event"`` vocabulary, the key the readers dispatch on.

This module is stdlib-only and must stay backend-free: ``tools/graftlint``
reads it (by AST, never by import) and the report CLIs import it; neither may
pay for — let alone initialize — a jax backend.
"""

from __future__ import annotations

# kind -> producer (one line). A PURE dict literal: tools/graftlint extracts the
# keys by parsing this file's AST (no import, no jax), so computed keys,
# unpacking, or concatenation here would be invisible to the lint gate.
EVENT_KINDS: dict[str, str] = {
    # -- training/bench telemetry (utils/telemetry.py helpers) ------------------
    "manifest": "once per run: config/mesh/device/version snapshot",
    "compile": "AOT compile timing + cost_analysis of one program",
    "epoch": "per-epoch wall/execute/eval/data split + losses",
    "data": "per-epoch streaming-loader ledger: batches/stall wall/cursor (data/stream.py)",
    "health": "per-epoch grad-norm/loss accumulators (train/step.py carry)",
    "mfu": "steady-state achieved FLOPs and HBM bytes vs chip peak",
    "bench": "one bench*.py measurement line",
    # -- serving: engine/server (utils/telemetry.py serve helpers) --------------
    "serve": "one served request: TTFT/TPOT/queue-wait/e2e (serving/server.py)",
    "serve_config": "once per serving run: engine/model knobs (serving/server.py)",
    "serve_summary": "once per serving run at drain: aggregates + percentiles",
    "prefill": "one completed prompt prefill: chunks/tokens/cache-hit/wall",
    "spec": "one speculative verify step: slots, proposed/accepted/emitted",
    "shed": "one overload-shed decision: tenant, quota/refused/displaced reason",
    "tenant_summary": "one tenant's drain ledger: counts/percentiles/preemptions/slo",
    "kv_pages": "paged-KV pool ledger at drain: in_use/shared/refusals/COW (serving/server.py)",
    # -- serving: fleet router (serving/router.py via utils/jsonl.py) -----------
    "route": "one routed request: replica, affinity, redispatches, finish",
    "replica": "replica lifecycle transition: start/fail/restart/dead",
    "router_config": "once per router run: fleet shape + knobs",
    "router_summary": "once per router run at drain: fleet-wide counts",
    "fleet_snapshot": "periodic load signal: queue depth/age, per-replica occupancy",
    "scale": "autoscaler action: up/down/reload (+reload_drain bookkeeping)",
    "eject": "straggler ejection lifecycle: eject (degraded) / probe (back to ready)",
    "hedge": "one speculative re-dispatch: request, second replica, deadline",
    "chaos": "one injected network fault (resilience/netfaults.py proxy schedule)",
    "tier": "replica tier membership at ready: role + handoff port (disaggregation)",
    "kv_handoff": "one prefill→decode KV plane handoff: bytes/wall/ok (serving/tiers.py)",
    # -- resilience (resilience/supervisor.py, utils/checkpoint.py) -------------
    "checkpoint": "one checkpoint save/restore: op/kind/bytes/wall",
    "restart": "supervisor restart: attempt, reason, backoff, resume cursor",
    "anomaly": "per-epoch --guard verdict: anomalies/skipped/EMA/fingerprint",
    "preempt": "cooperative SIGTERM stop at an epoch boundary (exit 75)",
    "supervise_summary": "once per supervised run: final status + attempts",
    # -- continuous deployment (deploy/promoter.py) -----------------------------
    "promote": "promotion-gate lifecycle: candidate seen/qualified/rejected/promoted/rolled_back",
    "canary": "one canary window verdict: attainment + sampled-token NLL vs fleet",
    # -- planner (plan/) --------------------------------------------------------
    "plan": "once per --plan run: chosen layout + predicted cost",
    "autotune": "one empirically trialed candidate: predicted vs measured",
    # -- run-level observability (obs/) -----------------------------------------
    "slo": "SLO attainment vs spec: serving drain (server/router via obs/slo.py)",
    "goodput": "exclusive wall-time decomposition of a training run (obs/goodput.py)",
    "bench_guard": "one perf-gate metric: median-of-N vs baseline (tools/bench_guard.py)",
    # -- distributed tracing (utils/trace.py) -----------------------------------
    "span": "one trace span (rendered by tools/trace_report.py, passed over here)",
    # -- loss-curve metrics.jsonl kinds (utils/metrics.py history rows) ---------
    "train": "per-epoch train loss row (reference-parity loss curve)",
    "test": "per-epoch test loss/accuracy row (reference-parity loss curve)",
}

# The derived set the report tools dispatch on (tools/telemetry_report.py
# re-exports this as its KNOWN_EVENTS).
KNOWN_EVENTS: frozenset[str] = frozenset(EVENT_KINDS)


def describe(kind: str) -> str | None:
    """Producer note for ``kind``, or None for an unregistered kind."""
    return EVENT_KINDS.get(kind)
