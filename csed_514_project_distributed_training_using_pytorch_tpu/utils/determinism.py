"""Determinism + replica-consistency checks.

The reference's determinism story is fixed seeds and ``cudnn.enabled = False`` (reference
``src/train.py:19-21``, ``src/train_dist.py:135-137``; SURVEY.md §5 "race detection") — there
is no check that DDP replicas actually stayed in sync. Here determinism is structural
(explicit PRNG-key threading; one compiled program), and this module adds the missing check:
a cross-process parameter fingerprint comparison, the SPMD analog of a desynced-replica "race
detector". Desync cannot arise within one jit'd SPMD program, but it *can* arise from host-side
bugs (different seeds per process, divergent restore paths), which is what this catches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def param_fingerprint(params) -> float:
    """Order-independent scalar digest of a params pytree (sum of |p| over all leaves)."""
    leaves = jax.tree_util.tree_leaves(params)
    total = sum(jnp.sum(jnp.abs(leaf.astype(jnp.float32))) for leaf in leaves)
    return float(jax.device_get(total))


def assert_replicas_synced(params, *, atol: float = 0.0) -> None:
    """Raise if any process holds a different parameter fingerprint.

    No-op on a single process. Multi-host: every process must call this (it is a collective —
    uses ``process_allgather``).
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    mine = np.asarray([param_fingerprint(params)])
    everyone = np.asarray(multihost_utils.process_allgather(mine)).reshape(-1)
    if not np.all(np.abs(everyone - everyone[0]) <= atol):
        raise RuntimeError(
            f"replica parameter desync detected across processes: {everyone.tolist()}")
