"""Structured run telemetry: machine-readable record of WHAT ran and WHERE the time went.

The reference's entire observability surface is ``t0 = time.time()`` plus print lines
(SURVEY.md §5), faithfully reproduced in ``utils/metrics.py`` — which means nothing
downstream can answer "what mesh was that run on", "how much of epoch 1 was XLA
compile", or "was training healthy" without parsing stdout. This module is the
structured layer every perf PR proves its numbers through:

- **events** — one JSON object per line (strict JSONL: non-finite floats become
  ``null``), each typed by an ``"event"`` key. The types and their producers:

  =============  =====================================================================
  ``manifest``   once per run: config snapshot, mesh axes/shape, device kind+count,
                 process count, jax/jaxlib/python versions, precision flags
  ``compile``    AOT compile timing of the epoch program (``jit(...).lower().compile()``)
                 plus its ``cost_analysis()`` FLOPs
  ``epoch``      per epoch: wall/execute/eval/data-feed seconds, examples/s,
                 compile_s, flops_per_step, train/val loss
  ``health``     per epoch when ``--health-stats`` is on: grad-norm mean/max, loss
                 min/max/mean, param norm — accumulated INSIDE the compiled scan
                 (see ``train/step.py``), zero extra host syncs on the hot path
  ``mfu``        steady-state throughput: measured step seconds vs compiled FLOPs vs
                 the chip's published peak (``utils/benchmarks.py``)
  ``bench``      one line per ``bench*.py`` measurement (same schema, comparable to
                 training runs in ``tools/telemetry_report.py``)
  ``serve``      one line per served request (``serving/server.py``): TTFT/TPOT,
                 queue wait, e2e latency, tokens/s, finish reason
  ``serve_summary``  once per serving run at drain: request counts, aggregate
                 tokens/s, slot occupancy, p50/p95/p99 latency percentiles, and
                 the admission queue's snapshot (depth/oldest-age/rejected)
  ``route``      written by the fleet router (``serving/router.py``, via the
                 jax-free ``utils.jsonl.JsonlWriter`` — same schema, same
                 reader): one line per routed request — replica, affinity hit,
                 redispatch count, finish, latencies
  ``replica``    router lifecycle record: a replica start/fail/restart/dead
                 transition with reason (crash/hung), exit code, backoff
  ``router_summary``  once per router run at drain: fleet-wide counts,
                 redispatch/duplicate totals, affinity hit rate, per-replica
                 dispatch table, aggregated replica prefix-cache stats
  ``checkpoint`` one line per checkpoint save/restore (``utils/checkpoint.py``
                 savers + ``restore_for_resume``): op, path, full/sharded kind,
                 bytes, wall seconds, step, and — for the write-behind saver —
                 how many queued states the write coalesced away
  ``preempt``    once, when a ``--handle-preemption`` trainer honors SIGTERM at an
                 epoch boundary: the stop epoch/step and the durable checkpoint
                 (the run then exits 75 — resilience/preemption.py)
  ``restart``    written by the fleet supervisor (``resilience/supervisor.py``,
                 via its own jax-free writer — same schema, same reader): attempt,
                 crash/hung/timeout reason, exit code, the checkpoint the next
                 attempt resumes from, backoff seconds
  ``plan``       once per ``--plan`` run (``plan/``): the chosen mesh/microbatch
                 split, its source (auto/tune/file), predicted step seconds +
                 per-chip bytes, and how many candidates were ranked
  ``autotune``   one line per empirically trialed candidate (``--plan tune``,
                 ``plan/autotune.py``): mesh, analytical rank, predicted vs
                 measured step seconds, AOT compile seconds, compiled FLOPs
  =============  =====================================================================

- **writer** — ``TelemetryWriter`` is process-0 gated (a fleet writes ONE file) and
  atomic: every emit rewrites the file via tmp+rename (the checkpoint writer's
  ``_atomic_write``), so a reader never observes a torn line and a killed run keeps
  every event emitted before the kill. Event volume is O(epochs), not O(steps) —
  rewriting is cheap by construction, because anything per-step would be a host sync
  the compiled-epoch design exists to delete. The serving path is the exception:
  its volume is O(requests), so ``TelemetryWriter(path, stream=True)`` appends one
  flushed line per emit instead of rewriting — a kill can tear at most the final
  line, which ``metrics.load_metrics_jsonl`` tolerates (torn-tail rule).

Read side: ``utils.metrics.load_metrics_jsonl`` (shared with the loss-curve JSONL);
renderer: ``tools/telemetry_report.py``.
"""

from __future__ import annotations

import dataclasses
import math
import platform
import threading
import time

import jax

from csed_514_project_distributed_training_using_pytorch_tpu.utils import metrics as M

SCHEMA_VERSION = 1


def _finite(x):
    """Strict-JSONL rule (same as ``metrics.save_metrics_jsonl``): non-finite → None."""
    if x is None:
        return None
    x = float(x)
    return x if math.isfinite(x) else None


def _sanitize(obj):
    """Deep-copy ``obj`` with every non-finite float mapped to None — a diverged run
    (NaN loss, inf grad norm) must still serialize as valid JSON."""
    if isinstance(obj, float):
        return _finite(obj)
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


class TelemetryWriter:
    """Append-only event stream as atomically-(re)written JSONL; process-0 gated.

    ``path`` empty/None disables everything — every ``emit`` is then a no-op, so
    trainers call unconditionally and the off path costs a truthiness check.

    ``stream=True`` switches to append-per-emit (one flushed line each event, file
    truncated at the first emit): the serving path's mode, where event volume is
    O(requests) and the atomic full rewrite would go quadratic. A kill can tear at
    most the trailing line; the shared reader skips exactly that.

    History preservation (``preserve=True``, non-stream mode): a NEW writer
    on an EXISTING path loads the prior events first (through the guarded
    reader — a crashed writer's torn final line is dropped) and every rewrite
    carries them. This is the ``JsonlWriter`` append doctrine applied to the
    rewrite mode, for RESUMED runs only: a supervised restart re-runs the
    same trainer command — same ``--telemetry`` path — and the crashed
    attempt's events must survive into the resumed run's file, or run-level
    accounting (``obs/goodput.py``: replayed-epoch badput needs the FIRST
    attempt's epoch history) is impossible. Attempts stay distinguishable:
    each one opens with its own ``manifest`` event. The trainers pass
    ``preserve=bool(config.resume_from)`` — a FRESH run on a stale path
    still truncates (two unrelated runs must not blend into one fake
    multi-attempt history).
    """

    def __init__(self, path: str | None, *, stream: bool = False,
                 preserve: bool = False):
        self.path = path or ""
        self.stream = bool(stream)
        self.preserve = bool(preserve)
        self._fh = None
        self._truncated = False       # stream mode: first open truncates, later
                                      # reopens (emit after close) append
        self._events: list[dict] = []
        self._loaded_history = False  # non-stream: prior-run events loaded once,
                                      # lazily (only the logging process reads)
        self._t0 = time.time()
        # emit() must be thread-safe: the write-behind checkpointer reports its
        # completed writes from its worker thread while the trainer keeps emitting
        # epoch events from the main one.
        self._emit_lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.path) and M.is_logging_process()

    def emit(self, event: dict) -> None:
        """Record one typed event; rewrite the JSONL atomically (default) or
        append+flush the one line (``stream=True``)."""
        if not self.enabled:
            return
        if "event" not in event:
            raise ValueError(f"telemetry event missing its 'event' type key: {event}")
        import json
        import os

        from csed_514_project_distributed_training_using_pytorch_tpu.utils.checkpoint import (
            _atomic_write,
        )

        row = dict(event)
        row.setdefault("t_s", round(time.time() - self._t0, 6))
        row = _sanitize(row)
        with self._emit_lock:
            if self.stream:
                # No in-memory event log here: stream mode exists for O(requests)
                # volume, and the disk line IS the record. Reopening after close()
                # appends — a writer shared across serving runs must never truncate
                # lines it already flushed.
                if self._fh is None:
                    os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                    self._fh = open(self.path, "a" if self._truncated else "w")
                    self._truncated = True
                self._fh.write(json.dumps(row, allow_nan=False) + "\n")
                self._fh.flush()
                return
            if not self._loaded_history:
                self._loaded_history = True
                if self.preserve and os.path.exists(self.path):
                    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
                        read_jsonl,
                    )
                    self._events = read_jsonl(self.path) + self._events
            self._events.append(row)
            payload = "".join(json.dumps(e, allow_nan=False) + "\n"
                              for e in self._events)
            _atomic_write(self.path, payload.encode())

    def close(self) -> None:
        """Release the stream-mode file handle (no-op otherwise)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def manifest_event(config=None, *, mesh=None, run_type: str = "") -> dict:
    """The once-per-run provenance record: config, topology, software versions.

    ``config`` is any of the frozen config dataclasses (snapshotted field-by-field);
    ``mesh`` the jax Mesh when the trainer has one (axis names + sizes).
    """
    try:
        import jaxlib
        jaxlib_version = jaxlib.__version__
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = None
    devs = jax.devices()
    ev = {
        "event": "manifest",
        "schema_version": SCHEMA_VERSION,
        "run_type": run_type or (type(config).__name__ if config is not None else ""),
        "unix_time": time.time(),
        "platform": devs[0].platform,
        "device_kind": getattr(devs[0], "device_kind", devs[0].platform),
        "device_count": len(devs),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
        "python_version": platform.python_version(),
    }
    if mesh is not None:
        ev["mesh"] = {"axis_names": list(mesh.axis_names),
                      "shape": {str(k): int(v) for k, v in mesh.shape.items()}}
    if config is not None and dataclasses.is_dataclass(config):
        cfg = dataclasses.asdict(config)
        ev["config"] = cfg
        ev["precision"] = {"bf16": bool(cfg.get("bf16", False)),
                           "jax_enable_x64": bool(jax.config.jax_enable_x64)}
    return ev


def _compiled_cost_value(compiled, key: str) -> float | None:
    """One positive value out of an AOT program's ``cost_analysis()`` dict —
    None when the backend doesn't report it."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):      # older jax: one dict per partition
        cost = cost[0] if cost else {}
    try:
        value = cost.get(key)
    except AttributeError:
        return None
    return float(value) if value and value > 0 else None


def compiled_flops(compiled) -> float | None:
    """Total FLOPs of ONE invocation of an AOT-compiled program, from XLA's
    ``cost_analysis()`` — None when the backend doesn't report them."""
    return _compiled_cost_value(compiled, "flops")


def compiled_bytes_accessed(compiled) -> float | None:
    """Total HBM bytes one invocation actually touches, from XLA's
    ``cost_analysis()`` ``bytes accessed`` — the BYTE-TRUE traffic of the
    compiled program (int8 operands priced at one byte, fusions not
    double-counted), as opposed to a dtype-naive estimate from tensor shapes.
    None when the backend doesn't report it."""
    return _compiled_cost_value(compiled, "bytes accessed")


def aot_compile(jit_fn, *args) -> tuple[object | None, dict | None]:
    """Time ``jit_fn.lower(*args).compile()`` — the compile/execute split.

    Returns ``(compiled, {"lower_s", "compile_s", "flops"})``; the caller should
    invoke ``compiled`` directly (the AOT program does not populate ``jit_fn``'s
    cache, so calling the jit object afterwards would compile twice). ``args`` may
    mix concrete arrays and ``jax.ShapeDtypeStruct``s. ``(None, None)`` when the
    callee has no ``.lower`` (the cached-sharding compile wrappers) or lowering
    fails — callers then fall back to the ordinary jit path with compile time
    folded into the first epoch.
    """
    try:
        t0 = time.perf_counter()
        lowered = jit_fn.lower(*args)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
    except Exception:
        return None, None
    return compiled, {"lower_s": lower_s, "compile_s": compile_s,
                      "flops": compiled_flops(compiled),
                      "bytes_accessed": compiled_bytes_accessed(compiled)}


def compile_event(fn_name: str, aot: dict, *, steps_per_call: int | None = None) -> dict:
    """The ``compile`` event for one AOT-timed program."""
    flops = aot.get("flops")
    return {
        "event": "compile",
        "fn": fn_name,
        "lower_s": _finite(aot.get("lower_s")),
        "compile_s": _finite(aot.get("compile_s")),
        "flops_per_call": _finite(flops),
        "steps_per_call": steps_per_call,
        "flops_per_step": _finite(flops / steps_per_call
                                  if flops and steps_per_call else None),
        "bytes_accessed_per_call": _finite(aot.get("bytes_accessed")),
        "bytes_accessed_per_step": _finite(
            aot["bytes_accessed"] / steps_per_call
            if aot.get("bytes_accessed") and steps_per_call else None),
    }


def epoch_event(epoch: int, *, examples: int, steps: int | None = None,
                wall_s: float | None = None, execute_s: float | None = None,
                eval_s: float | None = None, data_s: float | None = None,
                compile_s: float | None = None, flops_per_step: float | None = None,
                train_loss: float | None = None, val_loss: float | None = None,
                mfu: float | None = None) -> dict:
    """Per-epoch phase-timing record. ``execute_s`` is device execution of the epoch
    program (closed by a host fetch, SURVEY.md §7c); ``wall_s`` the whole epoch
    including host work; ``data_s`` index-plan/feed construction; ``compile_s`` the
    AOT epoch-program compile (constant per run, repeated per event so each line is
    self-contained)."""
    ex = _finite(execute_s)
    return {
        "event": "epoch",
        "epoch": int(epoch),
        "examples": int(examples),
        "steps": int(steps) if steps is not None else None,
        "wall_s": _finite(wall_s),
        "execute_s": ex,
        "eval_s": _finite(eval_s),
        "data_s": _finite(data_s),
        "compile_s": _finite(compile_s),
        "examples_per_s": _finite(examples / ex if ex else None),
        "steps_per_s": _finite(steps / ex if ex and steps else None),
        "flops_per_step": _finite(flops_per_step),
        "train_loss": _finite(train_loss),
        "val_loss": _finite(val_loss),
        "mfu": _finite(mfu),
    }


def data_event(epoch: int, *, batches: int, sequences: int,
               wait_s: float | None = None, throttle_s: float = 0.0,
               cursor: dict | None = None,
               stream_digest: int | None = None) -> dict:
    """Per-epoch streaming-loader ledger (``data/stream.py``): how many
    batches the epoch consumed, the seconds the consumer spent blocked on the
    loader (the goodput ``data_wait`` input, charged inside the epoch event's
    ``data_s``), the resume cursor the matching checkpoint manifest carries,
    and the epoch's stream CRC — the bitwise pin deterministic-resume tests
    compare across a kill/resume boundary."""
    return {
        "event": "data",
        "epoch": int(epoch),
        "batches": int(batches),
        "sequences": int(sequences),
        "wait_s": _finite(wait_s),
        "throttle_s": _finite(throttle_s),
        "cursor": dict(cursor) if cursor else None,
        "stream_digest": int(stream_digest) if stream_digest is not None else None,
    }


def health_event(epoch: int, health, steps: int, *,
                 param_norm: float | None = None) -> dict:
    """The ``health`` event from a ``train.step.HealthStats`` carry (host-fetched
    once per epoch). ``grad_norm`` is the per-step mean — the headline trajectory;
    min/max bound the epoch."""
    steps = max(int(steps), 1)
    return {
        "event": "health",
        "epoch": int(epoch),
        "steps": steps,
        "grad_norm": _finite(float(health.grad_norm_sum) / steps),
        "grad_norm_max": _finite(float(health.grad_norm_max)),
        "loss_min": _finite(float(health.loss_min)),
        "loss_max": _finite(float(health.loss_max)),
        "loss_mean": _finite(float(health.loss_sum) / steps),
        "param_norm": _finite(param_norm),
    }


def anomaly_event(epoch: int, guard, steps: int, *,
                  fingerprint: float | None = None, skip: str = "") -> dict:
    """The per-epoch ``anomaly`` event from a ``train.step.GuardState`` carry
    (host-fetched once per epoch with the losses — no extra syncs). Counters
    are CUMULATIVE for the attempt (a rollback resumes the healthy
    checkpoint's counters, so a resumed attempt restarts from its baseline);
    ``fingerprint`` is the cross-replica param fingerprint
    (``param_fingerprint``), ``skip`` the active ``--skip-steps`` windows."""
    import math as _math

    mean = float(guard.ema_mean)
    std = _math.sqrt(max(float(guard.ema_sq) - mean * mean, 0.0))
    return {
        "event": "anomaly",
        "epoch": int(epoch),
        "steps": int(steps),
        "anomalies": int(guard.anomalies),
        "nonfinite": int(guard.nonfinite),
        "spikes": int(guard.spikes),
        "skipped": int(guard.skipped),
        "clean_steps": int(guard.count),
        "first_anomaly_step": int(guard.first_anomaly_step),
        "last_anomaly_step": int(guard.last_anomaly_step),
        "grad_norm_ema": _finite(mean),
        "grad_norm_std": _finite(std),
        "fingerprint": _finite(fingerprint),
        "skip": skip,
    }


def _local_blocks(leaf):
    """This process's deduped addressable blocks of ``leaf`` as host arrays
    (sorted by global offset for a deterministic fold), or None when the
    local blocks do not cover the full logical array — the multi-host-sharded
    case, where per-process fingerprints would differ by construction."""
    import numpy as np

    if not hasattr(leaf, "addressable_shards"):
        return [np.asarray(leaf)]
    blocks: dict[tuple, object] = {}
    covered = 0
    for sh in leaf.addressable_shards:
        key = tuple(0 if s.start is None else int(s.start) for s in sh.index)
        if key in blocks:
            continue                     # a replica of an already-seen block
        data = np.asarray(sh.data)
        blocks[key] = data
        covered += data.size
    if covered != leaf.size:
        return None
    return [blocks[k] for k in sorted(blocks)]


def param_fingerprint(tree) -> float | None:
    """Cross-replica state fingerprint: the f32 per-leaf absolute-sum folded
    over this process's LOCAL view of the tree — cheap, deterministic, and
    identical across replicas iff their replicated state actually is.
    Deliberately NOT a jitted global reduction: on a multi-host fleet that
    would all-reduce, handing every process the identical (corruption
    included) scalar — the detector would be structurally blind. Host-local
    math means each process vouches only for the bytes it holds. Computed
    once per epoch at the sanctioned boundary fetch and compared by the
    supervisor's fingerprint-verify mode through the heartbeat files
    (``resilience/heartbeat.py::fingerprint_mismatch``) — post-update
    divergence (SDC, desync) is detected before the diverged state can be
    RESUMED as truth (the supervisor rolls back strictly past the mismatch
    step). Returns None when this process's addressable shards do not cover
    the full state (multi-host FSDP/TP: per-process fingerprints would differ
    by construction, and a beat without a fingerprint is simply not
    compared)."""
    import numpy as np

    total = np.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(tree):
        blocks = _local_blocks(leaf)
        if blocks is None:
            return None
        for data in blocks:
            total += np.abs(data.astype(np.float32)).sum(dtype=np.float32)
    return float(total)


def checkpoint_event(*, op: str, path: str, kind: str = "full",
                     nbytes: int | None = None, wall_s: float | None = None,
                     step: int | None = None, coalesced: int | None = None,
                     background: bool = False) -> dict:
    """One checkpoint save/restore (``utils/checkpoint.py``). ``op`` is ``"save"``
    or ``"restore"``; ``kind`` ``"full"`` (one msgpack file) or ``"sharded"``
    (per-process directory). ``coalesced`` counts the queued states a write-behind
    save absorbed before this write hit disk (async saver only)."""
    return {
        "event": "checkpoint",
        "op": op,
        "path": path,
        "kind": kind,
        "bytes": int(nbytes) if nbytes is not None else None,
        "wall_s": _finite(wall_s),
        "step": int(step) if step is not None else None,
        "background": bool(background),
        "coalesced": int(coalesced) if coalesced is not None else None,
    }


def preempt_event(*, epoch: int, step: int, checkpoint: str = "") -> dict:
    """A cooperative preemption stop (resilience/preemption.py): where the run
    halted and which checkpoint that progress is durable in."""
    return {
        "event": "preempt",
        "epoch": int(epoch),
        "step": int(step),
        "checkpoint": checkpoint,
    }


def plan_event(plan, *, candidates: int | None = None) -> dict:
    """The once-per-run ``plan`` record (``plan.apply_plan``): which layout the
    planner picked, from which source, at what predicted/measured cost.
    ``plan`` is a ``plan.artifact.Plan``; the full candidate table lives in the
    saved plan JSON — this line carries the decision, not the search."""
    predicted = plan.predicted or {}
    return {
        "event": "plan",
        "run_type": plan.run_type,
        "source": plan.source,
        "mesh": plan.mesh,
        "axes": dict(plan.axes),
        "fsdp": bool(plan.fsdp),
        "grad_accum": int(plan.grad_accum),
        "pipeline_microbatches": int(plan.pipeline_microbatches),
        "device_count": int(plan.device_count),
        "global_batch": int(plan.global_batch),
        "predicted_step_s": _finite(predicted.get("step_s")),
        "predicted_bytes_per_chip": _finite(predicted.get("total_bytes_per_chip")),
        "measured_step_s": _finite(plan.measured_step_s),
        "candidates": (int(candidates) if candidates is not None
                       else len(plan.candidates)),
    }


def autotune_event(*, mesh: str, fsdp: bool, grad_accum: int, microbatches: int,
                   rank: int, predicted_step_s: float | None,
                   measured_step_s: float | None = None,
                   compile_s: float | None = None,
                   flops_per_step: float | None = None) -> dict:
    """One empirically trialed candidate (``plan/autotune.py``): the analytical
    prediction next to the measured fact, so the cost model is auditable from
    the telemetry alone. ``measured_step_s`` None = the trial harness could not
    build this layout (analytical estimate retained in the ranking)."""
    return {
        "event": "autotune",
        "mesh": mesh,
        "fsdp": bool(fsdp),
        "grad_accum": int(grad_accum),
        "microbatches": int(microbatches),
        "rank": int(rank),
        "predicted_step_s": _finite(predicted_step_s),
        "measured_step_s": _finite(measured_step_s),
        "compile_s": _finite(compile_s),
        "flops_per_step": _finite(flops_per_step),
    }


def _l2_norm_program(tree):
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.optim import (
        global_l2_norm as _norm,
    )

    return _norm(tree)


_l2_norm_jit = jax.jit(_l2_norm_program)


def global_l2_norm(tree) -> float:
    """Global L2 norm of a pytree (param-norm for the health event; called once per
    epoch, off the hot path; the formula is ``ops.optim.global_l2_norm`` — one
    owner with the clip and the grad-norm accumulator). Runs as one jitted program
    so sharded leaves (TP/FSDP states) reduce via compiler-inserted collectives —
    eager ops on non-fully-addressable arrays would fail on a multi-host fleet.

    On a multi-host fleet this IS an SPMD computation: every process must enter it.
    The trainers therefore compute it whenever ``--health-stats`` is on — outside
    the process-0 emission gate — and only process 0 emits the event."""
    return float(jax.device_get(_l2_norm_jit(tree)))


def estimate_mfu(flops_per_step: float | None, step_s: float | None,
                 bytes_per_step: float | None = None) -> dict:
    """Model-FLOP-utilization against the chip's published bf16 peak.

    ``flops_per_step`` comes from ``compiled.cost_analysis()``, which prices the
    post-SPMD-partitioning PER-DEVICE module — each device's share of the step —
    so ``mfu`` divides the per-device achieved rate by ONE chip's peak. That is
    the same quantity ``bench.py`` reports (global analytic FLOPs over
    ``peak * devices``): the two conventions agree when work divides evenly, so
    A-vs-B comparisons across telemetry and bench files compare like with like.
    Uses ``utils.benchmarks.peak_flops`` (the committed spec-sheet table); ``mfu``
    is None off-TPU or on an unknown device kind — never a guess.

    ``bytes_per_step`` (``compiled_bytes_accessed`` / steps — XLA's own count
    of the bytes the compiled step ACTUALLY touches, so an int8 operand is
    priced at one byte) adds the bandwidth side: achieved bytes/s and the HBM
    roofline fraction ``hbm_frac``. Quantization moves this number, which is
    why it must be measured, not derived from a parameter count at an assumed
    dtype."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        peak_flops,
        peak_hbm_bytes,
    )

    devs = jax.devices()
    device_kind = getattr(devs[0], "device_kind", devs[0].platform)
    achieved = (flops_per_step / step_s if flops_per_step and step_s else None)
    on_tpu = devs[0].platform == "tpu"
    peak = peak_flops(device_kind) if on_tpu else None
    bw = (bytes_per_step / step_s if bytes_per_step and step_s else None)
    peak_bw = peak_hbm_bytes(device_kind) if on_tpu else None
    return {
        "flops_per_step": _finite(flops_per_step),
        "step_s": _finite(step_s),
        "achieved_flops_per_s_per_device": _finite(achieved),
        "device_kind": device_kind,
        "devices": len(devs),
        "peak_flops_per_s_per_device": _finite(peak),
        "mfu": _finite(achieved / peak if achieved and peak else None),
        "bytes_accessed_per_step": _finite(bytes_per_step),
        "achieved_bytes_per_s_per_device": _finite(bw),
        "peak_hbm_bytes_per_s": _finite(peak_bw),
        "hbm_frac": _finite(bw / peak_bw if bw and peak_bw else None),
    }


def mfu_event(flops_per_step: float | None, step_s: float | None,
              bytes_per_step: float | None = None) -> dict:
    """The steady-state ``mfu`` event (emit once, with the best measured step time)."""
    return {"event": "mfu", **estimate_mfu(flops_per_step, step_s,
                                           bytes_per_step)}


# Nearest-rank percentiles — the one estimator all serving summaries and the
# report CLI share. Owned by the jax-free utils.jsonl (the router needs it
# without importing jax); re-exported here, its historical home.
from csed_514_project_distributed_training_using_pytorch_tpu.obs.hist import (  # noqa: E402
    LogHistogram,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (  # noqa: E402
    percentiles,
)


def series_percentiles(series, qs=(50, 95, 99)) -> dict | None:
    """p50/p95/p99 of a latency series that is EITHER a raw sequence (the
    nearest-rank oracle, ``utils.jsonl.percentiles``) or an ``obs.hist``
    ``LogHistogram`` sketch (bounded memory, quantiles within its configured
    relative error). The serving summaries call this so the schema stays
    identical while the backing store became O(buckets)."""
    if isinstance(series, LogHistogram):
        return series.percentiles(qs)
    return percentiles(series, qs)


def serve_event(*, request_id: int, prompt_len: int, new_tokens: int, finish: str,
                queue_wait_s: float | None = None, ttft_s: float | None = None,
                tpot_s: float | None = None, e2e_s: float | None = None,
                tenant: str = "default", preemptions: int = 0) -> dict:
    """One served request (``serving/server.py``): the per-request latency record.
    ``tokens_per_s`` is request-local decode throughput — generated tokens over the
    time since admission (e2e minus queue wait). ``tenant`` is the request's
    service class (``"default"`` = the implicit single-tenant class);
    ``preemptions`` how many times it was parked mid-decode by priority
    pressure (DESIGN.md §22) — a parked-then-resumed request finishes
    ``"ok"``, token-identical, but its e2e carries the squeeze it absorbed."""
    decode_s = (e2e_s - queue_wait_s
                if e2e_s is not None and queue_wait_s is not None else None)
    return {
        "event": "serve",
        "request_id": int(request_id),
        "prompt_len": int(prompt_len),
        "new_tokens": int(new_tokens),
        "finish": finish,
        "queue_wait_s": _finite(queue_wait_s),
        "ttft_s": _finite(ttft_s),
        "tpot_s": _finite(tpot_s),
        "e2e_s": _finite(e2e_s),
        "tokens_per_s": _finite(new_tokens / decode_s
                                if new_tokens and decode_s else None),
        "tenant": tenant,
        "preemptions": int(preemptions),
    }


def shed_event(*, tenant: str, reason: str, request_id: int | None = None,
               priority: int | None = None, source: str = "server") -> dict:
    """One overload-shedding decision (``serving/scheduler.py`` via the
    server/router front doors): ``reason`` is ``"quota"`` (token-bucket
    refusal), ``"refused"`` (arrival shed because the queue was full of
    strictly higher-priority work), or ``"displaced"`` (a queued request
    evicted so a higher class could be admitted). These are the deliberate
    degradations — the whole point of SLO tiers is that they land on the
    best-effort class, which this event makes auditable per tenant."""
    return {
        "event": "shed",
        "source": source,
        "tenant": tenant,
        "reason": reason,
        "request_id": int(request_id) if request_id is not None else None,
        "priority": int(priority) if priority is not None else None,
    }


def tenant_summary_event(*, tenant: str, source: str = "server",
                         requests: int = 0, ok: int = 0, timeout: int = 0,
                         shed: int = 0, new_tokens: int = 0,
                         preemptions: int = 0,
                         ttft_s: dict | None = None,
                         e2e_s: dict | None = None,
                         slo: dict | None = None) -> dict:
    """One tenant's drain-time ledger (``serving/server.py`` /
    ``serving/router.py``): counts, latency percentiles, preemptions
    absorbed, and attainment against the tenant's own SLO — the per-class
    A/B surface (the committed tenant-burst artifact compares the paid
    tenant's row across loaded/unloaded runs)."""
    return {
        "event": "tenant_summary",
        "source": source,
        "tenant": tenant,
        "requests": int(requests),
        "ok": int(ok),
        "timeout": int(timeout),
        "shed": int(shed),
        "new_tokens": int(new_tokens),
        "preemptions": int(preemptions),
        "ttft_s": ttft_s,
        "e2e_s": e2e_s,
        "slo": slo,
    }


def prefill_event(*, request_id: int, prompt_len: int, chunks: int, tokens: int,
                  cache_hit_len: int, wall_s: float | None,
                  latency_s: float | None = None) -> dict:
    """One completed prompt prefill (``serving/engine.py`` chunked path):
    ``chunks`` program invocations covered ``tokens`` prompt positions
    (``cache_hit_len`` more came free from the prefix cache; a full hit is
    ``chunks == 0``). ``wall_s`` is the host wall spent in THIS prompt's chunk
    programs — so ``tokens_per_s`` is true prefill throughput, not deflated by
    queueing; ``latency_s`` is admission to decode-ready (includes waiting
    behind other prompts under the chunk budget)."""
    return {
        "event": "prefill",
        "request_id": int(request_id),
        "prompt_len": int(prompt_len),
        "chunks": int(chunks),
        "tokens": int(tokens),
        "cache_hit_len": int(cache_hit_len),
        "wall_s": _finite(wall_s),
        "latency_s": _finite(latency_s),
        "tokens_per_s": _finite(tokens / wall_s if tokens and wall_s else None),
    }



def spec_event(*, step: int, active: int, proposed: int, accepted: int,
               emitted: int, draft_wall_s: float | None = None,
               verify_wall_s: float | None = None) -> dict:
    """One speculative verify step (``serving/engine.py`` spec mode):
    ``active`` slots offered ``proposed`` draft tokens, ``accepted`` of them
    survived verification and ``emitted`` tokens landed (accepted drafts plus
    one correction/bonus per slot). ``emitted_per_slot`` is the step's
    amortization factor — tokens emitted per slot per full-cache read; its
    FLOOR is 1.0 even at zero acceptance (the correction token always lands),
    so monitor acceptance from ``accepted``/``proposed``, not from it."""
    return {
        "event": "spec",
        "step": int(step),
        "active": int(active),
        "proposed": int(proposed),
        "accepted": int(accepted),
        "emitted": int(emitted),
        "emitted_per_slot": _finite(emitted / active if active else None),
        "draft_wall_s": _finite(draft_wall_s),
        "verify_wall_s": _finite(verify_wall_s),
    }


def serve_summary_event(*, requests: int, ok: int, timeout: int, new_tokens: int,
                        wall_s: float | None, steps: int | None = None,
                        shed: int = 0,
                        decode_invocations: int | None = None,
                        generated_tokens: int | None = None,
                        spec: dict | None = None,
                        slot_occupancy: float | None = None,
                        prefill_tokens: int | None = None,
                        prefill_chunks: int | None = None,
                        prefill_wall_s: float | None = None,
                        prefix_cache: dict | None = None,
                        queue: dict | None = None,
                        byte_accounting: dict | None = None,
                        kv_pages: dict | None = None,
                        slo: dict | None = None,
                        preemptions: int | None = None,
                        resumes: int | None = None,
                        tenants: dict | None = None,
                        ttft_s=(), tpot_s=(), e2e_s=(), queue_wait_s=()) -> dict:
    """The once-per-run serving aggregate, emitted at drain: counts, aggregate
    tokens/s over the server's whole wall clock, slot occupancy, and p50/p95/p99
    of each latency series (the per-request ``serve`` lines remain the raw data —
    the summary is what survives a truncated log and what A-vs-B compares).
    ``queue`` is the admission queue's ``RequestQueue.snapshot()`` (depth /
    oldest-age / rejected count) — the backpressure ledger. ``byte_accounting``
    (emitted as ``"bytes"``) is the engine's byte-TRUE decode working set
    (``ContinuousBatchingEngine.byte_accounting()`` — decode bytes/token, KV
    bytes/slot, slots-at-budget, kv_dtype), the quantization A/B ledger.
    ``slo`` is the run-level SLO attainment dict (``obs.slo
    .AttainmentTracker.summary()``) when the server carries a spec.
    ``kv_pages`` is the paged engine's ``page_stats()`` ledger (pool
    occupancy / sharing / refusals / COW copies) — None on a contiguous
    engine, so the field's presence is itself the layout A/B marker. The four
    latency series accept raw sequences or ``obs.hist.LogHistogram`` sketches
    (the server keeps sketches — O(buckets), not O(requests))."""
    return {
        "event": "serve_summary",
        "requests": int(requests),
        "ok": int(ok),
        "timeout": int(timeout),
        "new_tokens": int(new_tokens),
        "wall_s": _finite(wall_s),
        "tokens_per_s": _finite(new_tokens / wall_s
                                if new_tokens and wall_s else None),
        "steps": int(steps) if steps is not None else None,
        # Multi-token decode steps (speculative decoding) break the historical
        # steps == tokens 1:1: report PROGRAM INVOCATIONS and GENERATED TOKENS
        # as separate counters so tokens/s and MFU math stay honest at K>1.
        "decode_invocations": (int(decode_invocations)
                               if decode_invocations is not None else None),
        "generated_tokens": (int(generated_tokens)
                             if generated_tokens is not None else None),
        "tokens_per_invocation": _finite(
            generated_tokens / decode_invocations
            if generated_tokens and decode_invocations else None),
        "spec": spec,
        "slot_occupancy": _finite(slot_occupancy),
        "prefill_tokens": int(prefill_tokens) if prefill_tokens is not None
        else None,
        "prefill_chunks": int(prefill_chunks) if prefill_chunks is not None
        else None,
        "prefill_wall_s": _finite(prefill_wall_s),
        "prefill_tokens_per_s": _finite(
            prefill_tokens / prefill_wall_s
            if prefill_tokens and prefill_wall_s else None),
        "prefix_cache": prefix_cache,
        "queue": queue,
        "bytes": byte_accounting,
        "kv_pages": kv_pages,
        "slo": slo,
        # The tenancy ledger (DESIGN.md §22): deliberate degradations (shed)
        # and mid-decode evictions (preemptions/resumes) are first-class
        # outcomes, never folded into timeouts — plus the per-tenant rows.
        "shed": int(shed),
        "preemptions": int(preemptions) if preemptions is not None else None,
        "resumes": int(resumes) if resumes is not None else None,
        "tenants": tenants,
        "ttft_s": series_percentiles(ttft_s),
        "tpot_s": series_percentiles(tpot_s),
        "e2e_s": series_percentiles(e2e_s),
        "queue_wait_s": series_percentiles(queue_wait_s),
    }


def kv_pages_event(*, source: str = "server", stats: dict) -> dict:
    """One paged-KV pool ledger line (``serving/server.py`` at drain, paged
    engines only): the engine's ``page_stats()`` dict — pool shape
    (num_pages/page_size/groups), occupancy (free/in_use/shared/peak_in_use),
    the alloc/free/refusal counters, live-token fragmentation, and COW copies.
    A standalone kind (not just the ``serve_summary`` field) so ``fleet_top``
    and the report's A-vs-B table can scan for it without parsing summaries."""
    return {"event": "kv_pages", "source": source, **stats}


def promote_event(*, action: str, candidate: str, step: int | None = None,
                  reason: str = "", incumbent: str = "",
                  nll: float | None = None, incumbent_nll: float | None = None,
                  perf_s: float | None = None,
                  incumbent_perf_s: float | None = None) -> dict:
    """One promotion-gate lifecycle transition (``deploy/promoter.py``):
    ``action`` is ``candidate_seen`` / ``gate_pass`` / ``gate_fail`` /
    ``canary_start`` / ``promoted`` / ``rolled_back``. ``candidate`` and
    ``incumbent`` are checkpoint paths; the NLL and perf pairs record the
    gate's actual measurements so a rejected candidate's margin is auditable
    from the stream alone."""
    return {
        "event": "promote",
        "action": action,
        "candidate": candidate,
        "step": int(step) if step is not None else None,
        "reason": reason,
        "incumbent": incumbent,
        "nll": _finite(nll),
        "incumbent_nll": _finite(incumbent_nll),
        "perf_s": _finite(perf_s),
        "incumbent_perf_s": _finite(incumbent_perf_s),
    }


def canary_event(*, candidate: str, replica: int, verdict: str,
                 window_s: float | None = None,
                 canary_attainment: float | None = None,
                 fleet_attainment: float | None = None,
                 canary_nll: float | None = None,
                 fleet_nll: float | None = None,
                 canary_requests: int | None = None,
                 fleet_requests: int | None = None,
                 reason: str = "") -> dict:
    """One canary-window verdict (``deploy/promoter.py``): the candidate on
    ONE replica vs the rest of the fleet over the same attainment window —
    windowed SLO attainment (fractions) and sampled-token NLL under the
    shared last-good scorer. ``verdict`` is ``pass`` / ``fail`` /
    ``inconclusive`` (too few requests to judge)."""
    return {
        "event": "canary",
        "candidate": candidate,
        "replica": int(replica),
        "verdict": verdict,
        "window_s": _finite(window_s),
        "canary_attainment": _finite(canary_attainment),
        "fleet_attainment": _finite(fleet_attainment),
        "canary_nll": _finite(canary_nll),
        "fleet_nll": _finite(fleet_nll),
        "canary_requests": (int(canary_requests)
                            if canary_requests is not None else None),
        "fleet_requests": (int(fleet_requests)
                           if fleet_requests is not None else None),
        "reason": reason,
    }
