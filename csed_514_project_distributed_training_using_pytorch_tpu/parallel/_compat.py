"""jax version-compatibility shims for the parallel layer.

``shard_map`` moved twice across the jax versions this framework meets in the
wild: new releases export ``jax.shard_map`` with ``check_vma=`` and
``axis_names=`` (partial-manual axes), while the 0.4.x line ships it as
``jax.experimental.shard_map.shard_map`` with the older ``check_rep=`` /
``auto=`` spelling of the same two knobs. Every shard_map user in this package
imports the one wrapper below, written against the NEW surface, so the rest of
the codebase stays on the current idiom and version drift is handled in exactly
one place.
"""

from __future__ import annotations

try:                                    # new surface: jax.shard_map
    from jax import shard_map as _shard_map
    _NEW_API = True
except ImportError:                     # jax 0.4.x: experimental, check_rep/auto
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEW_API = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` with the new keyword surface on every supported jax.

    ``axis_names`` (the manual-axis subset; None = all mesh axes manual) maps to
    the legacy ``auto=`` complement on 0.4.x; ``check_vma`` maps to the legacy
    ``check_rep``.
    """
    if _NEW_API:
        kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs,
                  "check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return _shard_map(f, **kwargs)
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs,
              "check_rep": check_vma}
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, **kwargs)
