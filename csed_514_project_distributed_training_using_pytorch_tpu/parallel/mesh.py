"""Device mesh + cluster bootstrap.

Replaces the reference's rendezvous layer: ``os.environ['MASTER_ADDR']='10.128.0.2'`` /
``MASTER_PORT`` + ``dist.init_process_group("gloo", rank, world_size)`` (reference
``src/train_dist.py:144-146``, ``src/run1.py:21-23``), where the master IP is an
edit-the-source constant and the rank is encoded in *which launcher file you run*
(``src/run1.py:31`` vs ``src/run2.py:31``). Here:

- on a TPU pod slice, ``initialize_cluster()`` calls ``jax.distributed.initialize()`` with no
  arguments — coordinator address, process id, and world size all come from slice metadata, so
  every host runs the *same* command (this deletes the run1/run2 hand-editing pattern, the
  north-star ask in BASELINE.json);
- explicit coordinator/rank arguments remain available for non-TPU fleets (the gloo-style
  TCP-rendezvous analog);
- ``make_mesh()`` builds the ``jax.sharding.Mesh`` the SPMD step is compiled over. Default is
  the reference-parity one-axis ``('data',)`` mesh; multi-axis shapes (e.g. ``(data, model)``)
  are supported so wider parallelism can be layered on without redesign.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class ProcessInfo:
    """This host's coordinates in the cluster (≙ the reference's rank/world_size pair,
    ``src/train_dist.py:131,141``, but discovered rather than hand-assigned)."""

    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        """True on the process that owns rank-gated side effects (checkpoint writes, plots);
        ≙ the reference's ``if rank == 0`` (``src/train_dist.py:163``)."""
        return self.process_index == 0


def initialize_cluster(coordinator_address: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None,
                       initialization_timeout: int | None = None) -> ProcessInfo:
    """Join (or create) the distributed runtime and report this process's coordinates.

    No-op on a single-process run — safe to call unconditionally from every entry point.

    ``initialization_timeout`` (seconds; or env ``JAX_INITIALIZATION_TIMEOUT``) bounds the
    rendezvous wait — the clean-abort behavior SURVEY.md §5 "failure detection" asks for,
    where the reference's gloo rendezvous blocks forever on a missing peer
    (``src/train_dist.py:146``). On expiry the coordination client terminates the process
    with a DEADLINE_EXCEEDED fatal (not a catchable exception); exceptions jax does raise
    are re-raised with the cluster coordinates attached.
    """
    # Explicit arguments win; otherwise the rendezvous coordinates come from the environment
    # (as set by train.launch or a fleet runner). This is the analog of the reference's
    # MASTER_ADDR/MASTER_PORT env pair (src/train_dist.py:144-145) — except the process id is
    # handed in by the launcher, never hand-edited into the source.
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS") or None
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if initialization_timeout is None and os.environ.get("JAX_INITIALIZATION_TIMEOUT"):
        initialization_timeout = int(os.environ["JAX_INITIALIZATION_TIMEOUT"])

    # TPU pod slice metadata lists one hostname per host; a single entry means this is not
    # a multi-host fleet and no coordinator service is needed.
    slice_hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    multi_host = coordinator_address is not None or len(slice_hosts) > 1
    # Check the distributed-runtime state directly: touching jax.process_count() here would
    # initialize the local XLA backend first, after which jax.distributed.initialize raises.
    if multi_host and not _distributed_is_initialized():
        # Older jax (0.4.x) CPU backends reject multiprocess computations unless the
        # gloo collectives implementation is selected BEFORE backend init; newer jax
        # defaults to gloo and drops the option — hence feature-detected, best-effort.
        if "cpu" in (os.environ.get("JAX_PLATFORMS", "")
                     + str(jax.config.jax_platforms or "")):
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass
        kwargs = {}
        if initialization_timeout is not None:
            kwargs["initialization_timeout"] = initialization_timeout
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except Exception as e:
            raise RuntimeError(
                f"cluster rendezvous failed: coordinator={coordinator_address!r}, "
                f"process_id={process_id}, num_processes={num_processes}, "
                f"timeout={initialization_timeout or 'default'}s — check that every "
                f"peer is up and reachable (≙ a hung init_process_group in the "
                f"reference, src/train_dist.py:146)") from e
    return process_info()


def _distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax versions that
    predate it (0.4.x): the distributed runtime is up iff the global coordination
    client exists — checked WITHOUT touching jax.process_count(), which would
    initialize the local backend first (see the call site)."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:       # pragma: no cover - last resort: assume uninitialized
        return False


def process_info() -> ProcessInfo:
    return ProcessInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


_KNOWN_AXES = ("data", "seq", "model", "expert", "stage")


def parse_mesh_spec(spec: str) -> tuple[tuple[str, ...], tuple[int, ...]]:
    """``"data=2,seq=2,model=2"`` → (axis names, axis sizes). Order is the user's;
    unknown axis names and non-positive sizes are rejected. Shared by every trainer
    that accepts a ``--mesh`` string."""
    names, sizes = [], []
    for part in [p for p in spec.split(",") if p]:
        if "=" not in part:
            raise ValueError(f"mesh axis {part!r} must be name=size")
        name, _, size_s = part.partition("=")
        name = name.strip()
        if name not in _KNOWN_AXES:
            raise ValueError(f"unknown mesh axis {name!r} — choose from {_KNOWN_AXES}")
        if name in names:
            raise ValueError(f"duplicate mesh axis {name!r}")
        try:
            size = int(size_s)
        except ValueError:
            raise ValueError(f"mesh axis size {size_s!r} is not an integer") from None
        if size < 1:
            raise ValueError(f"mesh axis {name} size must be >= 1, got {size}")
        names.append(name)
        sizes.append(size)
    if not names:
        raise ValueError("empty --mesh spec")
    return tuple(names), tuple(sizes)


def make_mesh(num_devices: int | None = None,
              axis_names: tuple[str, ...] = ("data",),
              axis_shape: tuple[int, ...] | None = None) -> Mesh:
    """Build a device mesh.

    ``num_devices=None`` uses every addressable device (all chips on all hosts). With the
    default one-axis ``('data',)`` layout this is the analog of the reference's flat world of N
    single-process machines (``world_size``, ``src/train_dist.py:131``) — except chips within a
    host ride ICI and the axis order follows the physical topology, since
    ``jax.devices()`` enumerates in topology order.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    if axis_shape is None:
        axis_shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(axis_shape)) != len(devices):
        raise ValueError(f"axis_shape {axis_shape} != {len(devices)} devices")
    return Mesh(np.asarray(devices).reshape(axis_shape), axis_names)


def _slice_granules(devices, num_slices: int | None) -> dict:
    """DCN granule membership for ``make_hybrid_mesh``: a dict of granule id →
    topology-ordered device list.

    Natural granules first: real slice boundaries (multi-slice TPU), else host
    boundaries (multi-process). A SINGLE natural granule carries no topology
    information (e.g. single-slice backends report slice_index=0 on every device),
    so it falls through to the virtual ``num_slices`` partitioning rather than
    shadowing it. When ``num_slices`` names FEWER granules than the platform's H
    natural HOST granules and divides H (hosts-per-slice > 1 without the
    multi-slice ``slice_index`` attribute), contiguous host granules merge — in
    topology order, so intra-super-granule links stay as local as the enumeration
    allows. Real ``slice_index`` granules never merge (their boundaries ARE the
    DCN; grouping them would put per-layer collectives on it), and any other
    mismatch errors: the real topology wins."""
    n = len(devices)
    if {getattr(d, "slice_index", None) for d in devices} != {None}:
        natural, mergeable = (lambda d: d.slice_index), False
    elif len({d.process_index for d in devices}) > 1:
        # Host granules are a PROXY for slice membership — hosts-per-slice > 1 is
        # a legitimate layout, so these (unlike real slice_index granules, whose
        # boundaries ARE the DCN) may merge under a smaller num_slices below.
        natural, mergeable = (lambda d: d.process_index), True
    else:
        natural, mergeable = (lambda d: 0), False
    granules: dict = {}
    for d in devices:
        granules.setdefault(natural(d), []).append(d)
    if len(granules) == 1:
        if num_slices is None:
            raise ValueError(
                "single-slice single-process platform: pass num_slices to "
                "partition devices into virtual slices (or use make_mesh — "
                "there is no DCN here)")
        per = n // num_slices
        return {s: list(devices[s * per:(s + 1) * per])
                for s in range(num_slices)}
    slice_ids = sorted(granules)
    if num_slices is not None and len(slice_ids) != num_slices:
        if (mergeable and num_slices < len(slice_ids)
                and len(slice_ids) % num_slices == 0):
            per_super = len(slice_ids) // num_slices
            return {s: [d for g in slice_ids[s * per_super:(s + 1) * per_super]
                        for d in granules[g]]
                    for s in range(num_slices)}
        raise ValueError(
            f"num_slices {num_slices} != the platform's {len(slice_ids)} "
            f"natural granules (slices/hosts)"
            + (" and does not divide them" if mergeable else "")
            + " — the real topology wins; drop or match the override")
    return granules


# Nominal per-device budget when neither the runtime nor the spec table knows the
# chip (CPU test platforms, unknown kinds) — deterministic rather than a guess
# per machine; override with PLAN_HBM_BYTES.
DEFAULT_DEVICE_MEMORY = 16 << 30


def device_memory_budget(device=None) -> tuple[int, str]:
    """Usable accelerator-memory bytes for one device, with provenance.

    Returns ``(bytes, source)`` where source is ``"env"`` (the ``PLAN_HBM_BYTES``
    override), ``"runtime"`` (the PJRT ``memory_stats()['bytes_limit']`` this
    process actually got), ``"spec"`` (the committed per-kind capacity table —
    ``utils.benchmarks.HBM_CAPACITY_BY_KIND``, next to its bandwidth/FLOPs
    siblings), or ``"nominal"`` (unknown device — the deterministic default).
    The planner's memory pruning (``plan/search.py``) treats only the first two
    as hard facts; the table is what a pod the process can't see yet is judged
    by."""
    # Lazy: utils.benchmarks pulls the trainer stack, which imports this module.
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        HBM_CAPACITY_BY_KIND, lookup_by_kind,
    )

    if os.environ.get("PLAN_HBM_BYTES"):
        return int(os.environ["PLAN_HBM_BYTES"]), "env"
    if device is None:
        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"]), "runtime"
    kind = str(getattr(device, "device_kind", device.platform))
    cap = lookup_by_kind(HBM_CAPACITY_BY_KIND, kind)
    if cap is not None:
        return int(cap), "spec"
    return int(DEFAULT_DEVICE_MEMORY), "nominal"


def num_granules(devices=None) -> int:
    """How many DCN granules (slices, else hosts) the device set spans — the
    count whose boundaries collectives must cross the data-center network to
    pass. 1 means everything rides ICI (single slice, single host)."""
    if devices is None:
        devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if slice_ids != {None}:
        return len(slice_ids)
    return max(len({d.process_index for d in devices}), 1)


def topology_summary(devices=None) -> dict:
    """One-call snapshot of the physical topology the planner costs layouts
    against: device count/kind/platform, per-chip memory budget (+ provenance),
    and the DCN granule count. Pure introspection — no backend mutation, safe
    before or after ``initialize_cluster``."""
    if devices is None:
        devices = jax.devices()
    budget, source = device_memory_budget(devices[0])
    return {
        "platform": devices[0].platform,
        "device_kind": str(getattr(devices[0], "device_kind",
                                   devices[0].platform)),
        "device_count": len(devices),
        "process_count": jax.process_count(),
        "hbm_bytes": budget,
        "hbm_source": source,
        "num_granules": num_granules(devices),
    }


def make_hybrid_mesh(axis_names: tuple[str, ...], axis_shape: tuple[int, ...],
                     *, dcn_axis: str = "data", num_slices: int | None = None,
                     devices=None) -> Mesh:
    """Device mesh for multi-slice (ICI × DCN) topologies: ``dcn_axis``'s LEADING
    factor strides across slices — the only axis whose collectives cross the
    data-center network — while its within-slice remainder and every other axis stay
    inside a slice and ride ICI.

    This is the scaling-book recipe for multi-pod training: put (the outer factor
    of) data parallelism on DCN, where one gradient all-reduce per step amortizes
    the slow links, and keep model/seq/expert sharding — whose collectives fire per
    layer — on ICI. The device arrangement is what
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` produces for the same
    split (slice-major along ``dcn_axis``); first-party here so the slice
    granule can also be VIRTUAL (``num_slices`` on a single-slice or CPU platform),
    which is how the multi-slice layout is exercised without multi-slice hardware —
    the same trick the virtual 8-device CPU mesh plays for multi-chip.

    Slice membership comes from ``device.slice_index`` (multi-slice TPU), else
    process index (one granule per host), else an explicit ``num_slices``
    partitioning the topology-ordered device list into equal contiguous granules.
    """
    if dcn_axis not in axis_names:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in axis_names {axis_names}")
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if int(np.prod(axis_shape)) != n:
        raise ValueError(f"axis_shape {axis_shape} != {n} devices")
    if num_slices is not None and (num_slices < 1 or n % num_slices):
        raise ValueError(f"num_slices {num_slices} must be >= 1 and divide the "
                         f"{n} devices")

    granules = _slice_granules(devices, num_slices)
    slice_ids = sorted(granules)
    sizes = {len(v) for v in granules.values()}
    if len(sizes) != 1:
        raise ValueError(f"uneven slices: {sorted(sizes)} devices per granule")

    pos = axis_names.index(dcn_axis)
    n_slices = len(slice_ids)
    if axis_shape[pos] % n_slices:
        raise ValueError(
            f"{dcn_axis} axis size {axis_shape[pos]} must divide by the "
            f"{n_slices} slices (its leading factor is the DCN dimension)")
    inner = axis_shape[pos] // n_slices
    per_slice_shape = axis_shape[:pos] + (inner,) + axis_shape[pos + 1:]
    if int(np.prod(per_slice_shape)) != sizes.pop():
        raise ValueError(f"per-slice shape {per_slice_shape} != slice device count")
    stacked = np.stack([np.asarray(granules[s]).reshape(per_slice_shape)
                        for s in slice_ids], axis=pos)
    return Mesh(stacked.reshape(axis_shape), axis_names)
