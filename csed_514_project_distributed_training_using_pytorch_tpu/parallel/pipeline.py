"""Pipeline parallelism: stage-sharded layers with a microbatch ring.

Beyond-parity capability (the reference's model is a single 21.8k-param forward,
SURVEY.md §2c — no stage split possible or needed): a stack of identically-shaped layers
is sharded across devices along a ``stage`` mesh axis, and microbatches stream through the
stages GPipe-style. Depth then scales with chips: each device holds only its stage's
weights.

TPU-first expression — one ``shard_map`` program, no per-stage processes or RPC:

- Stage ``s`` holds slice ``s`` of the **stacked** layer parameters (leading dim =
  number of stages, sharded ``P('stage')`` — the natural SPMD layout for a homogeneous
  layer stack).
- A ``lax.scan`` runs ``M + S - 1`` ticks (M microbatches, S stages — the classic GPipe
  schedule incl. its fill/drain bubble). Every tick, each device applies its stage to its
  current activation and the activations rotate one hop with ``lax.ppermute`` (ICI
  neighbor traffic on hardware). Stage 0 ingests microbatch ``t``; the last stage banks
  microbatch ``t - (S-1)``.
- The banked outputs are combined with a masked ``psum`` so every device returns the full
  result replicated — and the whole schedule is reverse-mode differentiable (scan +
  ppermute transpose), so the pipeline composes with ``jax.value_and_grad`` training.

Bubble fraction is the textbook ``(S-1)/(M+S-1)``; choose ``M >> S`` to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from csed_514_project_distributed_training_using_pytorch_tpu.parallel._compat import (
    shard_map,
)


def stack_stage_params(stage_param_list):
    """Stack per-stage parameter pytrees (identical structure) into one pytree with a
    leading ``[num_stages, ...]`` dim — the shardable layout ``pipeline_apply`` consumes.

    For the transformer family: ``stack_stage_params([params[f"block_{i}"] for i in
    range(L)])`` turns L blocks into an L-stage stack (see tests).
    """
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *stage_param_list)


def stack_transformer_blocks(params, num_layers: int):
    """Bridge a ``TransformerClassifier`` params tree (per-name ``block_i`` subtrees —
    the checkpoint layout) to the stacked ``[num_layers, ...]`` layout this module
    shards: returns ``(stacked_blocks, rest)`` where ``rest`` is the tree minus the
    blocks (embeddings, final LN, head). Inverse: ``unstack_transformer_blocks``."""
    expected = {f"block_{i}" for i in range(num_layers)}
    missing = sorted(expected - set(params))
    if missing:
        raise ValueError(f"params tree lacks block subtrees {missing}")
    extra = sorted(k for k in params if k.startswith("block_") and k not in expected)
    if extra:
        raise ValueError(
            f"params tree has block subtrees beyond num_layers={num_layers}: {extra} "
            f"— silently dropping layers would corrupt the round-trip")
    stacked = stack_stage_params([params[f"block_{i}"] for i in range(num_layers)])
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    return stacked, rest


def unstack_transformer_blocks(stacked, rest) -> dict:
    """Rebuild the per-name checkpoint layout from ``(stacked_blocks, rest)``."""
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    out = dict(rest)
    for i in range(num_layers):
        out[f"block_{i}"] = jax.tree_util.tree_map(lambda p: p[i], stacked)
    return out


SCHEDULES = ("gpipe", "1f1b")


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stacked_params,
                   microbatches: jax.Array, *, axis_name: str = "stage",
                   batch_axis: str | None = None,
                   schedule: str = "gpipe") -> jax.Array:
    """Run ``microbatches`` through the stage pipeline.

    ``stage_fn(stage_params, x) -> y`` is one stage's computation with ``y.shape ==
    x.shape`` (residual-block-shaped, as transformer blocks are). ``stacked_params`` has
    leading dim == mesh axis size; ``microbatches: [M, mb, ...]``. Returns ``[M, mb, ...]``
    outputs, replicated over the stage axis.

    ``batch_axis`` ('data' in the composed trainer) additionally shards the microbatch
    dim (dim 1) over that mesh axis: each data coordinate streams its own batch slice
    through the same stage ring — PP × DP as one program, no cross-talk (every
    collective here names only ``axis_name``).

    ``schedule`` selects the backward formulation (forward numerics are identical —
    pinned in tests):

    - ``"gpipe"``: reverse-mode rides the transposed scan. Simple, but autodiff banks
      EVERY intra-stage residual of every tick — activation memory
      O(M · layers_per_stage · per-layer residuals) per device.
    - ``"1f1b"``: a custom VJP runs the 1F1B BACKWARD ordering — a counter-rotating
      gradient ring where stage ``s`` applies microbatch ``u``'s backward at tick
      ``u + (S-1-s)``, one microbatch in backward flight per device per tick, with
      only the per-microbatch STAGE INPUT saved and intra-stage activations
      rematerialized inside the tick's ``jax.vjp`` — activation memory
      O(M · stage-input) regardless of stage depth. Under XLA's two-phase autodiff
      the forward and backward are separate programs, so what 1F1B contributes here
      is its backward schedule and its memory bound, not wall-clock overlap of
      F and B ticks of different microbatches (that would need the loss computed
      inside the pipelined program — the interleaved "steady state" of the paper
      schedule).

    Bubble accounting (both schedules): each phase runs ``M + S − 1`` ticks of which
    ``S − 1`` are fill/drain on any given device — bubble fraction
    ``(S−1)/(M+S−1)`` per phase, amortized by ``M ≫ S``. 1F1B's paper win over
    GPipe is the memory bound above, not the bubble (identical for the
    non-interleaved schedule). MEASURED, not just stated (r5):
    ``tools/bench_pipeline_bubble.py`` fits ``t(M) = c·(M+S−1) + o`` and the
    measured fraction tracks this formula across M — committed artifacts
    ``bench_results/pipeline_bubble_r5_*.json``.
    """
    num_stages = mesh.shape[axis_name]
    if jax.tree_util.tree_leaves(stacked_params)[0].shape[0] != num_stages:
        raise ValueError(
            f"stacked params leading dim "
            f"{jax.tree_util.tree_leaves(stacked_params)[0].shape[0]} != mesh axis "
            f"{axis_name!r} size {num_stages}")
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} — "
                         f"one of {SCHEDULES}")
    num_micro = microbatches.shape[0]
    x_spec = P(*((None, batch_axis) + (None,) * (microbatches.ndim - 2)))

    # Only the axes this schedule itself manipulates are MANUAL; every other mesh
    # axis (e.g. ``model``) stays AUTO — inside the body those dims remain global
    # and GSPMD inserts their collectives from the params' own shardings. That is
    # how PP composes with TP here: the stage ring is hand-written ppermute, the
    # per-stage Megatron sharding is still annotation-driven (tensor_parallel.py),
    # nested without nested shard_maps (r4 verdict item 4).
    manual = frozenset({axis_name} | ({batch_axis} if batch_axis else set()))

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis_name), x_spec), out_specs=x_spec,
             axis_names=manual, check_vma=False)
    def run(params_stacked, xs):
        # This device's stage slice ([1, ...] shard → drop the stage dim).
        params = jax.tree_util.tree_map(lambda p: p[0], params_stacked)
        stage = lax.axis_index(axis_name)
        perm = [(j, (j + 1) % num_stages) for j in range(num_stages)]
        perm_rev = [(j, (j - 1) % num_stages) for j in range(num_stages)]

        def replicate_banked(banked):
            """Only the last stage holds real outputs; the masked psum replicates.
            Lives OUTSIDE the 1f1b custom-VJP op so shard_map's own collective
            transpose conventions apply to it identically in both schedules."""
            return lax.psum(
                jnp.where(stage == num_stages - 1, banked, jnp.zeros_like(banked)),
                axis_name)

        def fwd_ticks(params, xs, *, bank_inputs: bool):
            """The forward schedule → this device's LOCAL banked outputs (real on
            the last stage only); optionally banks each device's per-microbatch
            STAGE INPUT (the 1F1B backward's only residual)."""

            def tick(carry, t):
                # The xin_bank slot exists only when banking (a dead xs-sized
                # carry would otherwise ride every gpipe tick).
                x_cur, banked = carry[:2]
                # Stage 0 ingests microbatch t (clip keeps the gather in range during
                # drain; the value is discarded by the stage-0 select then anyway).
                feed = xs[jnp.clip(t, 0, num_micro - 1)]
                x_in = jnp.where(stage == 0, feed, x_cur)
                if bank_inputs:
                    xin_bank = carry[2]
                    # This device processes microbatch t - stage at tick t.
                    w_in = t - stage
                    w_in_c = jnp.clip(w_in, 0, num_micro - 1)
                    keep = (w_in >= 0) & (w_in < num_micro)
                    xin_bank = lax.dynamic_update_index_in_dim(
                        xin_bank,
                        jnp.where(keep, x_in, lax.dynamic_index_in_dim(
                            xin_bank, w_in_c, 0, keepdims=False)),
                        w_in_c, 0)
                y = stage_fn(params, x_in)
                # The last stage banks finished microbatch t-(S-1) once the pipe fills.
                w = t - (num_stages - 1)
                w_clipped = jnp.clip(w, 0, num_micro - 1)
                do_bank = jnp.logical_and(stage == num_stages - 1, w >= 0)
                banked = lax.dynamic_update_index_in_dim(
                    banked,
                    jnp.where(do_bank, y, lax.dynamic_index_in_dim(
                        banked, w_clipped, 0, keepdims=False)),
                    w_clipped, 0)
                x_next = lax.ppermute(y, axis_name, perm)
                out = (x_next, banked) + ((xin_bank,) if bank_inputs else ())
                return out, None

            banked0 = jnp.zeros_like(xs)
            carry0 = ((jnp.zeros_like(xs[0]), banked0)
                      + ((banked0,) if bank_inputs else ()))
            final, _ = lax.scan(tick, carry0,
                                jnp.arange(num_micro + num_stages - 1))
            return final[1], (final[2] if bank_inputs else None)

        if schedule == "gpipe":
            return replicate_banked(fwd_ticks(params, xs, bank_inputs=False)[0])

        @jax.custom_vjp
        def op(params, xs):
            return fwd_ticks(params, xs, bank_inputs=False)[0]

        def op_fwd(params, xs):
            banked, xin_bank = fwd_ticks(params, xs, bank_inputs=True)
            return banked, (params, xin_bank)

        def op_bwd(res, dys):
            # ``dys`` is the cotangent of this device's LOCAL banked outputs: real
            # on the last stage (the masked psum outside the op routes the true
            # output grads there), zeros elsewhere — exactly the feed the reverse
            # ring wants.
            params, xin_bank = res
            # Recomputed here, NOT closed over: the backward traces in its own
            # context (e.g. inside the jitted epoch's grad), where the forward
            # trace's axis_index tracer would be a leak.
            stage = lax.axis_index(axis_name)
            zero_params = jax.tree_util.tree_map(jnp.zeros_like, params)

            def tick(carry, u):
                g_cur, dparams, dxs = carry
                # The last stage ingests microbatch u's output grad at tick u;
                # stage s applies microbatch w = u - (S-1-s)'s backward.
                feed = dys[jnp.clip(u, 0, num_micro - 1)]
                g_in = jnp.where(stage == num_stages - 1, feed, g_cur)
                w = u - (num_stages - 1 - stage)
                w_c = jnp.clip(w, 0, num_micro - 1)
                active = (w >= 0) & (w < num_micro)
                x_in = lax.dynamic_index_in_dim(xin_bank, w_c, 0, keepdims=False)
                # Rematerialize the stage at its saved input — per-layer residuals
                # live only inside this tick.
                _, vjp_fn = jax.vjp(stage_fn, params, x_in)
                dp_h, dx = vjp_fn(g_in)
                dparams = jax.tree_util.tree_map(
                    lambda a, b: a + jnp.where(active, b, jnp.zeros_like(b)),
                    dparams, dp_h)
                # Stage 0's dx is the pipeline-input grad for microbatch w.
                do_bank = jnp.logical_and(stage == 0, active)
                dxs = lax.dynamic_update_index_in_dim(
                    dxs,
                    jnp.where(do_bank, dx, lax.dynamic_index_in_dim(
                        dxs, w_c, 0, keepdims=False)),
                    w_c, 0)
                g_next = lax.ppermute(dx, axis_name, perm_rev)
                return (g_next, dparams, dxs), None

            (_, dparams, dxs), _ = lax.scan(
                tick, (jnp.zeros_like(dys[0]), zero_params, jnp.zeros_like(dys)),
                jnp.arange(num_micro + num_stages - 1))
            # Per-DEVICE cotangent contributions, exactly as autodiff of the gpipe
            # body would produce them: dparams is this stage's local shard; dxs is
            # real on stage 0 only (the only stage whose x_in select consumes xs) —
            # the outer shard_map transpose combines them the same way for both
            # schedules.
            dxs = jnp.where(stage == 0, dxs, jnp.zeros_like(dxs))
            return dparams, dxs

        op.defvjp(op_fwd, op_bwd)
        return replicate_banked(op(params, xs))

    return run(stacked_params, microbatches)


def make_pipelined_blocks_fn(mesh: Mesh, stage_fn: Callable, *,
                             axis_name: str = "stage",
                             num_microbatches: int = 8,
                             batch_axis: str | None = None,
                             schedule: str = "gpipe") -> Callable:
    """Bind a mesh/microbatch count into ``f(stacked_params, x) -> y`` over a flat
    ``[B, ...]`` batch: splits B into microbatches, pipelines them, and re-flattens.
    ``B`` must divide by ``num_microbatches``. ``schedule`` as in
    ``pipeline_apply``."""

    def apply(stacked_params, x):
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
        xs = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])
        ys = pipeline_apply(mesh, stage_fn, stacked_params, xs, axis_name=axis_name,
                            batch_axis=batch_axis, schedule=schedule)
        return ys.reshape(x.shape)

    return apply


class PipelinedClassifier:
    """``TransformerClassifier`` forward with the block stack streamed GPipe-style —
    the composed trainer's ``--mesh ...,stage=K`` execution engine.

    Operates on the STACKED parameter layout ``{"blocks": stacked, "rest": rest}``
    (from ``stack_transformer_blocks``; inverse bridge restores the per-name checkpoint
    layout, so PP checkpoints interchange with every other sharding layout). Exposes
    flax's ``apply(variables, x, ...)`` calling convention, so ``train.step``'s
    ``make_train_step`` / ``make_epoch_fn`` / ``make_eval_fn`` drive it unchanged.

    The embed/head math intentionally mirrors ``models.transformer.
    TransformerClassifier.__call__`` (drift is pinned by
    ``tests/test_pipeline.py::test_pipelined_classifier_matches_model``); the per-stage
    body reuses ``TransformerBlock`` itself, scanned over the stage's layer sub-stack
    when ``num_layers > num_stages``. Dropout is unsupported (the composed trainer
    validates ``dropout_rate == 0`` for stage meshes): microbatches would need
    per-tick key threading through the ring.
    """

    def __init__(self, model, mesh: Mesh, *, axis_name: str = "stage",
                 num_microbatches: int = 4, batch_axis: str | None = None,
                 schedule: str = "gpipe"):
        from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
            TransformerBlock,  # lazy: models.transformer imports parallel/ at load
        )

        num_stages = mesh.shape[axis_name]
        if model.num_layers % num_stages:
            raise ValueError(
                f"num_layers {model.num_layers} not divisible by stage axis "
                f"{num_stages}")
        if model.num_experts:
            raise ValueError("stage pipelining of MoE blocks is unsupported")
        if model.dropout_rate:
            raise ValueError(
                "stage pipelining requires dropout_rate == 0 — the microbatch ring "
                "does not thread dropout keys, so a nonzero rate would silently "
                "train without dropout")
        self.model = model
        self.layers_per_stage = model.num_layers // num_stages
        self.num_stages = num_stages
        # Mirror EVERY attention-shaping field of the source model — a dropped field
        # here silently trains a different function on stage meshes (num_kv_heads
        # would at least fail loudly on param-tree mismatch; rope would not).
        block = TransformerBlock(
            num_heads=model.num_heads, num_kv_heads=model.num_kv_heads,
            mlp_ratio=model.mlp_ratio,
            dropout_rate=0.0, attention_fn=model.attention_fn,
            causal=model.causal, rope=model.rope, dtype=model.dtype)

        def stage_fn(stage_params, x):
            # stage_params leaves: [layers_per_stage, ...] — apply in stack order.
            def body(h, p):
                return block.apply({"params": p}, h, True), None

            h, _ = lax.scan(body, x, stage_params)
            return h

        self._blocks_fn = make_pipelined_blocks_fn(
            mesh, stage_fn, axis_name=axis_name,
            num_microbatches=num_microbatches, batch_axis=batch_axis,
            schedule=schedule)

    def apply(self, variables, x, deterministic: bool = True, rngs=None,
              mutable=None):
        from csed_514_project_distributed_training_using_pytorch_tpu import ops

        from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
            tokenize_images,
        )

        model = self.model
        params = variables["params"]
        rest, blocks = params["rest"], params["blocks"]
        if x.ndim == 4:
            x = tokenize_images(x, model.seq_len)
        x = x.astype(model.dtype)

        h = ops.dense(x, rest["embed_kernel"].astype(model.dtype),
                      rest["embed_bias"].astype(model.dtype))
        h = h + rest["pos_embed"].astype(model.dtype)[None]

        stacked = jax.tree_util.tree_map(
            lambda p: p.reshape((self.num_stages, self.layers_per_stage)
                                + p.shape[1:]), blocks)
        h = self._blocks_fn(stacked, h)

        h = ops.layer_norm(h, rest["ln_f_scale"], rest["ln_f_bias"])
        h = jnp.mean(h, axis=1)
        logits = ops.dense(h, rest["head_kernel"].astype(model.dtype),
                           rest["head_bias"].astype(model.dtype))
        out = ops.log_softmax(logits.astype(jnp.float32))
        return (out, {}) if mutable is not None else out


def stacked_state_shardings(mesh: Mesh, state, *, axis_name: str = "stage",
                            model_axis: str = "model"):
    """``TrainState``-shaped ``NamedSharding`` tree for the stacked PP layout: every
    ``blocks`` leaf shards its leading (layer-stack) dim over ``axis_name`` — each
    device stores only its stage's layers — and, when the mesh also has
    ``model_axis``, its Megatron dim over that axis too (``tensor_parallel``'s
    column/row rules shifted one dim right for the stack): PP × TP memory division
    in one sharding tree. Everything else replicates."""
    from jax.sharding import NamedSharding

    from csed_514_project_distributed_training_using_pytorch_tpu.ops.optim import (
        map_param_trees,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        tensor_parallel as _tp,
    )

    has_model = model_axis in mesh.shape and mesh.shape[model_axis] > 1
    rep = NamedSharding(mesh, P())

    def stacked_spec(path, leaf) -> P:
        """``tensor_parallel``'s per-leaf classification, applied to a leaf whose
        dim 0 is the layer stack (so every rule's dims shift right by one)."""
        name = _tp._leaf_name(path)
        if has_model and leaf.ndim == 3 and name in _tp._COLUMN_PARALLEL:
            return P(axis_name, None, model_axis)
        if has_model and leaf.ndim == 3 and name in _tp._ROW_PARALLEL:
            return P(axis_name, model_axis, None)
        if has_model and leaf.ndim == 2 and name in _tp._COLUMN_PARALLEL_BIAS:
            return P(axis_name, model_axis)
        return P(axis_name)

    def tree_sh(tree):
        return {"blocks": jax.tree_util.tree_map_with_path(
                    lambda p, l: NamedSharding(mesh, stacked_spec(p, l)),
                    tree["blocks"]),
                "rest": jax.tree_util.tree_map(lambda _: rep, tree["rest"])}

    import csed_514_project_distributed_training_using_pytorch_tpu.train.step as _step
    # The optimizer state holds one stacked {"blocks","rest"} layout per params-
    # congruent subtree (AdamW: each moment; SGD: the velocity itself) — shard each
    # like the params; the AdamW step count replicates.
    return _step.TrainState(
        params=tree_sh(state.params),
        velocity=map_param_trees(state.velocity, tree_sh, scalar_fn=lambda _: rep),
        step=rep,
        ema=tree_sh(state.ema) if state.ema is not None else None,
        # Guard scalars (anomaly detector) replicate like step.
        guard=jax.tree_util.tree_map(lambda _: rep, state.guard)
        if state.guard is not None else None)
