"""Pipeline parallelism: stage-sharded layers with a microbatch ring.

Beyond-parity capability (the reference's model is a single 21.8k-param forward,
SURVEY.md §2c — no stage split possible or needed): a stack of identically-shaped layers
is sharded across devices along a ``stage`` mesh axis, and microbatches stream through the
stages GPipe-style. Depth then scales with chips: each device holds only its stage's
weights.

TPU-first expression — one ``shard_map`` program, no per-stage processes or RPC:

- Stage ``s`` holds slice ``s`` of the **stacked** layer parameters (leading dim =
  number of stages, sharded ``P('stage')`` — the natural SPMD layout for a homogeneous
  layer stack).
- A ``lax.scan`` runs ``M + S - 1`` ticks (M microbatches, S stages — the classic GPipe
  schedule incl. its fill/drain bubble). Every tick, each device applies its stage to its
  current activation and the activations rotate one hop with ``lax.ppermute`` (ICI
  neighbor traffic on hardware). Stage 0 ingests microbatch ``t``; the last stage banks
  microbatch ``t - (S-1)``.
- The banked outputs are combined with a masked ``psum`` so every device returns the full
  result replicated — and the whole schedule is reverse-mode differentiable (scan +
  ppermute transpose), so the pipeline composes with ``jax.value_and_grad`` training.

Bubble fraction is the textbook ``(S-1)/(M+S-1)``; choose ``M >> S`` to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def stack_stage_params(stage_param_list):
    """Stack per-stage parameter pytrees (identical structure) into one pytree with a
    leading ``[num_stages, ...]`` dim — the shardable layout ``pipeline_apply`` consumes.

    For the transformer family: ``stack_stage_params([params[f"block_{i}"] for i in
    range(L)])`` turns L blocks into an L-stage stack (see tests).
    """
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *stage_param_list)


def stack_transformer_blocks(params, num_layers: int):
    """Bridge a ``TransformerClassifier`` params tree (per-name ``block_i`` subtrees —
    the checkpoint layout) to the stacked ``[num_layers, ...]`` layout this module
    shards: returns ``(stacked_blocks, rest)`` where ``rest`` is the tree minus the
    blocks (embeddings, final LN, head). Inverse: ``unstack_transformer_blocks``."""
    expected = {f"block_{i}" for i in range(num_layers)}
    missing = sorted(expected - set(params))
    if missing:
        raise ValueError(f"params tree lacks block subtrees {missing}")
    extra = sorted(k for k in params if k.startswith("block_") and k not in expected)
    if extra:
        raise ValueError(
            f"params tree has block subtrees beyond num_layers={num_layers}: {extra} "
            f"— silently dropping layers would corrupt the round-trip")
    stacked = stack_stage_params([params[f"block_{i}"] for i in range(num_layers)])
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    return stacked, rest


def unstack_transformer_blocks(stacked, rest) -> dict:
    """Rebuild the per-name checkpoint layout from ``(stacked_blocks, rest)``."""
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    out = dict(rest)
    for i in range(num_layers):
        out[f"block_{i}"] = jax.tree_util.tree_map(lambda p: p[i], stacked)
    return out


def pipeline_apply(mesh: Mesh, stage_fn: Callable, stacked_params,
                   microbatches: jax.Array, *, axis_name: str = "stage") -> jax.Array:
    """Run ``microbatches`` through the stage pipeline.

    ``stage_fn(stage_params, x) -> y`` is one stage's computation with ``y.shape ==
    x.shape`` (residual-block-shaped, as transformer blocks are). ``stacked_params`` has
    leading dim == mesh axis size; ``microbatches: [M, mb, ...]``. Returns ``[M, mb, ...]``
    outputs, replicated.
    """
    num_stages = mesh.shape[axis_name]
    if jax.tree_util.tree_leaves(stacked_params)[0].shape[0] != num_stages:
        raise ValueError(
            f"stacked params leading dim "
            f"{jax.tree_util.tree_leaves(stacked_params)[0].shape[0]} != mesh axis "
            f"{axis_name!r} size {num_stages}")
    num_micro = microbatches.shape[0]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis_name), P()), out_specs=P(),
             check_vma=False)
    def run(params_stacked, xs):
        # This device's stage slice ([1, ...] shard → drop the stage dim).
        params = jax.tree_util.tree_map(lambda p: p[0], params_stacked)
        stage = lax.axis_index(axis_name)
        perm = [(j, (j + 1) % num_stages) for j in range(num_stages)]

        def tick(carry, t):
            x_cur, banked = carry
            # Stage 0 ingests microbatch t (clip keeps the gather in range during drain;
            # the value is discarded by the stage-0 select on those ticks anyway).
            feed = xs[jnp.clip(t, 0, num_micro - 1)]
            x_in = jnp.where(stage == 0, feed, x_cur)
            y = stage_fn(params, x_in)
            # The last stage banks finished microbatch t-(S-1) once the pipe has filled.
            w = t - (num_stages - 1)
            w_clipped = jnp.clip(w, 0, num_micro - 1)
            do_bank = jnp.logical_and(stage == num_stages - 1, w >= 0)
            banked = lax.dynamic_update_index_in_dim(
                banked,
                jnp.where(do_bank, y, lax.dynamic_index_in_dim(
                    banked, w_clipped, 0, keepdims=False)),
                w_clipped, 0)
            x_next = lax.ppermute(y, axis_name, perm)
            return (x_next, banked), None

        banked0 = jnp.zeros_like(xs)
        (_, banked), _ = lax.scan(
            tick, (jnp.zeros_like(xs[0]), banked0),
            jnp.arange(num_micro + num_stages - 1))
        # Only the last stage holds real outputs; the masked psum replicates them.
        return lax.psum(
            jnp.where(stage == num_stages - 1, banked, jnp.zeros_like(banked)),
            axis_name)

    return run(stacked_params, microbatches)


def make_pipelined_blocks_fn(mesh: Mesh, stage_fn: Callable, *,
                             axis_name: str = "stage",
                             num_microbatches: int = 8) -> Callable:
    """Bind a mesh/microbatch count into ``f(stacked_params, x) -> y`` over a flat
    ``[B, ...]`` batch: splits B into microbatches, pipelines them, and re-flattens.
    ``B`` must divide by ``num_microbatches``."""

    def apply(stacked_params, x):
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
        xs = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])
        ys = pipeline_apply(mesh, stage_fn, stacked_params, xs, axis_name=axis_name)
        return ys.reshape(x.shape)

    return apply
