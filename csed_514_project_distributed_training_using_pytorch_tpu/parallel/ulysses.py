"""All-to-all (Ulysses-style) sequence/context parallelism.

Beyond-parity capability (the reference is DP-only — SURVEY.md §2c — and has no
attention op at all; reference ``src/model.py:4-22`` is a fixed-28×28 CNN): the second
of the two canonical sequence-parallel attention schedules, complementing the ring
family in ``parallel/ring_attention.py``.

Where ring attention keeps queries resident and rotates K/V blocks hop-by-hop
(n-1 ``ppermute`` rounds, online-softmax merges), the all-to-all schedule re-shards
ONCE: activations arrive sequence-sharded ``[B, S/n, H, D]``, one ``lax.all_to_all``
converts them to head-sharded ``[B, S, H/n, D]`` — every device now holds the FULL
sequence for its own head group — the unmodified single-device attention op runs
locally, and a second all-to-all restores the sequence sharding. Attention is
independent per head, so the result is exactly the dense oracle with no online-softmax
merge math at all.

Trade-offs (why both schedules exist — the published DeepSpeed-Ulysses vs
ring/blockwise comparison, re-derived for TPU):

- **Communication**: 2 all-to-alls of the activations per attention call vs the ring's
  n-1 K/V ppermute rounds. On a TPU mesh XLA lowers ``all_to_all`` onto ICI directly;
  for moderate n the single re-shard moves less data than the full ring rotation and
  has no per-hop latency chain.
- **Composability**: the local op is arbitrary — causal masking needs no global-position
  plumbing or hop-case analysis (the device sees the whole sequence), and the Pallas
  flash kernels drop in unchanged (``use_flash=True``), giving O(S·D) local memory.
- **Limits**: parallelism is bounded by the head count (``H_local % n == 0`` required),
  and peak activation memory holds the full S per device for the attention input —
  the ring never materializes full-S activations, so for the longest contexts at small
  head counts the ring (and zig-zag ring-of-flash) remains the scaling path.

Differentiability is structural: ``all_to_all`` transposes to the inverse all-to-all,
and the local op is the already-differentiable dense einsum or flash custom-VJP — no
custom VJP needed here. Pinned against ``ops.full_attention`` forward AND gradients in
``tests/test_ulysses.py``.

No backend strings, no explicit sends: the collective schedule is the compiler's job
(same philosophy as ``parallel/collectives.py``).
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh
from csed_514_project_distributed_training_using_pytorch_tpu.parallel._compat import (
    shard_map,
)

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
    _qkv_spec,
)


def ulysses_attention(mesh: Mesh, q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "seq", causal: bool = False,
                      use_flash: bool = False, window: int = 0) -> jax.Array:
    """Sequence-parallel attention via head-scatter all-to-all.

    ``q, k, v: [B, S, H, D]`` with S sharded over ``axis_name``; drop-in equivalent of
    ``ops.full_attention`` (same signature modulo the mesh), callable under ``jax.jit``
    (the mesh is static). Requirements: ``S % n == 0`` and the per-device head count
    must divide by ``n`` (heads are what the all-to-all scatters). With
    ``use_flash=True`` the local op is the Pallas flash kernel, which additionally
    needs ``S % 128 == 0`` (the full sequence is local after the first all-to-all).

    On a composed mesh the batch/head dims co-shard over ``data``/``model``
    (``_qkv_spec``, shared with the ring family) — the head-divisibility requirement
    then applies to the model-sharded local head count ``H / model_axis``.

    ``window=W`` (r4) is sliding-window attention: the device holds the full
    sequence after the first all-to-all, so the band needs no hop-offset plumbing —
    it binds straight into the local op (the banded flash grid or the dense band
    mask), same semantics as ``ops.full_attention(window=W)``.
    """
    n = mesh.shape[axis_name]
    b, s, h, d = q.shape
    if s % n:
        raise ValueError(
            f"sequence length {s} not divisible by mesh axis {axis_name!r} size {n} "
            f"— ulysses attention shards the sequence evenly")
    spec = _qkv_spec(mesh, q.shape, axis_name)
    h_local = h if spec[2] is None else h // mesh.shape[spec[2]]
    if h_local % n:
        raise ValueError(
            f"ulysses attention scatters heads over the {axis_name!r} axis: local "
            f"head count {h_local} must divide by its size {n} (use ring attention "
            f"when heads are scarcer than sequence shards)")
    if use_flash:
        from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
            pallas_attention as pa,
        )
        if s % pa.BLOCK:
            raise ValueError(
                f"ulysses attention with use_flash=True runs the flash kernel over "
                f"the full sequence locally — S must divide by BLOCK = {pa.BLOCK}, "
                f"got {s}")
        local_op = pa.flash_attention
    else:
        local_op = ops.full_attention
    if window:
        local_op = partial(local_op, window=window)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
             check_vma=False)
    def _ulysses(ql, kl, vl):
        # [B_l, S/n, H_l, D] → [B_l, S, H_l/n, D]: head chunk i lands on device i,
        # sequence pieces concatenate in source-device (= global position) order.
        gather_seq = lambda x: lax.all_to_all(x, axis_name, split_axis=2,
                                              concat_axis=1, tiled=True)
        # Inverse: sequence chunk i returns to device i, head pieces concatenate in
        # source order, restoring the original head layout.
        scatter_seq = lambda x: lax.all_to_all(x, axis_name, split_axis=1,
                                               concat_axis=2, tiled=True)
        out = local_op(gather_seq(ql), gather_seq(kl), gather_seq(vl),
                       causal=causal)
        return scatter_seq(out)

    return _ulysses(q, k, v)


def make_ulysses_attention_fn(mesh: Mesh, *, axis_name: str = "seq",
                              use_flash: bool = False, window: int = 0):
    """Bind a mesh into a ``(q, k, v, *, causal) -> out`` callable with
    ``ops.full_attention``'s exact signature — the injection point for
    ``models/transformer.py``'s pluggable ``attention_fn``, mirroring
    ``make_ring_attention_fn``. ``window`` binds sliding-window masking into the
    local op (see ``ulysses_attention``)."""

    def attention_fn(q, k, v, *, causal: bool = False):
        return ulysses_attention(mesh, q, k, v, axis_name=axis_name,
                                 causal=causal, use_flash=use_flash,
                                 window=window)

    return attention_fn
