"""Parallelism layer: device mesh, SPMD data parallelism, sharded sampling, collectives.

TPU-native replacement for the reference's L3/L1 stack (SURVEY.md §1): ``DDP(model)`` +
``DistributedSampler`` + ``init_process_group("gloo")`` (reference ``src/train_dist.py:63``,
``:33-37``, ``:146``). There is no wrapper object and no backend string here: parallelism is a
``jax.sharding.Mesh`` plus sharding annotations on one jit-compiled train step; XLA inserts the
gradient all-reduce (the DDP-Reducer analog) and maps it onto ICI within a slice and DCN across
slices.
"""

from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
    make_hybrid_mesh,
    make_mesh,
    initialize_cluster,
    process_info,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.collectives import (
    ring_pass,
    all_reduce_sum,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
    ring_attention,
    ring_flash_attention,
    make_ring_attention_fn,
    zigzag_ring_attention,
    zigzag_ring_flash_attention,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ulysses import (
    ulysses_attention,
    make_ulysses_attention_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.tensor_parallel import (
    param_partition_specs,
    shard_train_state,
    compile_step_tp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.pipeline import (
    pipeline_apply,
    make_pipelined_blocks_fn,
    stack_stage_params,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.expert_parallel import (
    init_moe_params,
    moe_apply,
    shard_moe_params,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import fsdp

__all__ = [
    "ShardedSampler",
    "make_hybrid_mesh",
    "make_mesh",
    "initialize_cluster",
    "process_info",
    "ring_pass",
    "all_reduce_sum",
    "ring_attention",
    "ring_flash_attention",
    "make_ring_attention_fn",
    "zigzag_ring_attention",
    "zigzag_ring_flash_attention",
    "ulysses_attention",
    "make_ulysses_attention_fn",
    "param_partition_specs",
    "shard_train_state",
    "compile_step_tp",
    "pipeline_apply",
    "make_pipelined_blocks_fn",
    "stack_stage_params",
    "init_moe_params",
    "moe_apply",
    "shard_moe_params",
    "fsdp",
]
