"""SPMD data parallelism: compile the train step/epoch over a device mesh.

The reference's data parallelism is a wrapper object plus autograd hooks: ``DDP(model)``
broadcasts params, registers per-bucket hooks, and ring-allreduces gradients over gloo/TCP
during every ``backward()`` (reference ``src/train_dist.py:63,83``; SURVEY.md §2b). Here the
same math is expressed with *sharding annotations only*:

- the global batch is sharded along the mesh's ``data`` axis (the ``DistributedSampler``
  division of labor, reference ``src/train_dist.py:33-37``, but enforced by the compiler);
- params/optimizer state are replicated (``P()``);
- XLA's SPMD partitioner then auto-inserts the gradient ``all-reduce`` inside the one compiled
  step program, scheduled on ICI within a slice / DCN across slices, overlapped with compute
  where profitable — the Reducer/bucketing machinery DDP hand-builds.

The compiled step is numerically the *same program* as the single-device one (GSPMD
semantics), which is the DDP-equivalence oracle tests assert (SURVEY.md §4).
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    """Leading-dim sharding for per-example arrays (images, labels, per-step index plans)."""
    return NamedSharding(mesh, P(axis_name))


def replicated(mesh: Mesh) -> NamedSharding:
    """Full replication — params, optimizer state, PRNG keys."""
    return NamedSharding(mesh, P())


def gather_replicated(mesh: Mesh) -> Callable:
    """On-device all-gather to replicated layout: ``gather(tree)`` returns the tree
    with every leaf replicated. The step before ANY host fetch of possibly-sharded
    state (TP/FSDP trainers) — ``jax.device_get`` on a sharded array fails on a
    multi-host fleet where no process addresses every shard. One owner for the
    pattern shared by the composed, LM, and distributed trainers (r5 review)."""
    return jax.jit(lambda tree: tree, out_shardings=replicated(mesh))


def cached_sharded_compile(fn: Callable, mesh: Mesh, state_shardings_fn: Callable,
                           other_in_shardings: tuple, *,
                           shape_key: bool = False) -> Callable:
    """The shared compile-with-state-dependent-shardings scaffold behind
    ``tensor_parallel.compile_{step,epoch}_tp`` and ``fsdp.compile_{step,epoch}_fsdp``
    (r5 review: previously four near-verbatim copies). jit's ``in_shardings`` must
    be stated eagerly but the state's shardings depend on its pytree (TP: leaf
    names; FSDP: leaf SHAPES — set ``shape_key``), so the jitted program is built
    from the first call's state and cached per structure(+shapes). State is donated
    and returned with the same shardings; the second output replicates."""
    compiled = {}

    def wrapper(state, *args):
        key = jax.tree_util.tree_structure(state)
        if shape_key:
            key = (key, tuple(leaf.shape
                              for leaf in jax.tree_util.tree_leaves(state)))
        if key not in compiled:
            state_sh = state_shardings_fn(state)
            compiled[key] = jax.jit(
                fn,
                in_shardings=(state_sh,) + tuple(other_in_shardings),
                out_shardings=(state_sh, replicated(mesh)),
                donate_argnums=(0,))
        return compiled[key](state, *args)

    return wrapper


def compile_step(step_fn: Callable, mesh: Mesh, *, axis_name: str = "data") -> Callable:
    """Compile ``step(state, images, labels, rng)`` over ``mesh`` with DP shardings.

    State is donated (buffers reused in-place on device — no reallocation per step).
    """
    rep, bsh = replicated(mesh), batch_sharding(mesh, axis_name)
    return jax.jit(step_fn,
                   in_shardings=(rep, bsh, bsh, rep),
                   out_shardings=(rep, rep),
                   donate_argnums=(0,))


def compile_epoch(epoch_fn: Callable, mesh: Mesh, *, axis_name: str = "data") -> Callable:
    """Compile ``epoch(state, images, labels, idx_matrix, rng)`` over ``mesh``.

    The dataset stays replicated on every device (MNIST is ~180 MB — far under HBM); the
    ``[steps, batch]`` index plan is sharded along the batch axis, so each device gathers and
    computes only its shard of every step's batch. Gradient/loss reductions become global
    all-reduces inserted by XLA.
    """
    rep = replicated(mesh)
    idx_sh = NamedSharding(mesh, P(None, axis_name))
    return jax.jit(epoch_fn,
                   in_shardings=(rep, rep, rep, idx_sh, rep),
                   out_shardings=(rep, rep),
                   donate_argnums=(0,))


def compile_eval(eval_fn: Callable, mesh: Mesh, *, axis_name: str = "data",
                 shard: bool = False) -> Callable:
    """Compile ``evaluate(params, images, labels)`` over ``mesh``.

    ``shard=False`` reproduces the reference's duplicated evaluation — every replica computes
    the full test set (reference ``src/train_dist.py:21-24,92-109``, SURVEY.md §2d.7); with
    one compiled SPMD program this costs nothing extra to express. ``shard=True`` is the
    fixed version: examples sharded, partial sums all-reduced by XLA.
    """
    rep = replicated(mesh)
    data_sh = batch_sharding(mesh, axis_name) if shard else rep
    return jax.jit(eval_fn,
                   in_shardings=(rep, data_sh, data_sh),
                   out_shardings=(rep, rep))


def device_put_dataset(mesh: Mesh, images: np.ndarray, labels: np.ndarray):
    """Place the full dataset on devices, replicated (single-host path)."""
    rep = replicated(mesh)
    return jax.device_put(images, rep), jax.device_put(labels, rep)


def put_global(mesh: Mesh, array: np.ndarray, spec: P):
    """Place a host-resident array on the mesh under ``spec``, working on both a single
    controller and a multi-host fleet. Every process must hold the (identical) full array —
    true for our datasets and index plans, which are pure functions of (seed, epoch) on every
    host (see ``parallel.sampler``); each process materializes only its addressable shards.
    """
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(array.shape, sharding, lambda i: array[i])


def global_batch_from_host_local(mesh: Mesh, local_images: np.ndarray,
                                 local_labels: np.ndarray,
                                 axis_name: str = "data"):
    """Assemble a globally-sharded batch from this process's host-local shard (multi-host
    path: each host feeds only its addressable devices, SURVEY.md §7 hard part (d)).

    ``local_*`` must be this process's contiguous slice of the global batch, in the order
    given by the sampler's global permutation.
    """
    bsh = batch_sharding(mesh, axis_name)
    gi = jax.make_array_from_process_local_data(bsh, local_images)
    gl = jax.make_array_from_process_local_data(bsh, local_labels)
    return gi, gl
