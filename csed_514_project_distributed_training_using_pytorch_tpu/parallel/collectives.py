"""Collective/p2p primitives over the mesh.

The reference exercises three distributed primitives (SURVEY.md §5 "communication backend"):
TCP-store rendezvous, DDP's bucketed ring all-reduce (``src/train_dist.py:63,83``), and
blocking point-to-point ``dist.send``/``dist.recv`` (``src/run1.py:13,16``). Rendezvous lives
in ``parallel.mesh``; the all-reduce is normally *implicit* — XLA inserts it from sharding
annotations inside the compiled train step — but explicit wrappers are provided here for the
smoke test and for ad-hoc use. All are ``shard_map``-wrapped XLA collectives: the transport
(ICI vs DCN) is the compiler's/runtime's job, never a user-visible backend string.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from csed_514_project_distributed_training_using_pytorch_tpu.parallel._compat import (
    shard_map,
)


def ring_pass(mesh: Mesh, values: jax.Array, *, axis_name: str = "data",
              shift: int = 1) -> jax.Array:
    """Rotate per-device values one step around the mesh axis ring.

    The ``lax.ppermute`` analog of the reference's rank0→rank1 ``dist.send``/``dist.recv``
    smoke test (``src/run1.py:8-17``): device ``i``'s value lands on device
    ``(i + shift) % n``. ``values`` must have leading dim == mesh axis size (one value per
    device); returns the rotated array, which callers can check against the expected
    permutation to validate cross-device/host connectivity.
    """
    n = mesh.shape[axis_name]
    perm = [(i, (i + shift) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name),
             check_vma=False)
    def _shift(x):
        return lax.ppermute(x, axis_name, perm)

    return _shift(values)


def all_reduce_sum(mesh: Mesh, values: jax.Array, *, axis_name: str = "data") -> jax.Array:
    """Explicit all-reduce-sum of per-device leading-dim shards (the gloo ring-allreduce
    analog, ≙ what DDP's Reducer does per gradient bucket at ``src/train_dist.py:83``).

    Provided for diagnostics; the train step never calls this — its all-reduce is fused in by
    XLA from sharding annotations (see ``parallel/data_parallel.py``).
    """

    @partial(shard_map, mesh=mesh, in_specs=P(axis_name), out_specs=P(None),
             check_vma=False)
    def _sum(x):
        return lax.psum(jnp.sum(x, axis=0, keepdims=True), axis_name)

    return _sum(values)[0]
